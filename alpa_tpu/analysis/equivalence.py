"""Translation validation: certify every lowered plan computes the
source jaxpr (ISSUE 15 tentpole).

The seventh ``verify_program`` analysis.  The other six prove the
lowered ``RegisterFileProgram`` is *internally* consistent (typed,
deadlock-free, leak-free, structurally sound, schedulable, precise);
none of them compares the program against the traced source jaxpr — a
lowering bug that wires the *wrong value* of the *right shape* (a stale
weight after donation, a dropped microbatch, a duplicated gradient
accumulation, a mis-paired reshard) passes every existing gate.  This
pass closes that loop by symbolic execution over an opaque term
algebra:

* **Term model.**  Values are hash-consed terms over one shared intern
  table: ``leaf(var, instance)`` for launch-placed values (parameters,
  batch shards, accumulator zero-buffers), ``app(stage_sig, out_pos,
  args...)`` for a stage application (the stage's jaxpr is opaque — its
  deterministic signature identifies it), and an n-ary ``sum{...}`` for
  gradient accumulation.  ``sum`` members are kept as a *sorted
  multiset*, which bakes the accumulation reassociation/commutation
  axiom into term identity; equality is pointer equality on interned
  ids.
* **Candidate execution.**  The lowered program runs over its register
  slots in flat emission order: each RUN applies its stage as an
  opaque term over the symbolic values currently in its operand slots
  (donated inputs consume their term — a later read of the consumed
  slot is ``equiv.stale-operand``); accumulated outputs compose as
  ``sum(acc_in, contrib(stage, mb, non-acc args))``; RESHARD / SEND /
  RECV / BROADCAST are value identities (the resharding-identity
  axiom; quantized edges are identity-within-bound, cross-referencing
  the PR 14 numerics certificate); FREE kills the slot.
* **Reference execution.**  The driver's pre-lowering instruction
  stream (``pipeshard_executable`` plumbs it down as
  ``equiv_reference``) serially composes the *same* stage
  decomposition over ``(var, microbatch)`` value keys — the source
  jaxpr's semantics under the scheduler-independent serial order.
* **Proof obligation.**  Every protected output's candidate term must
  equal its reference term, modulo the two documented rewrite axioms
  (accumulation reassociation/commutation, resharding identity) plus
  the certificate-backed quantized-within-bound identity.

Finding taxonomy (:func:`severity_of`):

* ``equiv.output-mismatch`` (error) — a protected output's term graph
  differs structurally from the reference; the finding carries a
  rendered term-diff witness naming the first divergence.
* ``equiv.stale-operand`` (error) — an op reads a slot whose value was
  consumed (donated away or freed) — the plan wires a stale buffer.
* ``equiv.dropped-microbatch`` (error) — an accumulated output is
  missing one or more microbatch contributions present in the
  reference sum.
* ``equiv.duplicated-accumulation`` (error) — an accumulated output
  contains a contribution more times than the reference (a gradient
  counted twice).
* ``equiv.unproven-output`` (warning) — the proof needs an axiom
  outside the allowed set: the quantized-within-bound identity was
  used but no valid numerics certificate backs it.
* ``equiv.budget-exhausted`` (note) — the term table hit
  ``equiv_term_budget``; the verdict is partial, never false.

Gated by ``global_config.verify_plans_equiv`` (``off | warn | error``,
default ``warn``; env ``ALPA_TPU_VERIFY_EQUIV``) — ``error`` blocks
``_launch`` with ``PlanVerificationError`` independently of
``verify_plans``.  Stats land at ``PlanVerdict.stats["equiv"]``
(JSON-able, deterministic, replayed byte-identically from the verdict
cache), render as ``equiv.txt`` in ``dump_debug_info``, export the
``alpa_plan_equiv_total{result}`` counter and the
``alpa_equiv_terms_total`` gauge, and print offline via
``scripts/verify_tool.py equiv`` (schema ``alpa-equiv/v1``).
"""
import dataclasses
import time
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    "TermTable", "EquivResult", "check_equiv", "severity_of",
    "format_equiv", "export_metrics", "render_term",
    "stage_signature", "stage_equiv_info", "build_reference",
    "reference_digest", "DEFAULT_TERM_BUDGET",
    "AXIOM_ACC", "AXIOM_RESHARD", "AXIOM_QUANT",
]

#: fallback hash-consed term budget when the caller passes none
#: (mirrors the global_env default)
DEFAULT_TERM_BUDGET = 100000

#: the documented rewrite axioms a proof may use
AXIOM_ACC = "accumulation-reassociation"
AXIOM_RESHARD = "resharding-identity"
AXIOM_QUANT = "quantized-within-bound"

#: finding code -> severity the plan verifier merges it at
_SEVERITY = {
    "equiv.output-mismatch": "error",
    "equiv.stale-operand": "error",
    "equiv.dropped-microbatch": "error",
    "equiv.duplicated-accumulation": "error",
    "equiv.unproven-output": "warning",
    "equiv.budget-exhausted": "note",
}

#: marker prefix for the poison leaf a stale read substitutes so
#: execution can continue past the finding
_STALE = "⊥stale"


def severity_of(code: str) -> str:
    """Severity class (``"error" | "warning" | "note"``) the plan
    verifier merges an equivalence finding at."""
    return _SEVERITY.get(code, "note")


class _BudgetExhausted(Exception):
    pass


class TermTable:
    """Hash-consing intern table: structurally equal terms get the same
    integer id, so term-graph equality is id equality and ``sum``
    multisets can sort by id.  One table is shared between the
    candidate and reference executions of a single proof."""

    __slots__ = ("_intern", "terms", "budget")

    def __init__(self, budget: Optional[int] = None):
        self._intern: Dict[Tuple, int] = {}
        self.terms: List[Tuple] = []
        self.budget = budget

    def __len__(self) -> int:
        return len(self.terms)

    def _make(self, struct: Tuple) -> int:
        tid = self._intern.get(struct)
        if tid is None:
            if self.budget is not None and \
                    len(self.terms) >= self.budget:
                raise _BudgetExhausted()
            tid = len(self.terms)
            self._intern[struct] = tid
            self.terms.append(struct)
        return tid

    def leaf(self, var: Any, instance: int) -> int:
        return self._make(("leaf", str(var), int(instance)))

    def app(self, sig: str, out: Any, args: Sequence[int]) -> int:
        return self._make(("app", str(sig), out, tuple(args)))

    def sum_(self, members: Sequence[int]) -> int:
        """N-ary accumulation sum: nested sums flatten and the member
        multiset sorts by interned id — reassociation and commutation
        are identities by construction (the documented axiom)."""
        flat: List[int] = []
        for m in members:
            s = self.terms[m]
            if s[0] == "sum":
                flat.extend(s[1])
            else:
                flat.append(m)
        return self._make(("sum", tuple(sorted(flat))))


def render_term(table: TermTable, tid: int, depth: int = 4,
                maxlen: int = 220) -> str:
    """Bounded-depth pretty-printer for term-diff witnesses."""
    def go(t: int, d: int) -> str:
        s = table.terms[t]
        if s[0] == "leaf":
            return s[1] + ("" if s[2] < 0 else f"@mb{s[2]}")
        if d <= 0:
            return "…"
        if s[0] == "app":
            out = s[2]
            o = (f"contrib{out[1]}.mb{out[2]}"
                 if isinstance(out, tuple) else f"out{out}")
            return (f"{s[1]}.{o}("
                    + ", ".join(go(a, d - 1) for a in s[3]) + ")")
        return "sum{" + " + ".join(go(m, d - 1) for m in s[1]) + "}"

    text = go(tid, depth)
    return text if len(text) <= maxlen else text[:maxlen - 1] + "…"


def _first_divergence(table: TermTable, want: int, got: int
                      ) -> Tuple[List[str], int, int]:
    """Descend through matching application heads to the smallest
    differing subterms; returns the path taken plus both sides."""
    path: List[str] = []
    while want != got:
        sw, sg = table.terms[want], table.terms[got]
        if (sw[0] == "app" and sg[0] == "app" and sw[1] == sg[1]
                and sw[2] == sg[2] and len(sw[3]) == len(sg[3])):
            diffs = [i for i, (a, b) in enumerate(zip(sw[3], sg[3]))
                     if a != b]
            if len(diffs) == 1:
                path.append(f"{sw[1]}.arg{diffs[0]}")
                want, got = sw[3][diffs[0]], sg[3][diffs[0]]
                continue
        break
    return path, want, got


def _witness(table: TermTable, want: int, got: int) -> str:
    path, w, g = _first_divergence(table, want, got)
    at = "/".join(path) or "root"
    return (f"at {at}: reference computes {render_term(table, w)} "
            f"but the plan computes {render_term(table, g)}")


def _is_tainted(table: TermTable, tid: int,
                memo: Dict[int, bool]) -> bool:
    """Whether a term contains a stale-read poison leaf."""
    hit = memo.get(tid)
    if hit is not None:
        return hit
    s = table.terms[tid]
    if s[0] == "leaf":
        out = s[1].startswith(_STALE)
    elif s[0] == "app":
        out = any(_is_tainted(table, a, memo) for a in s[3])
    else:
        out = any(_is_tainted(table, m, memo) for m in s[1])
    memo[tid] = out
    return out


########################################
# stage decomposition metadata (shared emitter <-> driver helpers)
########################################


def stage_signature(ex) -> str:
    """Deterministic opaque signature of a stage executable's jaxpr —
    the same helper names the stage on both the candidate (lowering
    rec) and reference (driver decomposition) sides, so a matching
    decomposition matches by construction.  Object ids embedded in var
    reprs are scrubbed before hashing."""
    sig = getattr(ex, "_equiv_stage_sig", None)
    if sig is None:
        import hashlib
        import re
        name = str(getattr(ex, "name", "") or "stage")
        try:
            text = str(ex.comp.closed_jaxpr())
        except Exception:  # pylint: disable=broad-except
            text = name
        canon = re.sub(r"\bid=\d+\b", "id=?", text)
        canon = re.sub(r"0x[0-9a-fA-F]+", "0x?", canon)
        digest = hashlib.sha256(canon.encode("utf-8")).hexdigest()[:8]
        sig = f"{name}#{digest}"
        try:
            ex._equiv_stage_sig = sig
        except Exception:  # pylint: disable=broad-except
            pass
    return sig


def stage_equiv_info(ex) -> Dict[str, Any]:
    """Per-stage equivalence metadata: opaque signature, donated invar
    positions, and the accumulation map ``{out_pos: acc_in_pos}``
    (string keys so the dict survives a JSON round-trip) derived from
    the driver's ``comp._acc_out_map``.  Cached on the executable —
    every RUN of the same stage shares one dict."""
    info = getattr(ex, "_equiv_stage_info", None)
    if info is not None:
        return info
    invars = list(getattr(ex, "invars", ()) or ())
    outvars = list(getattr(ex, "outvars", ()) or ())
    acc_out = getattr(getattr(ex, "comp", None),
                      "_acc_out_map", None) or {}
    acc: Dict[str, int] = {}
    for pos, ov in enumerate(outvars):
        iv = acc_out.get(ov)
        if iv is not None and iv in invars:
            acc[str(pos)] = invars.index(iv)
    info = {
        "stage": stage_signature(ex),
        "donate": sorted(int(i) for i in
                         (getattr(ex, "donate_idx", ()) or ())),
        "acc": acc,
    }
    try:
        ex._equiv_stage_info = info
    except Exception:  # pylint: disable=broad-except
        pass
    return info


def build_reference(instructions: Sequence[Any],
                    num_microbatches: int = 0) -> Dict[str, Any]:
    """The reference decomposition: the driver's pre-lowering RUN
    stream as serial stage applications over ``(var, instance)`` value
    keys (format ``alpa-equiv-reference/v1``, JSON-able).  Built by
    ``pipeshard_executable._ensure_lowered`` and plumbed into
    ``lower_to_register_file`` — deliberately *not* derived from the
    register lowering under verification."""
    apps: List[Dict[str, Any]] = []
    for inst in instructions:
        if getattr(getattr(inst, "opcode", None), "name", "") != "RUN":
            continue
        ex = inst.executable
        info = stage_equiv_info(ex)
        mb = getattr(inst, "micro_batch", None)
        apps.append({
            "stage": info["stage"],
            "mb": int(mb) if mb is not None else -1,
            "donate": list(info["donate"]),
            "acc": dict(info["acc"]),
            "in": [[str(v), int(i)]
                   for v, i in (inst.input_keys or ())],
            "out": [[str(v), int(i)]
                    for v, i in (inst.output_keys or ())],
        })
    return {"format": "alpa-equiv-reference/v1", "apps": apps,
            "num_microbatches": int(num_microbatches)}


def reference_digest(reference: Optional[Dict[str, Any]]) -> str:
    """Short deterministic digest of a reference decomposition — part
    of the verdict cache key (a changed reference must re-derive the
    proof).  Var-repr object ids are scrubbed so warm restarts of the
    same program hash identically."""
    if not reference:
        return "none"
    import hashlib
    import json
    import re
    text = json.dumps(reference, sort_keys=True, default=str)
    text = re.sub(r"\bid=\d+\b", "id=?", text)
    text = re.sub(r"0x[0-9a-fA-F]+", "0x?", text)
    return hashlib.sha256(text.encode("utf-8")).hexdigest()[:16]


########################################
# the analysis
########################################


@dataclasses.dataclass
class EquivResult:
    """Findings + stats of one :func:`check_equiv` run.  ``stats`` is
    JSON-able and stored verbatim at ``PlanVerdict.stats["equiv"]`` so
    cached verdicts replay the identical report."""
    findings: List[Any] = dataclasses.field(default_factory=list)
    stats: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not any(severity_of(f.code) == "error"
                       for f in self.findings)

    def format(self) -> str:
        return format_equiv(self.stats, self.findings)


def _exec_reference(reference: Dict[str, Any], table: TermTable
                    ) -> Dict[Tuple[str, int], int]:
    """Serially compose the stage decomposition over value keys —
    the source jaxpr's semantics, scheduler-independent."""
    env: Dict[Tuple[str, int], int] = {}
    for app in reference.get("apps", ()):
        sig = app.get("stage") or "stage"
        mb = int(app.get("mb", -1))
        acc = {int(k): int(v)
               for k, v in (app.get("acc") or {}).items()}
        keys = [(str(k[0]), int(k[1])) for k in app.get("in", ())]
        args = [env[k] if k in env else table.leaf(*k) for k in keys]
        acc_in = set(acc.values())
        contrib_args = tuple(t for i, t in enumerate(args)
                             if i not in acc_in)
        for pos, k in enumerate(app.get("out", ())):
            key = (str(k[0]), int(k[1]))
            if pos in acc:
                contrib = table.app(sig, ("contrib", pos, mb),
                                    contrib_args)
                env[key] = table.sum_((args[acc[pos]], contrib))
            else:
                env[key] = table.app(sig, pos, tuple(args))
    return env


def check_equiv(model, hooks: Optional[Sequence[Any]] = None,
                budget: Optional[int] = None,
                numerics_ok: Optional[bool] = None,
                reference: Optional[Dict[str, Any]] = None
                ) -> EquivResult:
    """Run the translation validation over a
    :class:`~alpa_tpu.analysis.plan_verifier.PlanModel` carrying a
    reference decomposition (``model.reference`` or the ``reference``
    override).  ``numerics_ok`` is the PR 14 certificate status: True
    when the numerics analysis ran without error findings (backs the
    quantized-within-bound axiom), False/None otherwise.  Pure function
    of its inputs — no globals, no cache, no metrics."""
    from alpa_tpu.analysis.plan_verifier import Finding
    del hooks  # footprint checks are the structure pass's job
    t0 = time.perf_counter()
    if budget is None:
        budget = DEFAULT_TERM_BUDGET
    budget = int(budget)
    reference = reference if reference is not None else \
        getattr(model, "reference", None)
    findings: List[Finding] = []

    def done(stats_extra: Dict[str, Any]) -> EquivResult:
        stats = {
            "n_terms": len(table),
            "n_outputs": 0,
            "n_proved": 0,
            "n_apps": len((reference or {}).get("apps", ())),
            "num_microbatches":
                int((reference or {}).get("num_microbatches", 0)),
            "axioms_used": [],
            "per_output": [],
            "budget": budget,
            "partial": False,
        }
        stats.update(stats_extra)
        stats["seconds"] = round(time.perf_counter() - t0, 6)
        return EquivResult(findings=findings, stats=stats)

    table = TermTable(budget=budget)
    if not reference:
        # no decomposition available (legacy fixture / hand-built
        # model): nothing to prove against — empty, ok result
        return done({})

    def _var(s: int) -> str:
        sm = model.slots.get(s)
        return sm.var if sm is not None else f"slot{s}"

    try:
        ref_env = _exec_reference(reference, table)

        # ---- candidate: the lowered program over register slots ----
        env: Dict[int, int] = {}
        ax: Dict[int, frozenset] = {}
        consumed: Dict[int, int] = {}       # slot -> consuming op idx
        for s, sm in sorted(model.slots.items()):
            if sm.preplaced:
                env[s] = table.leaf(sm.var, sm.instance)
                ax[s] = frozenset()

        def read(op, s: int, pos: int) -> Tuple[int, frozenset]:
            if s in consumed and s not in env:
                findings.append(Finding(
                    "equiv", "equiv.stale-operand",
                    f"{op.label}: operand {pos} reads slot {s} "
                    f"({_var(s)}) whose value was consumed at op "
                    f"{consumed[s]} (donation / free) — the plan "
                    f"wires a stale buffer", op.idx))
                return (table.leaf(f"{_STALE}[slot{s}@op{op.idx}]",
                                   -1), frozenset())
            t = env.get(s)
            if t is None:
                # undefined read: liveness reports it; model the value
                # as the slot's launch key so execution continues
                sm = model.slots.get(s)
                t = table.leaf(sm.var if sm else f"slot{s}",
                               sm.instance if sm else -1)
                return t, frozenset()
            return t, ax.get(s, frozenset())

        for op in model.ops:
            if op.kind == "RUN":
                eq = getattr(op, "equiv", None) or {}
                sig = eq.get("stage") or op.label or f"run{op.idx}"
                mb = int(eq.get("mb", -1))
                acc = {int(k): int(v)
                       for k, v in (eq.get("acc") or {}).items()}
                args: List[int] = []
                arg_ax: List[frozenset] = []
                for pos, s in enumerate(op.reads):
                    t, a = read(op, s, pos)
                    args.append(t)
                    arg_ax.append(a)
                joined = frozenset().union(*arg_ax) if arg_ax \
                    else frozenset()
                # Quantized gradient sync (ISSUE 19): a stage whose
                # gradient accumulation runs through the stochastic-
                # rounding codec computes the source jaxpr only up to
                # the certified bound — the proof needs the QUANT
                # axiom, admissible (like quantized RESHARDs) only
                # under a clean numerics certificate.
                if getattr(op, "grad_quant", None):
                    joined = joined | frozenset({AXIOM_QUANT})
                acc_in = set(acc.values())
                contrib_args = tuple(t for i, t in enumerate(args)
                                     if i not in acc_in)
                outs: List[Tuple[int, int, frozenset]] = []
                for pos, s in enumerate(op.writes):
                    if pos in acc and acc[pos] < len(args):
                        contrib = table.app(
                            sig, ("contrib", pos, mb), contrib_args)
                        t = table.sum_((args[acc[pos]], contrib))
                        outs.append((s, t, joined | {AXIOM_ACC}))
                    else:
                        outs.append((s, table.app(sig, pos,
                                                  tuple(args)),
                                     joined))
                for s in op.kills:
                    consumed[s] = op.idx
                    env.pop(s, None)
                for s, t, a in outs:
                    env[s] = t
                    ax[s] = a
            elif op.kind in ("RESHARD", "SEND", "RECV", "BROADCAST"):
                src = op.reads[0] if op.reads else None
                dst = op.writes[0] if op.writes else None
                if src is None or dst is None:
                    continue
                t, a = read(op, src, 0)
                hop = {AXIOM_RESHARD}
                if getattr(op, "codec", None) or \
                        getattr(op, "strategy", None) == "quantized":
                    hop.add(AXIOM_QUANT)
                env[dst] = t
                ax[dst] = a | hop
            elif op.kind == "FREE":
                for s in op.kills:
                    consumed[s] = op.idx
                    env.pop(s, None)

        # ---- proof obligations: every protected output ----
        taint_memo: Dict[int, bool] = {}
        per_output: List[Dict[str, Any]] = []
        n_proved = 0
        axioms_used: Set[str] = set()
        for s in sorted(model.slots):
            sm = model.slots[s]
            if not sm.protected:
                continue
            name = sm.var + ("" if sm.instance < 0
                             else f"@mb{sm.instance}")
            key = (sm.var, sm.instance)
            ref_t = ref_env.get(key)
            if ref_t is None:
                # output never produced by a stage: a launch-placed
                # pass-through — the reference value is its own leaf
                ref_t = table.leaf(*key)
            cand_t = env.get(s)
            used = sorted(ax.get(s, frozenset()))
            row: Dict[str, Any] = {
                "var": sm.var, "instance": sm.instance,
                "mesh": sm.mesh, "slot": s, "axioms": used,
            }
            if cand_t is None:
                row["status"] = "mismatched"
                w = (f"the plan never produces {name} (slot {s}"
                     + (f"; consumed at op {consumed[s]}"
                        if s in consumed else "") + "); reference "
                     f"computes {render_term(table, ref_t)}")
                row["witness"] = w
                findings.append(Finding(
                    "equiv", "equiv.output-mismatch",
                    f"protected output {name}: {w}"))
            elif _is_tainted(table, cand_t, taint_memo):
                # the stale read already carries the named finding;
                # record the output as stale rather than double-report
                row["status"] = "stale"
            elif cand_t == ref_t:
                axioms_used.update(used)
                if AXIOM_QUANT in used and numerics_ok is not True:
                    row["status"] = "unproven"
                    findings.append(Finding(
                        "equiv", "equiv.unproven-output",
                        f"protected output {name}: proof needs the "
                        f"{AXIOM_QUANT} axiom but no valid numerics "
                        f"certificate backs it "
                        f"(verify_plans_numerics off or failing) — "
                        f"outside the allowed axiom set"))
                else:
                    row["status"] = "proved"
                    n_proved += 1
            else:
                sr, sc = table.terms[ref_t], table.terms[cand_t]
                code = "equiv.output-mismatch"
                if sr[0] == "sum" and sc[0] == "sum":
                    want, got = Counter(sr[1]), Counter(sc[1])
                    missing = want - got
                    extra = got - want
                    if missing and not extra:
                        code = "equiv.dropped-microbatch"
                        w = ("missing accumulation member(s): "
                             + " + ".join(
                                 render_term(table, m)
                                 for m in sorted(missing.elements())))
                    elif extra and not missing:
                        code = "equiv.duplicated-accumulation"
                        w = ("surplus accumulation member(s): "
                             + " + ".join(
                                 render_term(table, m)
                                 for m in sorted(extra.elements())))
                    else:
                        w = _witness(table, ref_t, cand_t)
                else:
                    w = _witness(table, ref_t, cand_t)
                row["status"] = "mismatched"
                row["witness"] = w
                findings.append(Finding(
                    "equiv", code,
                    f"protected output {name}: {w}"))
            per_output.append(row)
    except _BudgetExhausted:
        findings.append(Finding(
            "equiv", "equiv.budget-exhausted",
            f"term table hit equiv_term_budget={budget} — proof "
            f"abandoned (partial verdict, never a false one); raise "
            f"ALPA_TPU_EQUIV_TERM_BUDGET to certify this plan"))
        return done({"partial": True})

    return done({
        "n_outputs": len(per_output),
        "n_proved": n_proved,
        "axioms_used": sorted(axioms_used),
        "per_output": per_output,
    })


def format_equiv(stats: Dict[str, Any],
                 findings: Optional[Sequence[Any]] = None) -> str:
    """Human-readable translation-validation report (``equiv.txt``,
    ``verify_tool.py equiv``).  Works from the JSON-able stats dict
    alone so cached verdicts render identically."""
    lines = [
        "translation validation: "
        + (f"{stats.get('n_proved', 0)}/{stats.get('n_outputs', 0)} "
           f"protected output(s) proved equivalent to the source "
           f"jaxpr"
           if not stats.get("partial")
           else "PARTIAL — term budget exhausted"),
        f"terms={stats.get('n_terms', 0)}  "
        f"apps={stats.get('n_apps', 0)}  "
        f"microbatches={stats.get('num_microbatches', 0)}  "
        f"axioms={','.join(stats.get('axioms_used', ())) or '-'}  "
        f"budget={stats.get('budget', 0)}  "
        f"seconds={stats.get('seconds', 0.0)}",
    ]
    table = stats.get("per_output", ())
    if table:
        lines.append("per-output proofs:")
        lines.append(f"  {'output':<22} {'status':<11} axioms")
        for row in table:
            name = str(row.get("var", "?")) + (
                "" if row.get("instance", -1) < 0
                else f"@mb{row['instance']}")
            axioms = ", ".join(row.get("axioms", ())) or "-"
            lines.append(f"  {name:<22} "
                         f"{row.get('status', '?'):<11} {axioms}")
            if row.get("witness"):
                lines.append(f"    witness: {row['witness']}")
    if findings:
        lines.append("findings:")
        for f in findings:
            at = f" (op {f.op})" if f.op >= 0 else ""
            lines.append(
                f"  [{severity_of(f.code)}] [{f.code}]{at} "
                f"{f.message}")
    return "\n".join(lines)


def export_metrics(stats: Optional[Dict[str, Any]],
                   result: str) -> None:
    """Record one translation-validation outcome in the central
    registry (``alpa_plan_equiv_total{result}`` /
    ``alpa_equiv_terms_total``).  The terms gauge is *set* from the
    deterministic stats, so warm-restart cache replays export exactly
    the cold compile's value."""
    _EQUIV_TOTAL.labels(result).inc()
    if stats:
        _TERMS_TOTAL.set(float(stats.get("n_terms", 0)))


from alpa_tpu.telemetry import metrics as _tmetrics  # noqa: E402

_REG = _tmetrics.get_registry()
_EQUIV_TOTAL = _REG.counter(
    "alpa_plan_equiv_total",
    "Translation-validation outcomes by result "
    "(ok / warning / error / skipped)",
    labelnames=("result",))
_TERMS_TOTAL = _REG.gauge(
    "alpa_equiv_terms_total",
    "Hash-consed symbolic terms interned while certifying the last "
    "verified plan against its source jaxpr")
