"""Explicit-state model checker over lowered register-file plans
(ISSUE 13 tentpole).

The four ISSUE-8 analyses each examine ONE order: the flat emission
order (typing/liveness) or one happens-before relation (Kahn).  A
multi-host deployment executes the per-mesh streams concurrently over
finite-capacity DCN send/recv channels, where correctness must hold
under EVERY scheduler interleaving — exactly what a single-order pass
cannot certify (ROADMAP item 1: "verify the real SEND/RECV streams,
not just the emulated ones").  This module explores that space
directly:

* **State model** — every cross-mesh RESHARD is split into an explicit
  SEND micro-op (on the source-mesh stream, where the payload is
  consumed) and a RECV micro-op (at the RESHARD's position on the
  destination-mesh stream), joined by a per-``(src, dst)``-mesh FIFO
  channel.  A state is the per-stream program counters plus the
  channel queue contents plus a digest of the slot liveness map; the
  checker runs a DFS with a visited set over that space.
* **Channel semantics matrix** — each plan is checked twice: under
  *rendezvous* semantics (capacity-1 channels: a SEND blocks while its
  channel holds an unconsumed payload) and under *buffered*
  capacity-k semantics (k = the declared overlap window, at least 2).
  A plan that deadlocks under the buffered model is broken everywhere
  (``model.deadlock``, error); a plan that only deadlocks under
  rendezvous needs channel buffering the runtime may not guarantee on
  every backend (``model.rendezvous-deadlock``, warning).
* **Hazard freedom in all interleavings** — the PR 6
  ``SlotHazardChecker`` invariants (use-after-free, use-undefined,
  double-free, free/write of an in-flight transfer endpoint) are
  re-checked on every explored schedule, not just the flat replay
  order (``model.hazard-*``, errors).
* **Partial-order reduction** — a micro-op whose slot footprint is
  touched by no other stream, that uses no channel, and that no other
  op waits on commutes with every concurrent transition; when one is
  enabled the checker commits it deterministically instead of
  branching (a singleton ample set; the state graph is acyclic, so the
  classic ignoring problem cannot arise).  The achieved reduction is
  reported as ``reduction_ratio``.
* **Window bound as a property** — the overlap scheduler *promises*
  at most ``overlap_inflight_window`` launched-but-unwaited transfers;
  the checker verifies the promise by walking the compiled hook
  sequence (``model.inflight-exceeds-window``, error) instead of
  trusting ``schedule_overlap``.
* **Fault/retry safety** — for every ``fault.KNOWN_SITES`` site
  reachable from the plan, symbolically replay inject-fail-then-retry:
  a retry double-applies a donated-buffer RUN
  (``retry.unsafe-donation``), resends every member of a partially
  delivered ``DirectTransferGroup`` (``retry.partial-group``), or
  re-enqueues behind a younger in-flight transfer on the same FIFO
  channel (``retry.fifo-reorder``).  Each site is classified
  safe / unsafe / unreachable in the verdict stats;
  ``fault.call_with_retry`` consults the classification and refuses
  statically-unsafe retries under ``verify_plans=error``.

Everything here is a pure function of the :class:`PlanModel` + hooks
(:func:`check_model`); :func:`plan_verifier.verify_program` wires it in
as the fifth analysis behind ``global_config.verify_plans_model_check``
and exports the ``alpa_model_check_*`` metrics.  A state budget
(``global_config.model_check_state_budget``) bounds exploration so
committed fixture plans finish in well under a second; exhaustion is
reported as coverage (``model.budget-exhausted`` note, ``partial``
stat), never silence.
"""
import dataclasses
import time
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

from alpa_tpu.telemetry import metrics as _tmetrics
from alpa_tpu.analysis.plan_verifier import (Finding, OpModel, PlanModel,
                                             SlotModel)

__all__ = [
    "DEFAULT_STATE_BUDGET", "FIXTURE_MAX_OPS", "MicroOp",
    "ModelCheckResult", "check_model", "classify_retry_sites",
    "severity_of", "format_stats", "model_to_dict", "model_from_dict",
    "load_fixture", "export_metrics",
]

#: default DFS state budget (overridable via
#: ``global_config.model_check_state_budget`` / the check_model arg)
DEFAULT_STATE_BUDGET = 50000

#: "fixture" knob mode model-checks only plans at most this many ops
FIXTURE_MAX_OPS = 256

_REG = _tmetrics.get_registry()
_STATES_TOTAL = _REG.counter(
    "alpa_model_check_states_total",
    "States explored by the plan model checker, summed over runs")
_MC_TOTAL = _REG.counter(
    "alpa_plan_model_check_total",
    "Plan model-check outcomes by result",
    labelnames=("result",))

_UNDEF, _LIVE, _DEAD = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class MicroOp:
    """One transition of the interleaving model.  Cross-mesh RESHARDs
    contribute a ``send``/``recv`` pair; every other instruction is a
    single ``exec``."""
    uid: int
    op: int                                 # flat instruction index
    kind: str                               # "exec" | "send" | "recv"
    stream: int
    reads: Tuple[int, ...] = ()
    writes: Tuple[int, ...] = ()
    kills: Tuple[int, ...] = ()
    channel: Optional[Tuple[int, int]] = None
    deps: FrozenSet[int] = frozenset()      # uids that must run first
    label: str = ""


@dataclasses.dataclass
class ModelCheckResult:
    """Findings + stats of one :func:`check_model` run.  ``stats`` is
    JSON-able and stored verbatim at ``PlanVerdict.stats["model_check"]``
    so cached verdicts replay the identical report."""
    findings: List[Finding] = dataclasses.field(default_factory=list)
    stats: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not any(severity_of(f.code) == "error"
                       for f in self.findings)

    def format(self) -> str:
        return format_stats(self.stats, self.findings)


#: finding code -> severity the verifier merges it at.  Hazards and
#: buffered-model deadlocks are hard errors; a rendezvous-only deadlock
#: is a warning (the plan is correct whenever the backend buffers at
#: least one payload per channel, which the in-process CPU backend and
#: buffered DCN transports do); retry-safety classifications and budget
#: exhaustion are notes — they describe the plan, they don't fail it.
_SEVERITY = {
    "model.deadlock": "error",
    "model.channel-endpoint": "error",
    "model.inflight-exceeds-window": "error",
    "model.rendezvous-deadlock": "warning",
    "model.budget-exhausted": "note",
    "retry.unsafe-donation": "note",
    "retry.partial-group": "note",
    "retry.fifo-reorder": "note",
}


def severity_of(code: str) -> str:
    """Severity class (``"error" | "warning" | "note"``) the plan
    verifier merges a model-check finding at."""
    if code in _SEVERITY:
        return _SEVERITY[code]
    if code.startswith("model.hazard-"):
        return "error"
    return "note"


########################################
# micro-op construction
########################################


def _stream_of_ops(model: PlanModel) -> Dict[int, int]:
    out: Dict[int, int] = {}
    for m, stream in enumerate(model.streams):
        for i in stream:
            out[i] = m
    return out


def _is_split(op: OpModel) -> bool:
    return (op.kind == "RESHARD" and op.cross and op.edge is not None
            and op.edge[0] != op.edge[1])


def build_micro_ops(model: PlanModel) -> List[List[MicroOp]]:
    """The per-stream micro-op lists: every cross-mesh RESHARD becomes
    a SEND on the source-mesh stream (ordered by its global instruction
    index among that stream's ops — where the payload leaves the
    sender) and a RECV at the RESHARD's own position on the
    destination-mesh stream; everything else is one EXEC in place.

    Cross-stream dependency edges from ``partition_streams`` are
    re-attached to the half they guard: a dependency that orders the
    *source* slot's producer/consumer binds the SEND; everything else
    binds the RECV (transfer completion)."""
    stream_of = _stream_of_ops(model)
    n_streams = max(model.num_meshes, 1)
    # per-stream member (op idx, kind) lists: given stream order is
    # preserved verbatim (it IS the property under test — a mutated
    # receive order must stay mutated); SENDs are interleaved into the
    # source-mesh stream at their global-emission position (before the
    # first member with a larger instruction index)
    per_stream: List[List[Tuple[int, str]]] = [
        [] for _ in range(n_streams)]
    split: Dict[int, bool] = {}
    for m, stream in enumerate(model.streams[:n_streams]):
        for i in stream:
            if i >= len(model.ops):
                continue
            op = model.ops[i]
            split[i] = _is_split(op)
            per_stream[m].append((i, "recv" if split[i] else "exec"))
    for op in model.ops:
        if op.idx in split:
            continue  # unreachable from any stream (defensive)
        split[op.idx] = False
    for op in model.ops:
        if not split.get(op.idx):
            continue
        src = op.edge[0] if 0 <= op.edge[0] < n_streams else 0
        members = per_stream[src]
        pos = next((p for p, (j, _k) in enumerate(members)
                    if j > op.idx), len(members))
        members.insert(pos, (op.idx, "send"))

    uid_of: Dict[Tuple[int, str], int] = {}
    placed: List[Tuple[int, int, str, int]] = []  # stream, op, kind, uid
    uid = 0
    for s in range(n_streams):
        for i, kind in per_stream[s]:
            uid_of[(i, kind)] = uid
            placed.append((s, i, kind, uid))
            uid += 1

    def _completion_uid(j: int,
                        waiter_foot: FrozenSet[int]) -> Optional[int]:
        if not split.get(j):
            return uid_of.get((j, "exec"))
        # a waiter that conflicts on j's source slot is ordered against
        # j's SEND (where the source is consumed); otherwise it waits
        # for the transfer to complete (RECV)
        j_src = model.ops[j].reads[0] if model.ops[j].reads else None
        if j_src is not None and j_src in waiter_foot:
            return uid_of.get((j, "send"))
        return uid_of.get((j, "recv"))

    deps_of: Dict[int, set] = {}
    for i, waits in model.deps.items():
        if i >= len(model.ops):
            continue
        op = model.ops[i]
        foot = frozenset(op.reads) | frozenset(op.writes) | \
            frozenset(op.kills)
        if split.get(i):
            src_slot = op.reads[0] if op.reads else None
            send_u, recv_u = uid_of[(i, "send")], uid_of[(i, "recv")]
            for j in waits:
                if j >= len(model.ops) or j == i:
                    continue
                j_op = model.ops[j]
                touches_src = src_slot is not None and (
                    src_slot in j_op.writes or src_slot in j_op.kills
                    or src_slot in j_op.reads)
                target = _completion_uid(j, foot)
                if target is None:
                    continue
                if touches_src:
                    deps_of.setdefault(send_u, set()).add(target)
                else:
                    deps_of.setdefault(recv_u, set()).add(target)
        else:
            u = uid_of.get((i, "exec"))
            if u is None:
                continue
            for j in waits:
                if j >= len(model.ops) or j == i:
                    continue
                target = _completion_uid(j, foot)
                if target is not None:
                    deps_of.setdefault(u, set()).add(target)

    streams_micro: List[List[MicroOp]] = [[] for _ in range(n_streams)]
    for s, i, kind, u in placed:
        op = model.ops[i]
        if kind == "send":
            reads = tuple(op.reads[:1])
            writes: Tuple[int, ...] = ()
            label = f"SEND {op.label} ch{op.edge[0]}->{op.edge[1]}"
        elif kind == "recv":
            reads = ()
            writes = tuple(op.writes[:1])
            label = f"RECV {op.label} ch{op.edge[0]}->{op.edge[1]}"
        else:
            reads, writes = tuple(op.reads), tuple(op.writes)
            label = op.label or op.kind
        streams_micro[s].append(MicroOp(
            uid=u, op=i, kind=kind, stream=s,
            reads=reads, writes=writes,
            kills=tuple(op.kills) if kind == "exec" else (),
            channel=tuple(op.edge) if kind in ("send", "recv") else None,
            deps=frozenset(deps_of.get(u, ())),
            label=label))
    return streams_micro


########################################
# the explorer
########################################


@dataclasses.dataclass
class _RunResult:
    capacity: int
    states: int = 0
    transitions: int = 0
    por_commits: int = 0
    partial: bool = False
    n_deadlock_states: int = 0
    deadlock_trace: Optional[List[str]] = None
    # (code, op idx) -> (message, trace)
    hazards: Dict[Tuple[str, int], Tuple[str, List[str]]] = \
        dataclasses.field(default_factory=dict)
    seconds: float = 0.0


def _explore(model: PlanModel, streams_micro: List[List[MicroOp]],
             capacity: int, budget: int) -> _RunResult:
    t0 = time.perf_counter()
    res = _RunResult(capacity=capacity)
    n_streams = len(streams_micro)
    pos: Dict[int, Tuple[int, int]] = {}
    by_uid: Dict[int, MicroOp] = {}
    for s, st in enumerate(streams_micro):
        for p, u in enumerate(st):
            pos[u.uid] = (s, p)
            by_uid[u.uid] = u
    channels = sorted({u.channel for st in streams_micro for u in st
                       if u.channel is not None})
    # POR precomputation: slots touched by >1 stream, uids waited on
    slot_streams: Dict[int, set] = {}
    dep_targets: set = set()
    for st in streams_micro:
        for u in st:
            for s in (*u.reads, *u.writes, *u.kills):
                slot_streams.setdefault(s, set()).add(u.stream)
            dep_targets.update(u.deps)

    def _local(u: MicroOp) -> bool:
        if u.channel is not None or u.uid in dep_targets:
            return False
        return all(slot_streams.get(s, set()) <= {u.stream}
                   for s in (*u.reads, *u.writes, *u.kills))

    pcs = [0] * n_streams
    queues: Dict[Tuple[int, int], List[int]] = {c: [] for c in channels}
    slot_state: Dict[int, int] = {}
    # destination slots of queued payloads (a SEND copies the source
    # into the channel, so the source is NOT held in flight — freeing
    # it after the send is the normal plan shape; the destination is
    # owned by the channel until its RECV lands)
    inflight_dst: Dict[int, int] = {}
    state_hash = 0
    for s, sm in model.slots.items():
        if sm.preplaced:
            slot_state[s] = _LIVE
            state_hash ^= hash((s, _LIVE))
    op_dst: Dict[int, Optional[int]] = {}
    for op in model.ops:
        op_dst[op.idx] = op.writes[0] if op.writes else None

    def _executed(uid: int) -> bool:
        s, p = pos[uid]
        return pcs[s] > p

    def _enabled(u: MicroOp) -> bool:
        if any(not _executed(d) for d in u.deps):
            return False
        if u.kind == "send":
            return len(queues[u.channel]) < capacity
        if u.kind == "recv":
            q = queues[u.channel]
            return bool(q) and q[0] == u.op
        return True

    def _enabled_list() -> List[MicroOp]:
        out = []
        for s in range(n_streams):
            p = pcs[s]
            if p < len(streams_micro[s]):
                u = streams_micro[s][p]
                if _enabled(u):
                    out.append(u)
        return out

    def _var(slot: int) -> str:
        sm = model.slots.get(slot)
        return sm.var if sm is not None else f"slot{slot}"

    path: List[MicroOp] = []

    def _trace(extra: Optional[List[str]] = None) -> List[str]:
        lines = [f"{i:3d}. m{u.stream}: {u.label}  (op {u.op})"
                 for i, u in enumerate(path)]
        return lines + (extra or [])

    def _blocked_lines() -> List[str]:
        lines = ["-- blocked --"]
        for s in range(n_streams):
            p = pcs[s]
            if p >= len(streams_micro[s]):
                lines.append(f"  m{s}: done")
                continue
            u = streams_micro[s][p]
            why = []
            unmet = [d for d in u.deps if not _executed(d)]
            if unmet:
                why.append("waits for "
                           + ", ".join(by_uid[d].label for d in unmet))
            if u.kind == "send" and len(queues[u.channel]) >= capacity:
                why.append(
                    f"channel {u.channel[0]}->{u.channel[1]} full "
                    f"(capacity {capacity}, holds op(s) "
                    f"{queues[u.channel]})")
            if u.kind == "recv":
                q = queues[u.channel]
                if not q:
                    why.append(f"channel {u.channel[0]}->{u.channel[1]}"
                               " empty")
                elif q[0] != u.op:
                    why.append(
                        f"channel {u.channel[0]}->{u.channel[1]} FIFO "
                        f"head is op {q[0]}, needs op {u.op}")
            lines.append(f"  m{s}: {u.label} — "
                         + ("; ".join(why) or "not enabled"))
        return lines

    def _set_slot(slot: int, new: int, changes: list):
        old = slot_state.get(slot, _UNDEF)
        nonlocal state_hash
        if old != _UNDEF:
            state_hash ^= hash((slot, old))
        if new != _UNDEF:
            state_hash ^= hash((slot, new))
        slot_state[slot] = new
        changes.append((slot, old))

    def _hazard(u: MicroOp) -> Optional[Tuple[str, str]]:
        for s in u.reads:
            st = slot_state.get(s, _UNDEF)
            if st == _DEAD:
                return ("model.hazard-use-after-free",
                        f"{u.label}: reads slot {s} ({_var(s)}) after "
                        f"it was freed in this schedule")
            if st == _UNDEF:
                return ("model.hazard-use-undefined",
                        f"{u.label}: reads slot {s} ({_var(s)}) before "
                        f"any producer ran in this schedule")
        if u.kind == "exec":
            for s in u.writes:
                if inflight_dst.get(s):
                    return ("model.hazard-write-in-flight",
                            f"{u.label}: writes slot {s} ({_var(s)}), "
                            f"the destination of an in-flight transfer "
                            f"in this schedule")
            for s in u.kills:
                st = slot_state.get(s, _UNDEF)
                if st == _DEAD:
                    return ("model.hazard-double-free",
                            f"{u.label}: frees slot {s} ({_var(s)}) "
                            f"twice in this schedule")
                if inflight_dst.get(s):
                    return ("model.hazard-free-in-flight",
                            f"{u.label}: frees/donates slot {s} "
                            f"({_var(s)}), the destination of an "
                            f"in-flight transfer in this schedule")
        return None

    def _apply(u: MicroOp):
        changes: list = []
        pcs[u.stream] += 1
        if u.kind == "send":
            queues[u.channel].append(u.op)
            dst = op_dst[u.op]
            if dst is not None:
                inflight_dst[dst] = inflight_dst.get(dst, 0) + 1
        elif u.kind == "recv":
            queues[u.channel].pop(0)
            dst = op_dst[u.op]
            if dst is not None:
                inflight_dst[dst] -= 1
            for s in u.writes:
                _set_slot(s, _LIVE, changes)
        else:
            for s in u.kills:
                _set_slot(s, _DEAD, changes)
            for s in u.writes:
                _set_slot(s, _LIVE, changes)
        return changes

    def _undo(u: MicroOp, changes: list):
        nonlocal state_hash
        pcs[u.stream] -= 1
        if u.kind == "send":
            queues[u.channel].pop()
            dst = op_dst[u.op]
            if dst is not None:
                inflight_dst[dst] -= 1
        elif u.kind == "recv":
            queues[u.channel].insert(0, u.op)
            dst = op_dst[u.op]
            if dst is not None:
                inflight_dst[dst] += 1
        for slot, old in reversed(changes):
            new = slot_state[slot]
            if new != _UNDEF:
                state_hash ^= hash((slot, new))
            if old != _UNDEF:
                state_hash ^= hash((slot, old))
            slot_state[slot] = old

    def _key():
        return (tuple(pcs),
                tuple(tuple(queues[c]) for c in channels),
                state_hash)

    def _select(en: List[MicroOp]) -> List[MicroOp]:
        for u in en:
            if _local(u):
                res.por_commits += 1
                return [u]
        return en

    visited = {_key()}
    res.states = 1
    frames: List[List[Any]] = [[_select(_enabled_list()), 0]]
    undo_stack: List[Tuple[MicroOp, list]] = []
    while frames:
        choices, i = frames[-1]
        if i >= len(choices):
            frames.pop()
            if undo_stack:
                u, changes = undo_stack.pop()
                _undo(u, changes)
                path.pop()
            continue
        frames[-1][1] += 1
        u = choices[i]
        haz = _hazard(u)
        if haz is not None:
            code, msg = haz
            key = (code, u.op)
            if key not in res.hazards:
                res.hazards[key] = (
                    msg, _trace([f"  -> {msg}"]))
            res.transitions += 1
            continue
        changes = _apply(u)
        res.transitions += 1
        path.append(u)
        k = _key()
        if k in visited:
            _undo(u, changes)
            path.pop()
            continue
        visited.add(k)
        res.states += 1
        if res.states >= budget:
            res.partial = True
            _undo(u, changes)
            path.pop()
            break
        en = _enabled_list()
        if not en:
            if any(pcs[s] < len(streams_micro[s])
                   for s in range(n_streams)):
                res.n_deadlock_states += 1
                if res.deadlock_trace is None:
                    res.deadlock_trace = _trace(_blocked_lines())
            _undo(u, changes)
            path.pop()
            continue
        frames.append([_select(en), 0])
        undo_stack.append((u, changes))
    res.seconds = time.perf_counter() - t0
    return res


########################################
# property families outside the interleaving model
########################################


def check_channel_endpoints(model: PlanModel) -> List[Finding]:
    """Structural channel check: a cross-mesh RESHARD's source slot
    must live on ``edge[0]`` and its destination slot on ``edge[1]`` —
    a corrupted edge binds the SEND/RECV pair to the wrong FIFO."""
    out: List[Finding] = []
    for op in model.ops:
        if not _is_split(op):
            continue
        src = model.slots.get(op.reads[0]) if op.reads else None
        dst = model.slots.get(op.writes[0]) if op.writes else None
        if src is not None and src.mesh != op.edge[0]:
            out.append(Finding(
                "model_check", "model.channel-endpoint",
                f"{op.label}: source slot {src.slot} ({src.var}) lives "
                f"on mesh {src.mesh} but the channel edge says the "
                f"SEND runs on mesh {op.edge[0]}", op.idx))
        if dst is not None and dst.mesh != op.edge[1]:
            out.append(Finding(
                "model_check", "model.channel-endpoint",
                f"{op.label}: destination slot {dst.slot} ({dst.var}) "
                f"lives on mesh {dst.mesh} but the channel edge says "
                f"the RECV runs on mesh {op.edge[1]}", op.idx))
    return out


def check_inflight_window(hooks: Optional[Sequence[Any]],
                          window: int
                          ) -> Tuple[List[Finding], int]:
    """Walk the compiled hook sequence counting launched-but-unwaited
    transfers (a batched group counts once, matching the scheduler's
    accounting) and verify the declared ``overlap_inflight_window``
    bound as a property instead of trusting the scheduler."""
    out: List[Finding] = []
    active: Dict[Tuple[int, ...], int] = {}
    max_inflight = 0
    first_over = -1
    for hook in hooks or ():
        kind = getattr(hook, "kind", "exec")
        members = tuple(getattr(hook, "members", ()) or ())
        if kind == "launch":
            active[members] = getattr(hook, "node", -1)
            if len(active) > max_inflight:
                max_inflight = len(active)
                if window and max_inflight > window and first_over < 0:
                    first_over = getattr(hook, "node", -1)
        elif kind == "wait":
            active.pop(members, None)
    if window and max_inflight > window:
        out.append(Finding(
            "model_check", "model.inflight-exceeds-window",
            f"the compiled schedule holds up to {max_inflight} "
            f"transfers in flight but declares "
            f"overlap_inflight_window={window} — the staging-memory "
            f"bound the window promises is not honored", first_over))
    return out, max_inflight


def classify_retry_sites(model: PlanModel,
                         hooks: Optional[Sequence[Any]]
                         ) -> Tuple[List[Finding],
                                    Dict[str, Dict[str, Any]]]:
    """Static inject-fail-then-retry replay over the compiled hooks.

    For each ``fault.KNOWN_SITES`` site, symbolically fail every hook
    bound to it mid-operation and re-run it, checking the three
    non-idempotence sources the model exposes: donated-buffer RUNs
    (the retry re-reads slots the first attempt consumed), multi-member
    transfer groups (the retry resends members that already landed),
    and same-channel in-flight overlap (the retry re-enqueues behind a
    younger payload, breaking FIFO pairing).  Returns note-severity
    findings plus the per-site classification installed into
    ``fault.install_retry_classification``."""
    from alpa_tpu import fault as _fault
    sites: Dict[str, Dict[str, Any]] = {
        s: {"classification": "unreachable", "reasons": [], "hooks": 0}
        for s in sorted(_fault.KNOWN_SITES)}
    findings: List[Finding] = []
    donated: Dict[str, List[int]] = {}
    grouped: Dict[str, List[int]] = {}
    reordered: Dict[str, List[int]] = {}
    launch_channel: Dict[Tuple[int, ...], Tuple[int, int]] = {}
    inflight_per_channel: Dict[Tuple[int, int], int] = {}

    def _edge_of(hook) -> Optional[Tuple[int, int]]:
        members = tuple(getattr(hook, "members", ()) or ())
        if members and 0 <= members[0] < len(model.ops):
            e = model.ops[members[0]].edge
            return tuple(e) if e else None
        return None

    for hook in hooks or ():
        kind = getattr(hook, "kind", "exec")
        members = tuple(getattr(hook, "members", ()) or ())
        if kind == "wait":
            ch = launch_channel.pop(members, None)
            if ch is not None:
                inflight_per_channel[ch] -= 1
            continue
        site = getattr(hook, "fault_site", None)
        if site is None or site not in sites:
            continue
        ent = sites[site]
        ent["hooks"] += 1
        if ent["classification"] == "unreachable":
            ent["classification"] = "safe"
        node = getattr(hook, "node", -1)
        if getattr(hook, "kills", ()) and \
                not getattr(hook, "idempotent", True):
            donated.setdefault(site, []).append(node)
        if len(members) > 1:
            grouped.setdefault(site, []).append(node)
        if kind == "launch":
            ch = _edge_of(hook)
            if ch is not None:
                if inflight_per_channel.get(ch, 0) > 0:
                    reordered.setdefault(site, []).append(node)
                inflight_per_channel[ch] = \
                    inflight_per_channel.get(ch, 0) + 1
                launch_channel[members] = ch

    for site, nodes in donated.items():
        sites[site]["classification"] = "unsafe"
        sites[site]["reasons"].append("unsafe-donation")
        findings.append(Finding(
            "model_check", "retry.unsafe-donation",
            f"site {site}: replaying inject-fail-then-retry "
            f"double-applies donated-buffer op(s) {nodes[:6]} — the "
            f"retry re-reads slots the first attempt consumed; "
            f"call_with_retry refuses the retry under "
            f"verify_plans=error", nodes[0]))
    for site, nodes in grouped.items():
        sites[site]["classification"] = "unsafe"
        sites[site]["reasons"].append("partial-group")
        findings.append(Finding(
            "model_check", "retry.partial-group",
            f"site {site}: op(s) {nodes[:6]} batch multiple transfers "
            f"into one DirectTransferGroup — a mid-group failure "
            f"retried whole resends members that already landed, "
            f"double-enqueueing onto the FIFO channel", nodes[0]))
    for site, nodes in reordered.items():
        sites[site]["classification"] = "unsafe"
        sites[site]["reasons"].append("fifo-reorder")
        findings.append(Finding(
            "model_check", "retry.fifo-reorder",
            f"site {site}: launch op(s) {nodes[:6]} overlap an older "
            f"in-flight transfer on the same channel — retrying the "
            f"older launch would re-enqueue its payload behind the "
            f"younger one, breaking FIFO send/recv pairing", nodes[0]))
    return findings, sites


########################################
# driver
########################################


def check_model(model: PlanModel,
                hooks: Optional[Sequence[Any]] = None,
                overlap_window: int = 0,
                budget: int = DEFAULT_STATE_BUDGET) -> ModelCheckResult:
    """Model-check one plan: explore all interleavings under buffered
    and rendezvous channel semantics, verify the in-flight window
    bound, and classify retry safety.  Pure function of its inputs."""
    t0 = time.perf_counter()
    findings: List[Finding] = list(check_channel_endpoints(model))
    streams_micro = build_micro_ops(model)
    n_micro = sum(len(s) for s in streams_micro)
    channels = sorted({u.channel for st in streams_micro for u in st
                       if u.channel is not None})

    cap_buffered = max(2, overlap_window) if overlap_window else 4
    runs = {}
    if not findings:
        # a corrupted channel edge makes the interleaving model
        # meaningless — report the structural break alone
        runs["buffered"] = _explore(model, streams_micro,
                                    cap_buffered, budget)
        runs["rendezvous"] = _explore(model, streams_micro, 1, budget)

    semantics: Dict[str, str] = {}
    counterexample: Optional[List[str]] = None
    hazard_keys = set()
    for name in ("buffered", "rendezvous"):
        r = runs.get(name)
        if r is None:
            semantics[name] = "skipped"
            continue
        verdict = "pass"
        if r.hazards:
            verdict = "hazard"
        if r.deadlock_trace is not None:
            verdict = "deadlock"
        elif r.partial:
            verdict = "partial"
        semantics[name] = verdict
        for (code, op), (msg, trace) in r.hazards.items():
            if (code, op) in hazard_keys:
                continue
            hazard_keys.add((code, op))
            findings.append(Finding("model_check", code, msg, op))
            if counterexample is None:
                counterexample = trace
    buf, rdv = runs.get("buffered"), runs.get("rendezvous")
    if buf is not None and buf.deadlock_trace is not None:
        counterexample = buf.deadlock_trace
        findings.append(Finding(
            "model_check", "model.deadlock",
            f"a reachable schedule deadlocks under buffered "
            f"(capacity-{cap_buffered}) channel semantics — "
            f"{buf.n_deadlock_states} deadlocked state(s) found; see "
            f"the counterexample schedule in the model-check report"))
    elif rdv is not None and rdv.deadlock_trace is not None:
        counterexample = rdv.deadlock_trace
        findings.append(Finding(
            "model_check", "model.rendezvous-deadlock",
            f"the plan is deadlock-free under buffered channels but a "
            f"reachable schedule deadlocks under rendezvous "
            f"(capacity-1) semantics — {rdv.n_deadlock_states} "
            f"deadlocked state(s); backends without per-channel "
            f"buffering would hang"))
    if any(r is not None and r.partial for r in (buf, rdv)):
        findings.append(Finding(
            "model_check", "model.budget-exhausted",
            f"state budget {budget} exhausted before full coverage "
            f"(partial exploration; raise "
            f"ALPA_TPU_MODEL_CHECK_BUDGET for a complete proof)"))

    window_findings, max_inflight = check_inflight_window(
        hooks, overlap_window)
    findings += window_findings
    retry_findings, retry_sites = classify_retry_sites(model, hooks)
    findings += retry_findings

    states = sum(r.states for r in runs.values())
    transitions = sum(r.transitions for r in runs.values())
    por = sum(r.por_commits for r in runs.values())
    stats: Dict[str, Any] = {
        "states": states,
        "transitions": transitions,
        "por_commits": por,
        "reduction_ratio": round(por / transitions, 4)
        if transitions else 0.0,
        "partial": any(r.partial for r in runs.values()),
        "budget": budget,
        "n_micro_ops": n_micro,
        "n_channels": len(channels),
        "capacity_buffered": cap_buffered,
        "semantics": semantics,
        "declared_window": overlap_window,
        "max_inflight": max_inflight,
        "retry_sites": retry_sites,
        "counterexample": counterexample,
        "seconds": round(time.perf_counter() - t0, 6),
    }
    return ModelCheckResult(findings=findings, stats=stats)


def format_stats(stats: Dict[str, Any],
                 findings: Optional[Sequence[Finding]] = None) -> str:
    """Human-readable model-check report (``model_check.txt``,
    ``verify_tool.py modelcheck``).  Works from the JSON-able stats
    dict alone so cached verdicts render identically."""
    sem = stats.get("semantics", {})
    lines = [
        "model check: "
        + "  ".join(f"{k}={v}" for k, v in sorted(sem.items()))
        + (" (PARTIAL — state budget exhausted)"
           if stats.get("partial") else ""),
        f"states={stats.get('states', 0)}  "
        f"transitions={stats.get('transitions', 0)}  "
        f"por_commits={stats.get('por_commits', 0)}  "
        f"reduction_ratio={stats.get('reduction_ratio', 0.0)}  "
        f"seconds={stats.get('seconds', 0.0)}",
        f"micro_ops={stats.get('n_micro_ops', 0)}  "
        f"channels={stats.get('n_channels', 0)}  "
        f"buffered_capacity={stats.get('capacity_buffered', 0)}  "
        f"window declared={stats.get('declared_window', 0)} "
        f"max_inflight={stats.get('max_inflight', 0)}",
    ]
    retry = stats.get("retry_sites", {})
    if retry:
        lines.append("retry sites:")
        for site, ent in sorted(retry.items()):
            reasons = ",".join(ent.get("reasons", ())) or "-"
            lines.append(
                f"  {site:<18} {ent.get('classification', '?'):<12} "
                f"hooks={ent.get('hooks', 0)}  reasons={reasons}")
    if findings:
        lines.append("findings:")
        for f in findings:
            at = f" (op {f.op})" if f.op >= 0 else ""
            lines.append(
                f"  [{severity_of(f.code)}] [{f.code}]{at} {f.message}")
    ce = stats.get("counterexample")
    if ce:
        lines.append("counterexample schedule:")
        lines += [f"  {ln}" for ln in ce]
    return "\n".join(lines)


def export_metrics(stats: Dict[str, Any], result: str) -> None:
    """Record one model-check outcome in the central registry
    (``alpa_model_check_states_total`` /
    ``alpa_plan_model_check_total{result}``)."""
    states = stats.get("states", 0) if stats else 0
    if states:
        _STATES_TOTAL.inc(states)
    _MC_TOTAL.labels(result).inc()


########################################
# fixture (de)serialization
########################################


def model_to_dict(model: PlanModel,
                  hooks: Optional[Sequence[Any]] = None,
                  overlap_window: int = 0) -> Dict[str, Any]:
    """JSON-able form of a plan model + hooks + declared window — the
    committed model-check fixture format
    (``benchmark/results/model_check_fixture_plan.json``)."""
    out = {
        "format": "alpa-model-check-plan/v1",
        "mode": model.mode,
        "num_meshes": model.num_meshes,
        "overlap_window": overlap_window,
        "slots": [dataclasses.asdict(sm)
                  for _s, sm in sorted(model.slots.items())],
        # the ISSUE-15 "equiv" / ISSUE-19 "grad_quant" facts are omitted
        # when absent so pre-existing committed fixtures round-trip
        # byte-identically
        "ops": [{k: (list(v) if isinstance(v, tuple) else v)
                 for k, v in dataclasses.asdict(op).items()
                 if not (k in ("equiv", "grad_quant") and v is None)}
                for op in model.ops],
        "streams": [list(s) for s in model.streams],
        "deps": {str(i): sorted(v) for i, v in model.deps.items()},
        "hooks": [
            dict({"kind": h.kind, "name": h.name, "node": h.node,
                  "mesh": h.mesh, "reads": list(h.reads),
                  "writes": list(h.writes), "kills": list(h.kills),
                  "slots": list(h.slots), "fault_site": h.fault_site,
                  "idempotent": h.idempotent,
                  "members": list(h.members)},
                 **({"equiv": h.equiv}
                    if getattr(h, "equiv", None) is not None else {}))
            for h in (hooks or ())],
    }
    if model.reference is not None:
        out["reference"] = model.reference
    return out


def model_from_dict(d: Dict[str, Any]
                    ) -> Tuple[PlanModel, List[Any], int]:
    """Inverse of :func:`model_to_dict`:
    ``(model, hooks, overlap_window)``."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import OpHook
    slots = {}
    for sd in d.get("slots", ()):
        sm = SlotModel(**{k: (tuple(v) if k == "shape" else v)
                          for k, v in sd.items()})
        slots[sm.slot] = sm
    ops = []
    for od in d.get("ops", ()):
        kw = dict(od)
        for k in ("reads", "writes", "kills"):
            kw[k] = tuple(tuple(x) if isinstance(x, list) else x
                          for x in kw.get(k, ()))
        for k in ("in_avals", "out_avals"):
            # avals are ((shape, dtype) | None) pairs whose shape must
            # come back as a tuple (the typing pass compares tuples)
            kw[k] = tuple(
                (tuple(a[0]), a[1])
                if isinstance(a, (list, tuple)) and len(a) == 2 and
                isinstance(a[0], (list, tuple)) else
                (tuple(a) if isinstance(a, list) else a)
                for a in kw.get(k, ()))
        if kw.get("edge") is not None:
            kw["edge"] = tuple(kw["edge"])
        ops.append(OpModel(**kw))
    model = PlanModel(
        ops=ops, slots=slots,
        num_meshes=int(d.get("num_meshes", 1)),
        streams=[list(s) for s in d.get("streams", ())],
        deps={int(i): set(v) for i, v in d.get("deps", {}).items()},
        mode=d.get("mode", "registers"),
        reference=d.get("reference"))
    hooks = [OpHook(kind=h["kind"], name=h["name"], node=h["node"],
                    mesh=h["mesh"], reads=tuple(h["reads"]),
                    writes=tuple(h["writes"]), kills=tuple(h["kills"]),
                    slots=tuple(h.get("slots", ())),
                    fault_site=h.get("fault_site"),
                    idempotent=bool(h.get("idempotent", True)),
                    members=tuple(h["members"]),
                    equiv=h.get("equiv"))
             for h in d.get("hooks", ())]
    return model, hooks, int(d.get("overlap_window", 0))


def load_fixture(path: str) -> Tuple[PlanModel, List[Any], int]:
    """Load a committed fixture plan JSON file."""
    import json
    with open(path, encoding="utf-8") as f:
        return model_from_dict(json.load(f))
