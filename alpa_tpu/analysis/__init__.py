"""Static analysis over compiled pipeshard plans (ISSUE 8).

Two halves:

* :mod:`alpa_tpu.analysis.plan_verifier` — a typed abstract
  interpretation over every lowered
  :class:`~alpa_tpu.pipeline_parallel.runtime_emitter.RegisterFileProgram`
  run at compile time: slot typing, cross-mesh deadlock freedom,
  liveness/leaks + peak-live-bytes, and a cached
  :class:`~alpa_tpu.analysis.plan_verifier.PlanVerdict` gating
  compilation behind ``global_config.verify_plans``.
* :mod:`alpa_tpu.analysis.lint` — an AST repo lint enforcing codified
  invariants (knob/env-var/doc registration, ``alpa_*`` metric names,
  no new legacy-timer call sites, known fault-site names), run as a
  tier-1 test (tests/util/test_repo_lint.py) and via
  ``scripts/verify_tool.py verify lint``.
* :mod:`alpa_tpu.analysis.critical_path` — pure-data critical-path
  walk + dependency-DAG re-simulation (ISSUE 9) under
  :mod:`alpa_tpu.telemetry.perf`.
* :mod:`alpa_tpu.analysis.model_check` — an explicit-state model
  checker (ISSUE 13) exploring all stream interleavings of a plan
  under explicit SEND/RECV channel semantics (rendezvous and
  buffered), with partial-order reduction, hazard re-checking in
  every interleaving, overlap-window verification, and fault/retry
  safety classification.  Runs as the fifth ``verify_program``
  analysis behind ``global_config.verify_plans_model_check``.
* :mod:`alpa_tpu.analysis.numerics` — a precision-flow abstract
  interpretation (ISSUE 14) composing end-to-end quantization
  error bounds per register slot (storage/accumulation dtypes,
  provenance, lossy-hop lists) from the transfer codec's documented
  ``ERROR_BOUND`` contract.  Runs as the sixth ``verify_program``
  analysis behind ``global_config.verify_plans_numerics``.
* :mod:`alpa_tpu.analysis.superopt` — a certified post-lowering
  rewrite engine (ISSUE 17): re-scheduling, FREE motion, transfer
  fusion/fission, and recompute flips over the lowered instruction
  list, scored by ``simulate_dag`` over calibrated costs and accepted
  only when the seven-analysis verdict introduces no new finding vs
  the baseline.  Behind ``global_config.superopt_mode``.
"""
from alpa_tpu.analysis.critical_path import (  # noqa: F401
    CriticalPathReport, MemSpec, PathStep, TimedOp, longest_path,
    measured_critical_path, simulate_dag)
from alpa_tpu.analysis.model_check import (  # noqa: F401
    ModelCheckResult, check_model, load_fixture, model_from_dict,
    model_to_dict)
from alpa_tpu.analysis.numerics import (  # noqa: F401
    NumericsResult, PrecisionValue, check_numerics)
from alpa_tpu.analysis.plan_verifier import (  # noqa: F401
    Finding, PlanModel, PlanVerdict, PlanVerificationError,
    verify_model)
from alpa_tpu.analysis.superopt import (  # noqa: F401
    PlanScore, SuperoptOutcome, reshard_group_extent, run_superopt,
    superopt_search, verdict_diff, verdict_new_findings)
