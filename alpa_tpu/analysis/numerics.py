"""Numerics certification: precision-flow abstract interpretation with
end-to-end quantization error bounds (ISSUE 14 tentpole).

The sixth ``verify_program`` analysis.  The plan verifier's other
passes prove *where* values move; this one proves *how much precision
survives the trip*.  Every register slot carries an abstract precision
value — storage dtype, narrowest accumulation dtype seen on its
producing path, a composed worst-case relative-error bound, a
provenance class (``param`` / ``opt_state`` / ``gradient`` /
``activation``, seeded from the PR 10 invar-path plumbing), and the
ordered list of lossy hops it crossed — propagated through the lowered
RUN/RESHARD/FREE program in flat emission order:

* **RUN** — outputs inherit the max input bound (error propagation
  through a stage is modeled unamplified: stages are
  Lipschitz-normalized matmul/elementwise pipelines, and the bound is a
  *relative-to-blockmax* term, not an absolute one), the merged lossy
  hop list, and the highest-priority provenance
  (opt_state > param > gradient > activation) of the stage's *donated*
  inputs — a donation is an in-place update of the same logical state
  (grad accumulate, apply_grad), so param/opt-state identity survives
  it, while an output computed from a merely-*read* param is a fresh
  activation that may legally cross lossy hops.  The stage executable's
  jaxpr-level eqn classification
  (:func:`alpa_tpu.shard_parallel.eqn_classify.classify_stage_precision`)
  types each stage's matmul/reduce/cast population; a reduction that
  accumulates below fp32 raises ``numerics.bf16-accumulation``.
* **RESHARD** — a lossy hop composes the codec's documented bound from
  :data:`alpa_tpu.pipeline_parallel.reshard_codec.ERROR_BOUND` (the
  int8 ``blockmax/254`` and fp8-e4m3 ``7% blockmax`` contract — the
  same constants the codec's property tests pin) first-order additively
  onto the flowing value, appends the hop, and is enumerated as a
  ``numerics.quantized-reduction`` note — the ROADMAP item-3 typing of
  which collectives are quantized vs full-precision.
* Lossless hops and FREEs propagate / drop values untouched.

Finding taxonomy (:func:`severity_of`):

* ``numerics.lossy-weight-path`` (error) — a value of ``param``
  provenance (or a weight edge) crosses a lossy hop.  Strengthens the
  typing pass's per-edge weight check into a full-flow proof: a weight
  that became an activation-name three hops ago is still caught.
* ``numerics.lossy-opt-state-path`` (error) — optimizer state (incl.
  future error-feedback accumulators) routed through a lossy hop.
* ``numerics.budget-exceeded`` (error) — a value's composed worst-case
  bound crossed ``global_config.numerics_error_budget``.
* ``numerics.bf16-accumulation`` (warning) — a stage reduction
  accumulates below fp32.
* ``numerics.quantized-reduction`` (note) — one per lossy collective,
  enumerating codec, edge, and the composed bound after the hop.
* ``numerics.quantized-gradient`` (note, ISSUE 19) — one per RUN whose
  gradient-provenance accumulation runs through the stochastic-rounding
  gradient codec (``grad_quantize != off``): composes
  ``ERROR_BOUND["grad_<mode>"]`` (the ``_rs`` two-hop variant for
  reduce-scatter syncs) onto the flowing bound, amortized to a single
  hop under error feedback and additive in the microbatch hop count
  without it; ``numerics_error_budget`` gates the result exactly like
  a resharding hop.

Gated by ``global_config.verify_plans_numerics`` (``off | warn |
error``, default ``warn``; env ``ALPA_TPU_VERIFY_NUMERICS``) —
``error`` blocks ``_launch`` with :class:`PlanVerificationError` even
when ``verify_plans`` itself is only warning.  Stats land at
``PlanVerdict.stats["numerics"]`` (JSON-able, deterministic, replayed
byte-identically from the verdict cache), render as ``numerics.txt``
in ``dump_debug_info``, export the ``alpa_numerics_max_error_bound`` /
``alpa_numerics_lossy_edges_total{kind}`` gauges, and print offline
via ``scripts/verify_tool.py numerics`` (schema ``alpa-numerics/v1``).
"""
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "PrecisionValue", "NumericsResult", "check_numerics", "severity_of",
    "format_numerics", "export_metrics", "DEFAULT_ERROR_BUDGET",
]

#: fallback per-tensor relative-error budget when the caller passes
#: none (mirrors the global_env default)
DEFAULT_ERROR_BUDGET = 0.05

#: provenance merge priority: the most precision-critical class wins
#: when a stage mixes inputs
_PROV_PRIORITY = {"opt_state": 3, "param": 2, "gradient": 1,
                  "activation": 0, "": -1}

#: finding code -> severity the plan verifier merges it at
_SEVERITY = {
    "numerics.lossy-weight-path": "error",
    "numerics.lossy-opt-state-path": "error",
    "numerics.budget-exceeded": "error",
    "numerics.bf16-accumulation": "warning",
    "numerics.quantized-reduction": "note",
    "numerics.quantized-gradient": "note",
}


def severity_of(code: str) -> str:
    """Severity class (``"error" | "warning" | "note"``) the plan
    verifier merges a numerics finding at."""
    return _SEVERITY.get(code, "note")


@dataclasses.dataclass(frozen=True)
class PrecisionValue:
    """The abstract domain: one slot's precision facts at a program
    point."""
    storage: str                        # dtype the value is stored in
    accum: str                          # narrowest accumulation dtype
    rel_bound: float                    # composed worst-case rel error
                                        # (fraction of block max)
    provenance: str                     # param|opt_state|gradient|
                                        # activation|""
    lossy_hops: Tuple[str, ...] = ()    # ordered "<edge>:<codec>" hops


@dataclasses.dataclass
class NumericsResult:
    """Findings + stats of one :func:`check_numerics` run.  ``stats``
    is JSON-able and stored verbatim at
    ``PlanVerdict.stats["numerics"]`` so cached verdicts replay the
    identical report."""
    findings: List[Any] = dataclasses.field(default_factory=list)
    stats: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not any(severity_of(f.code) == "error"
                       for f in self.findings)

    def format(self) -> str:
        return format_numerics(self.stats, self.findings)


def _error_bounds() -> Dict[str, float]:
    """The codec's machine-readable contract — never duplicated here
    (the ``codec-bound`` lint rule holds the codec side of this)."""
    from alpa_tpu.pipeline_parallel.reshard_codec import ERROR_BOUND
    return dict(ERROR_BOUND)


def _merge_provenance(provs: Sequence[str]) -> str:
    best = ""
    for p in provs:
        if _PROV_PRIORITY.get(p, -1) > _PROV_PRIORITY.get(best, -1):
            best = p
    return best


def _merge_hops(hop_lists: Sequence[Tuple[str, ...]]
                ) -> Tuple[str, ...]:
    out: List[str] = []
    for hops in hop_lists:
        for h in hops:
            if h not in out:
                out.append(h)
    return tuple(out)


def check_numerics(model, hooks: Optional[Sequence[Any]] = None,
                   budget: Optional[float] = None) -> NumericsResult:
    """Run the precision-flow abstract interpretation over a
    :class:`~alpa_tpu.analysis.plan_verifier.PlanModel`.  Pure function
    of its inputs — no globals, no cache, no metrics (see
    ``verify_program`` for the compile-time wrapper)."""
    from alpa_tpu.analysis.plan_verifier import Finding
    del hooks  # footprint checks are the structure pass's job
    t0 = time.perf_counter()
    if budget is None:
        budget = DEFAULT_ERROR_BUDGET
    budget = float(budget)
    bounds = _error_bounds()
    findings: List[Finding] = []

    # abstract state: slot -> PrecisionValue; seeded from the slot
    # table (launch-placed values carry their PR 10 provenance class)
    vals: Dict[int, PrecisionValue] = {}
    for s, sm in model.slots.items():
        if sm.preplaced or sm.dtype:
            prov = getattr(sm, "provenance", "") or ""
            if not prov and getattr(sm, "opt_state", False):
                prov = "opt_state"
            vals[s] = PrecisionValue(
                storage=sm.dtype, accum=sm.dtype, rel_bound=0.0,
                provenance=prov if sm.preplaced else "",
                lossy_hops=())

    lossy_edges: Dict[str, int] = {}
    n_bf16 = 0
    budget_hit: set = set()     # dst slots already reported

    def _slot_var(s: int) -> str:
        sm = model.slots.get(s)
        return sm.var if sm is not None else f"slot{s}"

    for op in model.ops:
        if op.kind == "RUN":
            ins = [vals.get(s) for s in op.reads]
            ins = [v for v in ins if v is not None]
            in_bound = max((v.rel_bound for v in ins), default=0.0)
            # error bounds and lossy-hop lists flow through compute
            # from EVERY input, but provenance only flows from donated
            # (killed) inputs — a donation is an in-place update of
            # the same logical state (grad accumulate, apply_grad),
            # whereas an output computed from a merely-read param is a
            # new activation and may legally cross lossy hops
            donated = set(op.kills)
            in_prov = _merge_provenance(
                [v.provenance for s, v in zip(op.reads,
                                              [vals.get(s)
                                               for s in op.reads])
                 if v is not None and s in donated])
            in_hops = _merge_hops([v.lossy_hops for v in ins])
            prec = getattr(op, "precision", None) or {}
            if prec.get("below_fp32_accum"):
                n_bf16 += 1
                findings.append(Finding(
                    "numerics", "numerics.bf16-accumulation",
                    f"{op.label}: {prec.get('n_reduce', 0)} "
                    f"reduction(s) / {prec.get('n_matmul', 0)} "
                    f"contraction(s) accumulate in "
                    f"{prec.get('min_accum', '?')} (below fp32) — "
                    f"partial sums lose mantissa before the final "
                    f"cast", op.idx))
            accum = str(prec.get("min_accum") or "")
            # Quantized gradient sync (ISSUE 19): a RUN carrying a
            # grad_quant fact whose donated inputs have gradient
            # provenance is a quantized gradient accumulation/sync —
            # compose the codec's stochastic-rounding bound.  With
            # error feedback the residual carries untransmitted mass
            # forward, so the cumulative bound over all accumulation
            # hops amortizes to a single hop; without it the worst
            # case is additive in the hop count.
            gq = getattr(op, "grad_quant", None) or {}
            if gq and in_prov == "gradient":
                mode = str(gq.get("mode", "int8"))
                bkey = f"grad_{mode}" + ("_rs" if gq.get("rs") else "")
                per_hop = bounds.get(bkey, max(bounds.values()))
                n_hops = 1 if gq.get("ef", True) else \
                    max(1, int(gq.get("hops", 1)))
                add = per_hop * n_hops
                new_bound = in_bound + add
                hop = f"{op.label or f'op{op.idx}'}:{bkey}"
                in_bound = new_bound
                in_hops = in_hops + (hop,)
                lossy_edges[bkey] = lossy_edges.get(bkey, 0) + 1
                findings.append(Finding(
                    "numerics", "numerics.quantized-gradient",
                    f"{op.label}: quantized gradient sync ({bkey}, "
                    f"documented bound {per_hop:.6g} of blockmax x "
                    f"{n_hops} hop(s)"
                    + (", error-feedback amortized" if gq.get("ef", True)
                       else "") +
                    f"); composed bound after sync {new_bound:.6g}",
                    op.idx))
                if new_bound > budget:
                    dsts = [s for s in op.writes if s not in budget_hit]
                    budget_hit.update(dsts)
                    findings.append(Finding(
                        "numerics", "numerics.budget-exceeded",
                        f"{op.label}: composed worst-case gradient "
                        f"bound {new_bound:.6g} exceeds "
                        f"numerics_error_budget {budget:.6g} after "
                        f"quantized sync {hop}", op.idx))
            for pos, s in enumerate(op.writes):
                declared = (op.out_avals[pos]
                            if pos < len(op.out_avals) else None)
                sm = model.slots.get(s)
                storage = (declared[1] if declared
                           else (sm.dtype if sm is not None else ""))
                vals[s] = PrecisionValue(
                    storage=storage, accum=accum or storage,
                    rel_bound=in_bound, provenance=in_prov,
                    lossy_hops=in_hops)
        elif op.kind == "RESHARD":
            src = op.reads[0] if op.reads else None
            dst = op.writes[0] if op.writes else None
            v = vals.get(src) if src is not None else None
            if v is None:
                sm = model.slots.get(src) if src is not None else None
                v = PrecisionValue(
                    storage=sm.dtype if sm is not None else "",
                    accum=sm.dtype if sm is not None else "",
                    rel_bound=0.0,
                    provenance=(getattr(sm, "provenance", "")
                                if sm is not None else ""),
                    lossy_hops=())
            codec = getattr(op, "codec", None)
            if codec is None and op.strategy == "quantized":
                codec = "int8"      # quantized edge with unknown mode
            if codec:
                hop_bound = bounds.get(codec,
                                       max(bounds.values()))
                edge = (f"{op.edge[0]}->{op.edge[1]}"
                        if op.edge else "?")
                hop = f"{edge}:{codec}"
                prov = v.provenance
                weightish = op.weight or prov == "param"
                new_bound = v.rel_bound + hop_bound
                v = PrecisionValue(
                    storage=v.storage, accum=v.accum,
                    rel_bound=new_bound, provenance=prov,
                    lossy_hops=v.lossy_hops + (hop,))
                lossy_edges[codec] = lossy_edges.get(codec, 0) + 1
                findings.append(Finding(
                    "numerics", "numerics.quantized-reduction",
                    f"{op.label}: lossy collective ({codec}, "
                    f"documented bound {hop_bound:.6g} of blockmax) on "
                    f"edge {edge}; composed bound after hop "
                    f"{new_bound:.6g}", op.idx))
                if weightish:
                    findings.append(Finding(
                        "numerics", "numerics.lossy-weight-path",
                        f"{op.label}: parameter-provenance value "
                        f"{_slot_var(src)} crosses lossy hop {hop} — "
                        f"weights must flow losslessly end to end",
                        op.idx))
                if prov == "opt_state":
                    findings.append(Finding(
                        "numerics", "numerics.lossy-opt-state-path",
                        f"{op.label}: optimizer-state value "
                        f"{_slot_var(src)} crosses lossy hop {hop} — "
                        f"opt state must flow losslessly end to end",
                        op.idx))
                if new_bound > budget and dst not in budget_hit:
                    budget_hit.add(dst)
                    findings.append(Finding(
                        "numerics", "numerics.budget-exceeded",
                        f"{op.label}: composed worst-case bound "
                        f"{new_bound:.6g} of {_slot_var(src)} exceeds "
                        f"numerics_error_budget {budget:.6g} after "
                        f"hops {list(v.lossy_hops)}", op.idx))
            if dst is not None:
                vals[dst] = v
        # FREE: values simply die; nothing to propagate

    # per-output bound table (protected slots = program outputs), plus
    # the program-wide worst case over every tracked slot
    table: List[Dict[str, Any]] = []
    for s in sorted(model.slots):
        sm = model.slots[s]
        if not sm.protected:
            continue
        v = vals.get(s)
        if v is None:
            continue
        table.append({
            "slot": s, "var": sm.var,
            "provenance": v.provenance or "activation",
            "storage": v.storage, "accum": v.accum,
            "bound": v.rel_bound, "hops": list(v.lossy_hops),
        })
    max_bound = max((v.rel_bound for v in vals.values()), default=0.0)

    stats = {
        "max_error_bound": max_bound,
        "lossy_edges": dict(sorted(lossy_edges.items())),
        "n_lossy_collectives": sum(lossy_edges.values()),
        "n_bf16_reductions": n_bf16,
        "bound_table": table,
        "budget": budget,
        "n_tracked": len(vals),
        "seconds": round(time.perf_counter() - t0, 6),
    }
    return NumericsResult(findings=findings, stats=stats)


def format_numerics(stats: Dict[str, Any],
                    findings: Optional[Sequence[Any]] = None) -> str:
    """Human-readable numerics report (``numerics.txt``,
    ``verify_tool.py numerics``).  Works from the JSON-able stats dict
    alone so cached verdicts render identically."""
    lossy = stats.get("lossy_edges", {})
    lines = [
        "numerics certification: "
        + ("no lossy hops" if not lossy else
           "  ".join(f"{k}={v}" for k, v in sorted(lossy.items()))),
        f"max_error_bound={stats.get('max_error_bound', 0.0):.6g}  "
        f"budget={stats.get('budget', 0.0):.6g}  "
        f"lossy_collectives={stats.get('n_lossy_collectives', 0)}  "
        f"bf16_reductions={stats.get('n_bf16_reductions', 0)}  "
        f"tracked_slots={stats.get('n_tracked', 0)}  "
        f"seconds={stats.get('seconds', 0.0)}",
    ]
    table = stats.get("bound_table", ())
    if table:
        lines.append("per-output bounds:")
        lines.append(f"  {'output':<20} {'provenance':<11} "
                     f"{'storage':<10} {'accum':<10} {'bound':>12}  "
                     f"hops")
        for row in table:
            hops = ", ".join(row.get("hops", ())) or "-"
            lines.append(
                f"  {str(row.get('var', '?')):<20} "
                f"{row.get('provenance', '?'):<11} "
                f"{row.get('storage', '?'):<10} "
                f"{row.get('accum', '?'):<10} "
                f"{row.get('bound', 0.0):>12.6g}  {hops}")
    if findings:
        lines.append("findings:")
        for f in findings:
            at = f" (op {f.op})" if f.op >= 0 else ""
            lines.append(
                f"  [{severity_of(f.code)}] [{f.code}]{at} {f.message}")
    return "\n".join(lines)


def export_metrics(stats: Optional[Dict[str, Any]]) -> None:
    """Publish one numerics run's gauges in the central registry
    (``alpa_numerics_max_error_bound`` /
    ``alpa_numerics_lossy_edges_total{kind}``).  Gauges are *set* from
    the deterministic stats, so warm-restart cache replays export
    exactly the cold compile's values."""
    if not stats:
        return
    _MAX_BOUND.set(float(stats.get("max_error_bound", 0.0)))
    for kind, n in (stats.get("lossy_edges") or {}).items():
        _LOSSY_EDGES.labels(str(kind)).set(float(n))


from alpa_tpu.telemetry import metrics as _tmetrics  # noqa: E402

_REG = _tmetrics.get_registry()
_MAX_BOUND = _REG.gauge(
    "alpa_numerics_max_error_bound",
    "Numerics certification: worst composed relative error bound "
    "(fraction of block max) over every register slot of the last "
    "verified plan")
_LOSSY_EDGES = _REG.gauge(
    "alpa_numerics_lossy_edges_total",
    "Numerics certification: lossy (quantized) transfer hops in the "
    "last verified plan, by codec kind",
    labelnames=("kind",))
