"""FollowParallel: parallelize a function following another's placement.

Analog of ref ``alpa/follow_parallel.py`` (SURVEY.md §2.1): compile e.g. an
eval/inference step whose inputs reuse the sharding layout chosen for the
train step, so no resharding happens between train and eval calls.
"""
import logging
from typing import Any, Optional, Sequence

import jax

from alpa_tpu.mesh_executable import NormalMeshExecutable
from alpa_tpu.parallel_method import ParallelMethod

logger = logging.getLogger(__name__)


class FollowParallel(ParallelMethod):
    """method=FollowParallel(train_step, train_step_args)
    (ref compile_follow_parallel_executable, follow_parallel.py:25)."""

    def __init__(self, src_func, src_args: Sequence[Any],
                 num_micro_batches: Optional[int] = None):
        self.src_func = src_func
        self.src_args = src_args
        self.num_micro_batches = num_micro_batches

    def compile_executable(self, fun, in_avals, in_tree, in_paths,
                           donated_invars, batch_invars):
        src_exec, _ = self.src_func.get_executable(*self.src_args)
        from alpa_tpu.pipeline_parallel.pipeshard_executable import (
            PipeshardDriverExecutable)
        if isinstance(src_exec, PipeshardDriverExecutable):
            return self._compile_following_pipeshard(
                src_exec, fun, in_avals, in_tree, in_paths,
                donated_invars, batch_invars)

        # Match our inputs to the source executable's inputs by
        # (shape, dtype): shared leaves (params/state) reuse the source
        # sharding; unmatched args (e.g. a different batch) stay unset.
        import numpy as np
        pool = {}
        for aval, s in zip(src_exec.in_avals, src_exec.in_shardings):
            pool.setdefault((tuple(aval.shape), np.dtype(aval.dtype)),
                            []).append(s)
        in_shardings = []
        for aval in in_avals:
            lst = pool.get((tuple(aval.shape), np.dtype(aval.dtype)))
            in_shardings.append(lst.pop(0) if lst else None)

        jitted = jax.jit(fun, in_shardings=tuple(in_shardings))
        compiled = jitted.lower(*in_avals).compile()
        return NormalMeshExecutable(
            src_exec.physical_mesh, compiled,
            in_avals=in_avals, out_avals=None,
            in_shardings=[
                s if s is not None else c for s, c in zip(
                    in_shardings, compiled.input_shardings[0])
            ],
            out_shardings=list(compiled.output_shardings),
            in_tree=in_tree, out_tree=None)

    def _compile_following_pipeshard(self, src_exec, fun, in_avals,
                                     in_tree, in_paths, donated_invars,
                                     batch_invars):
        """Follow a pipeshard train step (ref follow_parallel.py:25).

        The eval function is compiled as a pipeshard executable with the
        SOURCE method's options (same layer/stage slicing, same
        auto-sharding options, same deterministic compile seed), so the
        shared inputs — the train state resident across the stage meshes
        — land on identical (mesh, sharding) placements and flow into
        eval without any cross-mesh movement.  ``follow_report`` on the
        returned executable records per-placement agreement so tests can
        assert the follow actually held.
        """
        import numpy as np

        from alpa_tpu.parallel_method import PipeshardParallel

        src_method = getattr(self.src_func, "method", None)
        assert isinstance(src_method, PipeshardParallel), (
            "source executable is pipeshard but its function does not "
            "carry a PipeshardParallel method")
        method = PipeshardParallel(
            devices=src_method.devices,
            num_micro_batches=(self.num_micro_batches or 1),
            default_auto_sharding_option=src_method.as_option,
            pipeline_schedule=src_method.pipeline_schedule,
            layer_option=src_method.layer_option,
            stage_option=src_method.stage_option)
        exec2 = method.compile_executable(fun, in_avals, in_tree,
                                          in_paths, donated_invars,
                                          batch_invars)

        # report how many shared inputs follow the source placement:
        # match invars by (shape, dtype) and compare (mesh, spec) sets
        def placement_pool(ex):
            # batch inputs are fresh host values every call — only the
            # resident state (non-batch) must follow the source placement
            batch_vars = {
                v for v, is_b in zip(ex.global_invars, ex.batch_invars)
                if is_b
            }
            pool = {}
            for v, places in ex.input_place.items():
                if v in batch_vars:
                    continue
                key = (tuple(v.aval.shape), np.dtype(v.aval.dtype))
                pool.setdefault(key, []).append(
                    tuple(sorted((m, str(getattr(s, "spec", s)))
                                 for m, s in places)))
            return pool

        src_pool = placement_pool(src_exec)
        followed = mismatched = 0
        for key, placements in placement_pool(exec2).items():
            cands = list(src_pool.get(key, []))
            for p in placements:
                if p in cands:
                    cands.remove(p)   # multiset match: consume candidates
                    followed += 1
                else:
                    mismatched += 1
        exec2.follow_report = {"followed": followed,
                               "mismatched": mismatched}
        if mismatched:
            logger.info("FollowParallel(pipeshard): %d/%d shared inputs "
                        "diverged from the source placement", mismatched,
                        followed + mismatched)
        return exec2
