"""FollowParallel: parallelize a function following another's placement.

Analog of ref ``alpa/follow_parallel.py`` (SURVEY.md §2.1): compile e.g. an
eval/inference step whose inputs reuse the sharding layout chosen for the
train step, so no resharding happens between train and eval calls.
"""
import logging
from typing import Any, Optional, Sequence

import jax

from alpa_tpu.mesh_executable import NormalMeshExecutable
from alpa_tpu.parallel_method import ParallelMethod

logger = logging.getLogger(__name__)


class FollowParallel(ParallelMethod):
    """method=FollowParallel(train_step, train_step_args)
    (ref compile_follow_parallel_executable, follow_parallel.py:25)."""

    def __init__(self, src_func, src_args: Sequence[Any],
                 num_micro_batches: Optional[int] = None):
        self.src_func = src_func
        self.src_args = src_args
        self.num_micro_batches = num_micro_batches

    def compile_executable(self, fun, in_avals, in_tree, in_paths,
                           donated_invars, batch_invars):
        src_exec, _ = self.src_func.get_executable(*self.src_args)
        from alpa_tpu.pipeline_parallel.pipeshard_executable import (
            PipeshardDriverExecutable)
        if isinstance(src_exec, PipeshardDriverExecutable):
            raise NotImplementedError(
                "FollowParallel after a pipeshard executable is not wired "
                "yet; follow a ShardParallel executable or use "
                "PipeshardParallel with stage_input_shardings.")

        # Match our inputs to the source executable's inputs by
        # (shape, dtype): shared leaves (params/state) reuse the source
        # sharding; unmatched args (e.g. a different batch) stay unset.
        import numpy as np
        pool = {}
        for aval, s in zip(src_exec.in_avals, src_exec.in_shardings):
            pool.setdefault((tuple(aval.shape), np.dtype(aval.dtype)),
                            []).append(s)
        in_shardings = []
        for aval in in_avals:
            lst = pool.get((tuple(aval.shape), np.dtype(aval.dtype)))
            in_shardings.append(lst.pop(0) if lst else None)

        jitted = jax.jit(fun, in_shardings=tuple(in_shardings))
        compiled = jitted.lower(*in_avals).compile()
        return NormalMeshExecutable(
            src_exec.physical_mesh, compiled,
            in_avals=in_avals, out_avals=None,
            in_shardings=[
                s if s is not None else c for s, c in zip(
                    in_shardings, compiled.input_shardings[0])
            ],
            out_shardings=list(compiled.output_shardings),
            in_tree=in_tree, out_tree=None)
