"""Elastic training: detect -> quiesce -> snapshot -> re-solve -> resume.

The runtime assumes a fixed cluster for the lifetime of a compiled plan;
at preemptible-pod scale worker loss is the common case.  Every
ingredient for self-healing exists in isolation — RecoveryManager
quiesce/snapshot hooks (``fault``), bitwise cross-DP-degree ZeRO resume
(``checkpoint.store``), verified plan re-lowering (``replan_mode``) and
the seven-analysis plan verdict — and this module composes them into a
failure *lifecycle* owned end to end by :class:`ElasticSupervisor`:

1. **Detect** — a failure surfaces as (a) an exception out of the
   supervised step, (b) an injected or real signal at the elastic fault
   sites ``worker_lost`` / ``preemption_notice`` (polled at every step
   boundary), (c) a :class:`WedgeDetector` probe sweep, or (d) a
   watchdog escalation (``fault.set_escalation_manager``).
2. **Quiesce** — ``PipeshardDriverExecutable.quiesce()``: the launch
   gate closes and in-flight pipeshard work drains (bounded by
   ``global_config.elastic_quiesce_timeout_s``).
3. **Snapshot** — through the checkpoint manager, synchronously.  On a
   preemption *notice* the write must land inside the grace window
   (``elastic_grace_period_s``) to count as before-kill; a mid-step
   failure never snapshots (donated buffers make the live state torn)
   and falls back to the last *verified* checkpoint instead.
4. **Re-solve** — ``solve(survivors)`` builds a fresh parallel plan for
   the surviving (or grown) device set; shrinking/growing the DP degree
   rides ``ShardStore.read_leaf_slice`` bitwise shard reassembly on the
   restore below.  The full plan verdict (typing / deadlock / liveness
   / memory / model-check / numerics / translation-validation) is the
   acceptance gate: any finding not already present on the old plan
   rejects the candidate and rolls back to the old plan + last verified
   checkpoint.
5. **Resume** — restore the last hash-verified step, reopen the launch
   gate, and replay.  The episode is annotated into the flight ring and
   exported as ``alpa_elastic_*`` metrics; replay distance and wall
   clock are checked against ``elastic_step_budget`` /
   ``elastic_time_budget_s``.

The wedge-recovery runbook (``scripts/chip_recovery_runbook.sh``) is
code here: :class:`WedgeDetector` runs the probe-between-legs
discipline — a bounded-timeout trivial device program per mesh,
classified ``ok`` / ``wedged`` (no answer, not even an error) /
``dead`` (probe raised), short-circuiting at the first wedge sign —
and a wedge episode re-solves on the same devices (reset) and resumes
from the last verified checkpoint.

See docs/fault_tolerance.md#elastic-training.
"""
import concurrent.futures
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from alpa_tpu import fault
from alpa_tpu.global_env import global_config
from alpa_tpu.telemetry import flight as _flight
from alpa_tpu.telemetry import metrics as _tmetrics

logger = logging.getLogger(__name__)

__all__ = [
    "WorkerLost", "PreemptionNotice", "WedgeDetector",
    "ElasticSupervisor", "status_report", "get_supervisor",
]

_EPISODES = _tmetrics.get_registry().counter(
    "alpa_elastic_episodes_total",
    "Elastic recovery episodes by trigger "
    "(worker_lost/preemption_notice/wedge_detected/step_failure)",
    labelnames=("reason",))
_RECOVERY_SECONDS = _tmetrics.get_registry().histogram(
    "alpa_elastic_recovery_seconds",
    "Wall-clock seconds per episode, detect through resume")
_REPLAY_STEPS = _tmetrics.get_registry().histogram(
    "alpa_elastic_replay_steps",
    "Committed steps lost per episode (failure step minus restored step)")
_SNAPSHOTS = _tmetrics.get_registry().counter(
    "alpa_elastic_snapshots_total",
    "Episode snapshots by outcome (grace=landed inside the preemption "
    "window, late, boundary, skipped=mid-step state was torn, failed)",
    labelnames=("outcome",))
_REPLANS = _tmetrics.get_registry().counter(
    "alpa_elastic_replans_total",
    "Episode re-solve outcomes (accepted/rejected/reused/failed)",
    labelnames=("outcome",))
_BUDGET_VIOLATIONS = _tmetrics.get_registry().counter(
    "alpa_elastic_budget_violations_total",
    "Episodes exceeding the configured recovery budget, by kind "
    "(steps/seconds)",
    labelnames=("kind",))
_ELASTIC_STATE = _tmetrics.get_registry().gauge(
    "alpa_elastic_state",
    "Supervisor position (0=idle/training 1=inside a recovery episode)")


class WorkerLost(RuntimeError):
    """A mesh's workers died.  ``survivors`` (optional device list)
    names the device set to re-solve for; None keeps the current set
    (e.g. the scheduler will replace the host in place)."""

    def __init__(self, msg: str = "worker lost",
                 survivors: Optional[Sequence[Any]] = None):
        super().__init__(msg)
        self.survivors = list(survivors) if survivors is not None else None


class PreemptionNotice(RuntimeError):
    """Eviction warning: the kill lands after ``grace_s`` seconds
    (default ``global_config.elastic_grace_period_s``).  The supervisor
    snapshots synchronously inside the window, then re-solves for
    ``survivors``."""

    def __init__(self, msg: str = "preemption notice",
                 grace_s: Optional[float] = None,
                 survivors: Optional[Sequence[Any]] = None):
        super().__init__(msg)
        self.grace_s = grace_s
        self.survivors = list(survivors) if survivors is not None else None


class WedgeDetector:
    """The chip-recovery runbook's probe discipline as code.

    ``scripts/chip_recovery_runbook.sh`` runs ``timeout 120 python
    bench.py --probe`` between every leg and stops at the first sign of
    a wedge; the taxonomy it encodes is exactly three-valued and this
    class reproduces it per mesh:

    * ``"ok"``     — the probe program completed inside the timeout.
    * ``"wedged"`` — the probe neither answered nor errored (the
      runbook's hung-``timeout`` case): the device is alive enough to
      accept work but will never finish it.  Killing/retrying on it
      wedges harder; reset and restore instead.
    * ``"dead"``   — the probe raised or returned falsy: the device (or
      its runtime) is gone and says so.

    ``check()`` short-circuits at the first non-``ok`` mesh (remaining
    meshes report ``"skipped"``) — probing past a wedge is how failed
    legs get mistaken for successes.
    """

    def __init__(self, mesh_group=None,
                 probe: Optional[Callable[[Any], bool]] = None,
                 probe_timeout_s: Optional[float] = None):
        self.mesh_group = mesh_group
        self.probe_timeout_s = probe_timeout_s
        self._probe = probe

    def _timeout(self) -> float:
        if self.probe_timeout_s is not None:
            return self.probe_timeout_s
        return float(getattr(global_config, "wedge_probe_timeout_s", 120.0))

    def _default_probe(self, mesh) -> bool:
        import jax
        import jax.numpy as jnp
        fault.fire("probe", mesh=mesh)
        vals = [jax.device_put(jnp.zeros(()), d) + 1
                for d in mesh.flat_devices]
        jax.block_until_ready(vals)
        return True

    def probe_one(self, mesh) -> str:
        """One mesh's verdict: ``ok`` / ``wedged`` / ``dead``."""
        probe = self._probe or self._default_probe
        # No context manager: a genuinely wedged device never finishes
        # the probe and pool.__exit__ would join it forever — the
        # abandoned daemon thread IS the wedge signal (same discipline
        # as monitoring.check_alive).
        pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        fut = pool.submit(probe, mesh)
        try:
            ok = bool(fut.result(timeout=self._timeout()))
        except concurrent.futures.TimeoutError:
            return "wedged"
        except Exception:  # pylint: disable=broad-except
            return "dead"
        finally:
            pool.shutdown(wait=False)
        return "ok" if ok else "dead"

    def check(self) -> Dict[int, str]:
        """Probe the mesh group, stopping at the first wedge sign.
        ``fault.fire("wedge_detected")`` at entry is the injection
        point: an active FaultSpec raises here to simulate a wedge."""
        group = list(self.mesh_group or [])
        fault.fire("wedge_detected", n_meshes=len(group))
        statuses: Dict[int, str] = {}
        tripped = False
        for i, mesh in enumerate(group):
            if tripped:
                statuses[i] = "skipped"
                continue
            statuses[i] = self.probe_one(mesh)
            if statuses[i] != "ok":
                tripped = True
                logger.warning("wedge detector: mesh %d is %s — "
                               "stopping the sweep (runbook discipline: "
                               "never probe past a wedge)", i,
                               statuses[i])
        return statuses

    def healthy(self) -> bool:
        return all(s == "ok" for s in self.check().values())


#: the process's supervisor (set by ElasticSupervisor unless
#: ``register_globally=False``) — serve/healthz reads it
_ACTIVE: Optional["ElasticSupervisor"] = None


def get_supervisor() -> Optional["ElasticSupervisor"]:
    return _ACTIVE


def status_report() -> Optional[Dict[str, Any]]:
    """Elastic episode state for ``/healthz`` (None when no supervisor
    is registered in this process)."""
    sup = _ACTIVE
    if sup is None:
        return None
    last = sup.episodes[-1] if sup.episodes else None
    return {
        "step": sup.step_index,
        "devices": len(sup.devices),
        "episodes": len(sup.episodes),
        "recovering": bool(sup._in_episode),
        "last_episode": dict(last) if last else None,
    }


class ElasticSupervisor:
    """Owns a training loop's failure lifecycle (module docstring).

    ``solve(devices)`` is the re-solve hook: given a device list it
    returns a compiled-on-demand step callable (typically an
    ``@alpa_tpu.parallelize`` function over a ``ParallelMethod`` built
    for those devices) with the convention ``fn(state, *args) ->
    (new_state, *aux)``.  It is called once at construction for the
    full device set and once per episode for the survivors; returning a
    cached function for a device set it has already solved is
    encouraged (the acceptance gate then records a ``reused`` replan).

    ``manager`` is a :class:`~alpa_tpu.checkpoint.manager
    .CheckpointManager` (built over ``checkpoint_root`` when omitted,
    synchronous — elastic durability must not gamble on a write in
    flight).  A step-0 restore point is committed at construction so
    even a first-step failure has a verified floor to fall back to.

    Thread model: episodes run only on the training thread, inside
    :meth:`step`.  Cross-thread signals — ``notify_worker_lost``,
    ``notify_preemption``, watchdog ``escalate`` — enqueue and are
    drained at the next step boundary.
    """

    def __init__(self, solve: Callable[[Sequence[Any]], Callable],
                 state: Any,
                 checkpoint_root: Optional[str] = None,
                 devices: Optional[Sequence[Any]] = None,
                 manager: Optional[Any] = None,
                 wedge_detector: Optional[WedgeDetector] = None,
                 step_budget: Optional[int] = None,
                 time_budget_s: Optional[float] = None,
                 grace_period_s: Optional[float] = None,
                 quiesce_timeout_s: Optional[float] = None,
                 snapshot_interval: Optional[int] = None,
                 max_step_attempts: int = 3,
                 register_globally: bool = True):
        if manager is None:
            if checkpoint_root is None:
                raise ValueError(
                    "ElasticSupervisor needs a CheckpointManager or a "
                    "checkpoint_root to build one")
            from alpa_tpu.checkpoint.manager import CheckpointManager
            manager = CheckpointManager(checkpoint_root, async_save=False)
        if devices is None:
            import jax
            devices = jax.devices()
        self.solve = solve
        self.state = state
        self.manager = manager
        self.devices: List[Any] = list(devices)
        self.wedge_detector = wedge_detector
        self.step_budget = (step_budget if step_budget is not None else
                            global_config.elastic_step_budget)
        self.time_budget_s = (time_budget_s if time_budget_s is not None
                              else global_config.elastic_time_budget_s)
        self.grace_period_s = (grace_period_s if grace_period_s is not None
                               else global_config.elastic_grace_period_s)
        self.quiesce_timeout_s = (
            quiesce_timeout_s if quiesce_timeout_s is not None
            else global_config.elastic_quiesce_timeout_s)
        self.snapshot_interval = max(1, (
            snapshot_interval if snapshot_interval is not None
            else global_config.elastic_snapshot_interval))
        self.max_step_attempts = max(1, max_step_attempts)

        self.step_index = 0
        #: completed episode records, oldest first (JSON-able dicts)
        self.episodes: List[Dict[str, Any]] = []
        self._step_fn = solve(self.devices)
        self._baseline_findings: Optional[frozenset] = None
        self._mid_step = False
        self._in_episode = False
        self._last_args: Optional[tuple] = None
        self._signals: List[Dict[str, Any]] = []
        self._signal_lock = threading.Lock()

        # step-0 restore point: a failure before the first periodic
        # snapshot still has a verified floor
        if self.manager.latest_step() is None:
            self.manager.save(0, self.state,
                              plan_fingerprint=self._fingerprint(),
                              meta={"reason": "elastic_initial"},
                              sync=True)
            self.manager.wait()

        if register_globally:
            global _ACTIVE
            _ACTIVE = self
            fault.set_escalation_manager(self)
        _ELASTIC_STATE.set(0)

    # -- plumbing ------------------------------------------------------

    def _executable(self):
        get = getattr(self._step_fn, "get_last_executable", None)
        return get() if get is not None else None

    def _fingerprint(self) -> Optional[str]:
        ex = self._executable()
        get = getattr(ex, "get_plan_fingerprint", None)
        try:
            return get() if get is not None else None
        except Exception:  # pylint: disable=broad-except
            return None

    @staticmethod
    def _findings_of(ex) -> frozenset:
        """The plan verdict's findings as comparable (analysis, code)
        pairs; empty for executables without a verifier (shard-parallel
        paths) or with verification off."""
        get = getattr(ex, "get_plan_verdict", None)
        if get is None:
            return frozenset()
        try:
            verdict = get()
        except Exception:  # pylint: disable=broad-except
            logger.exception("elastic: plan verdict unavailable")
            return frozenset()
        if verdict is None:
            return frozenset()
        return frozenset((f.analysis, f.code) for f in verdict.findings())

    # -- external signals ---------------------------------------------

    def notify_worker_lost(self,
                           survivors: Optional[Sequence[Any]] = None):
        """Queue a worker-loss event (thread-safe); the episode runs at
        the next step boundary."""
        self._signal("worker_lost", WorkerLost(survivors=survivors))

    def notify_preemption(self, grace_s: Optional[float] = None,
                          survivors: Optional[Sequence[Any]] = None):
        """Queue a preemption notice (thread-safe)."""
        self._signal("preemption_notice",
                     PreemptionNotice(grace_s=grace_s, survivors=survivors))

    def escalate(self, site: str, error: BaseException):
        """``fault.set_escalation_manager`` target: elastic-site retry
        exhaustion becomes a queued lifecycle event."""
        self._signal(site if site in fault.ELASTIC_SITES
                     else "step_failure", error)

    def _signal(self, reason: str, error: BaseException):
        with self._signal_lock:
            self._signals.append({"reason": reason, "error": error})
        logger.warning("elastic: queued %s signal (%s)", reason, error)

    def _drain_signals(self):
        while True:
            with self._signal_lock:
                if not self._signals:
                    return
                sig = self._signals.pop(0)
            self._run_episode(sig["reason"], error=sig["error"])

    def _poll_sites(self):
        """The step-boundary instrumentation for the elastic fault
        sites: with no active FaultPlan both fire() calls are near-zero
        no-ops; an injected spec raises and becomes a queued signal —
        exactly how a real preemption notice or scheduler callback
        arrives."""
        for site in ("preemption_notice", "worker_lost"):
            try:
                fault.fire(site, step=self.step_index,
                           devices=len(self.devices))
            except Exception as e:  # pylint: disable=broad-except
                self._signal(site, e)

    # -- the supervised step ------------------------------------------

    def step(self, *args):
        """Run one training step under supervision: polls the elastic
        sites, drains queued signals (running their episodes), executes
        ``step_fn(state, *args)``, advances ``state``/``step_index``,
        and snapshots every ``snapshot_interval`` steps.  A failing
        step triggers an episode and is replayed (bounded by
        ``max_step_attempts``).  Returns the step's aux outputs (the
        loss for the usual ``(state, loss)`` convention)."""
        self._last_args = args
        self._poll_sites()
        self._drain_signals()
        attempts = 0
        while True:
            attempts += 1
            try:
                self._mid_step = True
                out = self._step_fn(self.state, *args)
                self._mid_step = False
                break
            except Exception as e:  # pylint: disable=broad-except
                if attempts >= self.max_step_attempts:
                    self._mid_step = False
                    raise
                reason, error = self._classify(e)
                self._run_episode(reason, error=error)
        if not (isinstance(out, tuple) and len(out) >= 1):
            raise TypeError(
                "elastic step functions must return (new_state, *aux); "
                f"got {type(out).__name__}")
        self.state = out[0]
        aux = out[1:]
        self.step_index += 1
        if self._baseline_findings is None:
            self._baseline_findings = self._findings_of(self._executable())
        if self.step_index % self.snapshot_interval == 0:
            self.manager.save(self.step_index, self.state,
                              plan_fingerprint=self._fingerprint(),
                              sync=True)
            self.manager.wait()
        return aux[0] if len(aux) == 1 else aux

    def _classify(self, e: BaseException):
        """Map a step failure to an episode reason.  Typed elastic
        errors name themselves; anything else consults the wedge
        detector (probe timeout taxonomy) before falling back to the
        generic ``step_failure``."""
        if isinstance(e, WorkerLost):
            return "worker_lost", e
        if isinstance(e, PreemptionNotice):
            return "preemption_notice", e
        if self.wedge_detector is not None:
            try:
                statuses = self.wedge_detector.check()
            except Exception as we:  # pylint: disable=broad-except
                # the wedge_detected injection point fired
                return "wedge_detected", we
            if any(s != "ok" for s in statuses.values()):
                return "wedge_detected", e
        return "step_failure", e

    # -- the episode ---------------------------------------------------

    def _run_episode(self, reason: str, error: Optional[BaseException]
                     = None) -> Dict[str, Any]:
        """Quiesce -> snapshot -> re-solve (gated) -> restore -> resume.
        Never raises: a failed phase degrades to the rollback path (old
        plan + last verified checkpoint)."""
        t0 = time.monotonic()
        self._in_episode = True
        _ELASTIC_STATE.set(1)
        _EPISODES.labels(reason).inc()
        survivors = getattr(error, "survivors", None)
        grace_s = getattr(error, "grace_s", None)
        ep: Dict[str, Any] = {
            "reason": reason,
            "error": f"{type(error).__name__}: {error}" if error else None,
            "step_at_failure": self.step_index,
            "mid_step": self._mid_step,
        }
        _flight.annotate("elastic_episode", {
            "reason": reason, "step": self.step_index,
            "phase": "detected"})
        _flight.auto_dump(f"elastic episode: {reason}")
        old_ex = self._executable()
        try:
            ep.update(self._episode_body(reason, survivors, grace_s))
        except Exception:  # pylint: disable=broad-except
            logger.exception("elastic episode body failed; resuming on "
                             "the old plan")
            ep["episode_error"] = True
        finally:
            # reopen the old executable's launch gate whatever happened:
            # a rolled-back (or crashed) episode keeps training on it
            if old_ex is not None and hasattr(old_ex, "resume"):
                try:
                    old_ex.resume()
                except Exception:  # pylint: disable=broad-except
                    logger.exception("elastic: resume of old "
                                     "executable failed")
            self._mid_step = False
            self._in_episode = False
            _ELASTIC_STATE.set(0)
        ep["seconds"] = round(time.monotonic() - t0, 6)
        ep["within_time_budget"] = ep["seconds"] <= self.time_budget_s
        if not ep["within_time_budget"]:
            _BUDGET_VIOLATIONS.labels("seconds").inc()
        _RECOVERY_SECONDS.observe(ep["seconds"])
        self.episodes.append(ep)
        _flight.annotate("elastic_episode", dict(ep))
        logger.warning(
            "elastic episode done: %s at step %d -> restored step %s, "
            "replan %s, %.3fs (budgets: steps %s, time %s)", reason,
            ep["step_at_failure"], ep.get("restored_step"),
            ep.get("replan"), ep["seconds"],
            "ok" if ep.get("within_step_budget", True) else "EXCEEDED",
            "ok" if ep["within_time_budget"] else "EXCEEDED")
        return ep

    def _episode_body(self, reason: str,
                      survivors: Optional[Sequence[Any]],
                      grace_s: Optional[float]) -> Dict[str, Any]:
        ep: Dict[str, Any] = {}
        # 1. quiesce: close the launch gate, drain in-flight work
        old_ex = self._executable()
        if old_ex is not None and hasattr(old_ex, "quiesce"):
            ep["quiesced"] = bool(old_ex.quiesce(self.quiesce_timeout_s))
        else:
            ep["quiesced"] = None
        _flight.annotate("elastic_episode", {
            "reason": reason, "phase": "quiesced"})

        # 2. snapshot
        ep["snapshot"] = self._snapshot_phase(reason, grace_s, ep)

        # 3. restore target: the last hash-verified step (a torn or
        # bit-rotted newest step falls through to the one before it)
        restored_step = self.manager.last_verified_step()
        restored = None
        if restored_step is not None:
            # cross-plan restore by design: no expected fingerprint —
            # ShardStore.read_leaf_slice reassembles saved shards into
            # whatever layout the surviving plan wants, bitwise
            restored = self.manager.restore(self.state,
                                            step=restored_step)
        ep["restored_step"] = restored_step

        # 4. re-solve for the survivors, gated on the plan verdict
        new_devices = (list(survivors) if survivors is not None
                       else list(self.devices))
        ep["devices_before"] = len(self.devices)
        ep["devices_after"] = len(new_devices)
        template = restored if restored is not None else self.state
        ep["replan"] = self._resolve_phase(new_devices, template)

        # 5. resume position: roll the loop back to the restored step
        if restored is not None:
            replay = max(0, self.step_index - restored_step)
            self.state = restored
            self.step_index = restored_step
        else:
            logger.warning("elastic: no verified checkpoint to restore "
                           "— continuing with the live state")
            replay = 0
        ep["replay_steps"] = replay
        ep["within_step_budget"] = replay <= self.step_budget
        if not ep["within_step_budget"]:
            _BUDGET_VIOLATIONS.labels("steps").inc()
        _REPLAY_STEPS.observe(float(replay))
        return ep

    def _snapshot_phase(self, reason: str, grace_s: Optional[float],
                        ep: Dict[str, Any]) -> str:
        """Durable snapshot of the live state — unless the failure was
        mid-step, in which case the state is torn (donated buffers may
        already be freed) and the episode falls back to the last
        verified checkpoint."""
        if self._mid_step:
            _SNAPSHOTS.labels("skipped").inc()
            return "skipped"
        grace = grace_s if grace_s is not None else self.grace_period_s
        t0 = time.monotonic()
        try:
            if self.manager.latest_step() != self.step_index:
                self.manager.save(self.step_index, self.state,
                                  plan_fingerprint=self._fingerprint(),
                                  meta={"reason": f"elastic_{reason}"},
                                  sync=True)
                self.manager.wait()
        except Exception:  # pylint: disable=broad-except
            logger.exception("elastic snapshot failed; falling back to "
                             "the last verified checkpoint")
            _SNAPSHOTS.labels("failed").inc()
            return "failed"
        took = time.monotonic() - t0
        if reason == "preemption_notice":
            hit = took <= grace
            ep["snapshot_before_kill"] = hit
            ep["snapshot_seconds"] = round(took, 6)
            outcome = "grace" if hit else "late"
        else:
            outcome = "boundary"
        _SNAPSHOTS.labels(outcome).inc()
        return outcome

    def _resolve_phase(self, new_devices: List[Any],
                       template: Any) -> str:
        """Re-solve + acceptance gate.  Compiles the candidate plan
        (no launch), compares its full verdict findings against the old
        plan's baseline, and hot-swaps only when nothing new appeared;
        otherwise rolls back to the old plan."""
        try:
            candidate = self.solve(new_devices)
        except Exception:  # pylint: disable=broad-except
            logger.exception("elastic re-solve failed; keeping the "
                             "old plan")
            _REPLANS.labels("failed").inc()
            return "failed"
        if candidate is self._step_fn:
            # solve() memoizes per device set: same plan, nothing to gate
            self.devices = new_devices
            _REPLANS.labels("reused").inc()
            return "reused"
        cand_ex = None
        if self._last_args is not None:
            try:
                candidate.get_executable(template, *self._last_args)
                cand_ex = candidate.get_last_executable()
            except Exception:  # pylint: disable=broad-except
                logger.exception("elastic: candidate plan failed to "
                                 "compile; rolling back")
                _REPLANS.labels("rejected").inc()
                return "rejected"
        baseline = (self._baseline_findings
                    if self._baseline_findings is not None
                    else self._findings_of(self._executable()))
        fresh = self._findings_of(cand_ex) - baseline
        if fresh:
            logger.warning(
                "elastic: candidate plan REJECTED — %d new verifier "
                "finding(s) vs the old plan: %s; rolling back to the "
                "old plan + last verified checkpoint", len(fresh),
                sorted(f"{a}:{c}" for a, c in fresh))
            _REPLANS.labels("rejected").inc()
            return "rejected"
        self._step_fn = candidate
        self.devices = new_devices
        self._baseline_findings = self._findings_of(cand_ex)
        _REPLANS.labels("accepted").inc()
        return "accepted"
