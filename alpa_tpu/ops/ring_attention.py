"""Ring attention: sequence-parallel attention over a mesh axis.

NEW capability vs the reference (SURVEY.md §2.7/§5: Alpa has no sequence /
context parallelism).  The sequence dim of q/k/v is sharded over a mesh
axis; each device keeps its q shard and the k/v shards rotate around the
ring with ``lax.ppermute`` (compiled to ICI neighbor exchanges that XLA
overlaps with the per-block attention compute).  Softmax statistics are
combined online across ring steps, so the result is exact attention over
the full sequence with per-device memory O(S/ring) — long-context training
scales with the ring size.

Causality with sequence sharding: chunk j of k/v attends to q chunk i as
  j <  i : full (unmasked) block
  j == i : causal block
  j >  i : fully masked (skipped via zero-weight contribution)

Used inside shard_map (manual axis) — see ``make_ring_attention_fn`` for a
GPT-pluggable closure — and differentiable end to end (the transpose of
ppermute is the reverse rotation, giving the standard ring-attention
backward communication pattern for free).
"""
import functools
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG_INF = -1e9


def _local_attention_stats(q, k, v, mask_mode: int, q_chunk: int,
                           k_chunk: int, chunk_len: int):
    """Blockwise attention returning (numerator, row-max, row-sum).

    q: (B, Sq, H, D); k/v: (B, Sk, H, D).
    mask_mode: 0 = full, 1 = causal-with-offset, 2 = masked-out.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    s = s / np.sqrt(d)
    if mask_mode == 1:
        sq, sk = q.shape[1], k.shape[1]
        q_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                          # (B,H,Sq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                          # (B,H,Sq)
    num = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return num, m, l


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True):
    """Exact attention with sequence sharded over ``axis_name``.

    Must be called inside a shard_map manual over ``axis_name``; q/k/v are
    the local sequence shards (B, S_local, H, D).
    """
    axis_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape

    # ring: step t processes the k/v chunk originally from rank
    # (my_idx - t) mod n, then forwards its current chunk to rank+1.
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, t):
        k_cur, v_cur, acc, m_acc, l_acc = carry
        src = (my_idx - t) % axis_size

        def blockwise(mode):
            return _local_attention_stats(q, k_cur, v_cur, mode, 0, 0,
                                          s_local)

        if causal:
            num_f, m_f, l_f = blockwise(0)   # unmasked
            num_c, m_c, l_c = blockwise(1)   # causal diagonal
            is_diag = src == my_idx
            keep = src < my_idx
            num = jnp.where(is_diag, num_c,
                            jnp.where(keep, num_f, jnp.zeros_like(num_f)))
            m = jnp.where(is_diag, m_c,
                          jnp.where(keep, m_f,
                                    jnp.full_like(m_f, NEG_INF)))
            l = jnp.where(is_diag, l_c,
                          jnp.where(keep, l_f, jnp.zeros_like(l_f)))
        else:
            num, m, l = blockwise(0)

        # online combine
        m_new = jnp.maximum(m_acc, m)
        alpha_acc = jnp.exp(m_acc - m_new)
        alpha_cur = jnp.exp(m - m_new)
        l_new = l_acc * alpha_acc + l * alpha_cur
        # acc: (B, Sq, H, D); alphas: (B, H, Sq) -> transpose
        a_acc = alpha_acc.transpose(0, 2, 1)[..., None]
        a_cur = alpha_cur.transpose(0, 2, 1)[..., None]
        acc = acc * a_acc + num.astype(jnp.float32) * a_cur
        # rotate k/v to the next rank
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, acc, m_new, l_new), None

    acc0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    m0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    (k_f, v_f, acc, m_f, l_f), _ = lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(axis_size))
    l_f = jnp.maximum(l_f, 1e-20).transpose(0, 2, 1)[..., None]
    return (acc / l_f).astype(q.dtype)


def make_ring_attention_fn(mesh, sp_axis: str):
    """Build an attention fn (q, k, v, causal=...) -> out that runs ring
    attention with the sequence dim sharded over ``sp_axis``.

    Plugs into ``GPTConfig(attention_impl='ring', sp_axis=...)``: shard_map
    manual over the sp axis only; batch/head dims stay automatic.
    """
    from jax.sharding import PartitionSpec as P

    def attention(q, k, v, *, causal: bool = True, offset: int = 0):
        del offset

        def inner(q_, k_, v_):
            return ring_attention(q_, k_, v_, axis_name=sp_axis,
                                  causal=causal)

        sm = jax.shard_map(inner,
                           mesh=mesh,
                           in_specs=(P(None, sp_axis), P(None, sp_axis),
                                     P(None, sp_axis)),
                           out_specs=P(None, sp_axis),
                           axis_names={sp_axis},
                           check_vma=False)
        return sm(q, k, v)

    return attention
