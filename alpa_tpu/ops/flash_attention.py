"""Flash attention forward kernel in pallas (TPU), with recompute backward.

Blocked online-softmax attention: the q-block stays in VMEM, k/v stream
block by block, and the softmax normalizer is maintained incrementally —
the S x S score matrix never materializes in HBM.  Grid: (batch*heads,
q blocks); k/v for one (batch, head) are VMEM-resident (fine for the
moderate per-chip sequence lengths this kernel targets; longer sequences
are handled by sharding the sequence with ring attention, which calls this
kernel per block).

Backward: ``jax.custom_vjp`` recomputes attention with the einsum reference
implementation and differentiates that — the standard remat-style tradeoff
(saves the O(S^2) residuals; XLA fuses the recomputed backward well).
"""
import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e9


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                      causal: bool, sm_scale: float, q_offset: int):
    """One (batch*head, q-block) program instance.

    q_ref: (block_q, d); k_ref/v_ref: (s_k, d); o_ref: (block_q, d).
    """
    block_q, d = q_ref.shape
    s_k = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32) * sm_scale

    q_blk = pl.program_id(1)
    q_start = q_blk * block_q + q_offset

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = pl.cdiv(s_k, block_k)

    def body(kb, carry):
        m_prev, l_prev, acc = carry
        k_start = kb * block_k
        k_blk = k_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_start + lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 0)
            k_pos = k_start + lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    if causal:
        # skip fully-masked k blocks beyond the diagonal
        last_needed = lax.div(q_start + block_q - 1, block_k) + 1
        n_iter = jnp.minimum(last_needed, num_k_blocks)
    else:
        n_iter = num_k_blocks
    m, l, acc = lax.fori_loop(0, n_iter, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-20)
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal: bool, q_offset: int = 0,
                   block_q: int = 256, block_k: int = 256,
                   interpret: bool = None):
    """q: (B, Sq, H, D); k/v: (B, Sk, H, D) -> (B, Sq, H, D)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    sm_scale = 1.0 / np.sqrt(d)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)

    # (B, Sq, H, D) -> (B*H, Sq, D)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    grid = (b * h, pl.cdiv(sq, block_q))
    out = pl.pallas_call(
        partial(_flash_fwd_kernel, block_k=block_k, causal=causal,
                sm_scale=sm_scale, q_offset=q_offset),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention(q, k, v, causal, q_offset):
    return _flash_forward(q, k, v, causal=causal, q_offset=q_offset)


def _flash_fwd_rule(q, k, v, causal, q_offset):
    out = _flash_forward(q, k, v, causal=causal, q_offset=q_offset)
    return out, (q, k, v)


def _flash_bwd_rule(causal, q_offset, res, do):
    from alpa_tpu.model.gpt_model import reference_attention
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: reference_attention(q_, k_, v_, causal=causal,
                                               offset=q_offset), q, k, v)
    return vjp(do)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, *, causal: bool = True, offset: int = 0):
    """Drop-in replacement for ``reference_attention`` (gpt_model.py)."""
    return _flash_attention(q, k, v, causal, offset)
