"""Flash attention forward kernels in pallas (TPU), with recompute backward.

Blocked online-softmax attention: the q-block stays in VMEM, the softmax
normalizer is maintained incrementally, and the S x S score matrix never
materializes in HBM.  Two forward paths, picked by k/v size:

* **resident** (short sequences): k/v for one (batch, head) live in VMEM;
  grid (B*H, q blocks) with a fori_loop over k blocks and causal
  early-exit.
* **streaming** (k/v > ~4MB): grid (B*H, q blocks, k blocks) — k/v blocks
  stream from HBM via BlockSpec index maps, the (m, l, acc) state persists
  in VMEM scratch across the sequential innermost grid dim, and causal
  blocks above the diagonal are skipped with ``pl.when``.  Per-chip
  sequence length is then HBM-bound, and ring attention shards beyond
  that.

Backward: real pallas kernels in the VMEM-resident regime — the standard
two-kernel flash backward (dq over q blocks; dk/dv over k blocks) off the
saved (out, logsumexp) residuals, never materializing S x S scores.  In
the HBM-streaming regime (k/v beyond the VMEM budget) the backward falls
back to q-chunked recompute with the einsum reference implementation —
the remat-style tradeoff (XLA fuses the recomputed backward well).
"""
import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9


def _online_softmax_update(q, k_blk, v_blk, m_prev, l_prev, acc, *,
                           causal: bool, q_start, k_start):
    """One flash-attention block update, shared by both kernels:
    (m, l, acc) -> (m', l', acc') after attending q to one k/v block."""
    block_q = q.shape[0]
    block_k = k_blk.shape[0]
    s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if causal:
        q_pos = q_start + lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 0)
        k_pos = k_start + lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    m_cur = jnp.max(s, axis=1)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new[:, None])
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=1)
    acc_new = acc * alpha[:, None] + jax.lax.dot_general(
        p, v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_k: int, causal: bool, sm_scale: float,
                      q_offset: int):
    """One (batch*head, q-block) program instance.

    q_ref: (block_q, d); k_ref/v_ref: (s_k, d); o_ref: (block_q, d);
    lse_ref: (block_q,) — per-row logsumexp of the scaled scores, the
    residual the backward kernels reconstruct P from.
    """
    block_q, d = q_ref.shape
    s_k = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32) * sm_scale

    q_blk = pl.program_id(1)
    q_start = q_blk * block_q + q_offset

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = pl.cdiv(s_k, block_k)

    def body(kb, carry):
        m_prev, l_prev, acc = carry
        k_start = kb * block_k
        k_blk = k_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        return _online_softmax_update(q, k_blk, v_blk, m_prev, l_prev,
                                      acc, causal=causal, q_start=q_start,
                                      k_start=k_start)

    if causal:
        # skip fully-masked k blocks beyond the diagonal
        last_needed = lax.div(q_start + block_q - 1, block_k) + 1
        n_iter = jnp.minimum(last_needed, num_k_blocks)
    else:
        n_iter = num_k_blocks
    m, l, acc = lax.fori_loop(0, n_iter, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-20)
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l)


# above this many k/v bytes per (batch, head), stream blocks from HBM
# instead of keeping k/v VMEM-resident (VMEM is ~16MB/core)
VMEM_RESIDENT_LIMIT = 4 * 1024 * 1024


def _flash_streaming_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref,
                            l_ref, acc_ref, *, causal: bool,
                            sm_scale: float, q_offset: int, nk: int,
                            block_q: int, block_k: int):
    """Grid (B*H, q blocks, k blocks): k/v blocks stream from HBM; the
    online-softmax state (m, l, acc) lives in VMEM scratch that persists
    across the sequential innermost grid dim."""
    kb = pl.program_id(2)
    qb = pl.program_id(1)

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qb * block_q + q_offset
    k_start = kb * block_k
    # blocks entirely above the diagonal contribute nothing (their DMA is
    # also suppressed by the clamped k index map in _flash_forward)
    run = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[:].astype(jnp.float32) * sm_scale
        k_blk = k_ref[:].astype(jnp.float32)
        v_blk = v_ref[:].astype(jnp.float32)
        m_new, l_new, acc_new = _online_softmax_update(
            q, k_blk, v_blk, m_ref[:], l_ref[:], acc_ref[:],
            causal=causal, q_start=q_start, k_start=k_start)
        m_ref[:] = m_new
        l_ref[:] = l_new
        acc_ref[:] = acc_new

    @pl.when(kb == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], 1e-20)
        o_ref[:] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)
        lse_ref[:] = m_ref[:] + jnp.log(l)


def _pick_block(size: int, target: int) -> int:
    """Largest divisor of ``size`` not exceeding ``target`` — blocks must
    tile the sequence exactly (no partial-block masking implemented)."""
    b = min(target, size)
    while size % b != 0:
        b -= 1
    return b


def _flash_forward(q, k, v, *, causal: bool, q_offset: int = 0,
                   block_q: int = 256, block_k: int = 256,
                   interpret: bool = None):
    """q: (B, Sq, H, D); k/v: (B, Sk, H, D) -> (out (B, Sq, H, D),
    lse (B*H, Sq))."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    sm_scale = 1.0 / np.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)

    # (B, Sq, H, D) -> (B*H, Sq, D)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    kv_bytes = 2 * sk * d * k.dtype.itemsize
    if kv_bytes > VMEM_RESIDENT_LIMIT:
        # long-sequence path: stream k/v blocks, carry softmax state in
        # scratch across the innermost (sequential) grid dim
        nk = sk // block_k
        grid = (b * h, sq // block_q, nk)
        if causal:
            # clamp the k index for fully-masked blocks to the last needed
            # block: pl.when skips their compute, and the clamp means no
            # fresh DMA is issued for them either (the previous block's
            # buffer is reused) — saves ~half the k/v HBM traffic
            def kv_index(i, j, kb):
                last_needed = (j * block_q + block_q - 1 + q_offset) \
                    // block_k
                return (i, jnp.minimum(kb, last_needed), 0)
        else:
            def kv_index(i, j, kb):
                return (i, kb, 0)
        out, lse = pl.pallas_call(
            partial(_flash_streaming_kernel, causal=causal,
                    sm_scale=sm_scale, q_offset=q_offset, nk=nk,
                    block_q=block_q, block_k=block_k),
            out_shape=(jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
                       jax.ShapeDtypeStruct((b * h, sq), jnp.float32)),
            grid=grid,
            in_specs=[
                pl.BlockSpec((None, block_q, d),
                             lambda i, j, kb: (i, j, 0)),
                pl.BlockSpec((None, block_k, d), kv_index),
                pl.BlockSpec((None, block_k, d), kv_index),
            ],
            out_specs=(
                pl.BlockSpec((None, block_q, d),
                             lambda i, j, kb: (i, j, 0)),
                pl.BlockSpec((None, block_q), lambda i, j, kb: (i, j)),
            ),
            scratch_shapes=[
                pltpu.VMEM((block_q,), jnp.float32),
                pltpu.VMEM((block_q,), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            interpret=interpret,
        )(qt, kt, vt)
        return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3), lse

    grid = (b * h, pl.cdiv(sq, block_q))
    out, lse = pl.pallas_call(
        partial(_flash_fwd_kernel, block_k=block_k, causal=causal,
                sm_scale=sm_scale, q_offset=q_offset),
        out_shape=(jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, sq), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q), lambda i, j: (i, j)),
        ),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3), lse


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool,
                         sm_scale: float, q_offset: int):
    """dq for one (batch*head, q-block): loop over k/v blocks up to the
    diagonal.  P is rebuilt from the saved logsumexp; delta is the
    precomputed rowsum(dO * O)."""
    block_q, d = q_ref.shape
    s_k = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]
    delta = delta_ref[:]
    q_start = pl.program_id(1) * block_q + q_offset

    def body(kb, dq_acc):
        k_start = kb * block_k
        k_blk = k_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = sm_scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq_acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    num_k_blocks = pl.cdiv(s_k, block_k)
    if causal:
        last_needed = lax.div(q_start + block_q - 1, block_k) + 1
        n_iter = jnp.minimum(last_needed, num_k_blocks)
    else:
        n_iter = num_k_blocks
    dq = lax.fori_loop(0, n_iter, body,
                       jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = (dq * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          sm_scale: float, q_offset: int):
    """dk/dv for one (batch*head, k-block): loop over q blocks from the
    diagonal down."""
    block_k, d = k_ref.shape
    s_q = q_ref.shape[0]
    k_blk = k_ref[:].astype(jnp.float32)
    v_blk = v_ref[:].astype(jnp.float32)
    k_start = pl.program_id(1) * block_k

    def body(qb, carry):
        dk_acc, dv_acc = carry
        q_start_local = qb * block_q
        q_start = q_start_local + q_offset
        q = q_ref[pl.ds(q_start_local, block_q), :].astype(jnp.float32)
        do = do_ref[pl.ds(q_start_local, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(q_start_local, block_q)]
        delta = delta_ref[pl.ds(q_start_local, block_q)]
        s = sm_scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    num_q_blocks = pl.cdiv(s_q, block_q)
    if causal:
        # the first q block whose rows can see this k block
        first = lax.div(jnp.maximum(k_start - q_offset, 0), block_q)
    else:
        first = 0
    z = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = lax.fori_loop(first, num_q_blocks, body, (z, z))
    dk_ref[:] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_backward_kernels(q, k, v, out, lse, do, *, causal: bool,
                            q_offset: int, block_q: int = 256,
                            block_k: int = 256, interpret: bool = None):
    """Two-pass flash backward (dq; dk/dv), VMEM-resident regime."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    sm_scale = 1.0 / np.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)

    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    dot = do.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    ot = out.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    # delta = rowsum(dO * O): cheap elementwise reduce, XLA-fused
    delta = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1)

    full = lambda i, j: (i, 0, 0)  # noqa: E731
    full1 = lambda i, j: (i, 0)    # noqa: E731
    blk = lambda i, j: (i, j, 0)   # noqa: E731
    blk1 = lambda i, j: (i, j)     # noqa: E731

    dq = pl.pallas_call(
        partial(_flash_bwd_dq_kernel, block_k=block_k, causal=causal,
                sm_scale=sm_scale, q_offset=q_offset),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), blk),       # q
            pl.BlockSpec((None, sk, d), full),           # k
            pl.BlockSpec((None, sk, d), full),           # v
            pl.BlockSpec((None, block_q, d), blk),       # do
            pl.BlockSpec((None, block_q), blk1),         # lse
            pl.BlockSpec((None, block_q), blk1),         # delta
        ],
        out_specs=pl.BlockSpec((None, block_q, d), blk),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    dk, dv = pl.pallas_call(
        partial(_flash_bwd_dkv_kernel, block_q=block_q, causal=causal,
                sm_scale=sm_scale, q_offset=q_offset),
        out_shape=(jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk, d), v.dtype)),
        grid=(b * h, sk // block_k),
        in_specs=[
            pl.BlockSpec((None, sq, d), full),           # q
            pl.BlockSpec((None, block_k, d), blk),       # k
            pl.BlockSpec((None, block_k, d), blk),       # v
            pl.BlockSpec((None, sq, d), full),           # do
            pl.BlockSpec((None, sq), full1),             # lse
            pl.BlockSpec((None, sq), full1),             # delta
        ],
        out_specs=(pl.BlockSpec((None, block_k, d), blk),
                   pl.BlockSpec((None, block_k, d), blk)),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    unt = lambda x, s: x.reshape(b, h, s, d).transpose(0, 2, 1, 3)  # noqa: E731
    return unt(dq, sq), unt(dk, sk), unt(dv, sk)


def _bwd_kernels_feasible(q, k) -> bool:
    """Static predicate: the dq kernel keeps k+v (and the dkv kernel
    q+do) resident per (batch, head) — beyond the VMEM budget the
    backward recomputes instead."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    itemsize = jnp.dtype(q.dtype).itemsize
    return max(2 * sk * d, 2 * sq * d) * itemsize <= VMEM_RESIDENT_LIMIT


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, q_offset, block_q, block_k):
    return _flash_forward(q, k, v, causal=causal, q_offset=q_offset,
                          block_q=block_q, block_k=block_k)[0]


def _flash_fwd_rule(q, k, v, causal, q_offset, block_q, block_k):
    out, lse = _flash_forward(q, k, v, causal=causal, q_offset=q_offset,
                              block_q=block_q, block_k=block_k)
    if _bwd_kernels_feasible(q, k):
        return out, (q, k, v, out, lse)
    # streaming regime: the recompute backward reads only (q, k, v) —
    # do not hold activation-sized out/lse residuals exactly where
    # memory is tightest
    return out, (q, k, v, None, None)


def _chunked_reference_attention(q, k, v, *, causal: bool, offset: int,
                                 chunk: int = 512):
    """Reference attention computed q-chunk-wise with lax.map: peak score
    memory is chunk x S instead of S x S, so the recompute backward stays
    feasible at the long sequence lengths the streaming forward unlocks."""
    from alpa_tpu.model.gpt_model import reference_attention
    b, s, h, d = q.shape
    if s % chunk != 0 or s <= chunk:
        return reference_attention(q, k, v, causal=causal, offset=offset)
    n = s // chunk
    qc = q.reshape(b, n, chunk, h, d).transpose(1, 0, 2, 3, 4)

    def one_chunk(args):
        i, q_i = args
        return reference_attention(q_i, k, v, causal=causal,
                                   offset=offset + i * chunk)

    outs = jax.lax.map(one_chunk, (jnp.arange(n), qc))
    return outs.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)


def _flash_bwd_rule(causal, q_offset, block_q, block_k, res, do):
    q, k, v, out, lse = res
    if out is not None:  # resident regime (see _flash_fwd_rule)
        return _flash_backward_kernels(q, k, v, out, lse, do,
                                       causal=causal, q_offset=q_offset,
                                       block_q=block_q, block_k=block_k)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _chunked_reference_attention(
            q_, k_, v_, causal=causal, offset=q_offset), q, k, v)
    return vjp(do)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, *, causal: bool = True, offset: int = 0,
                    block_q: int = 256, block_k: int = 256):
    """Drop-in replacement for ``reference_attention`` (gpt_model.py).
    ``block_q``/``block_k`` tune the kernel tiling (targets; clipped to
    divisors of the sequence lengths)."""
    return _flash_attention(q, k, v, causal, offset, block_q, block_k)
