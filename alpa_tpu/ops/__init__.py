"""TPU kernels (pallas) and kernel-backed ops.

New capability vs the reference (SURVEY.md §2.7: sequence parallelism is
ABSENT in Alpa): flash attention (VMEM-resident and HBM-streaming paths)
plus two sequence-parallel designs — ring attention (k/v rotation) and
Ulysses (all-to-all head redistribution) — make long-context training a
first-class citizen of this framework.
"""
from alpa_tpu.ops.flash_attention import flash_attention
from alpa_tpu.ops.ring_attention import (make_ring_attention_fn,
                                         ring_attention)
from alpa_tpu.ops.ulysses_attention import (make_ulysses_attention_fn,
                                            ulysses_attention)
