"""TPU kernels (pallas) and kernel-backed ops.

New capability vs the reference (SURVEY.md §2.7: sequence parallelism is
ABSENT in Alpa): flash attention and ring attention make long-context
training a first-class citizen of this framework.
"""
