"""Ulysses-style sequence parallelism: all-to-all head redistribution.

The second sequence-parallel design named in SURVEY.md §2.7 (alongside
ring attention): instead of rotating k/v around a ring, an all-to-all
converts sequence sharding into *head* sharding —

  in : q/k/v sharded over sequence     (B, S/n, H,   D)
  a2a: -> sharded over heads           (B, S,   H/n, D)
  attention per head group (full sequence visible locally)
  a2a: -> back to sequence sharding    (B, S/n, H,   D)

Two all-to-alls per attention instead of (ring-size - 1) permutes; better
when heads divide evenly by the axis and the sequence is very long (each
device sees the whole sequence for its heads, so any attention kernel —
including the pallas flash kernel — applies unchanged per shard).
"""
import logging
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

logger = logging.getLogger(__name__)


def _seq_to_heads(x, axis_name: str):
    """(B, S/n, H, D) local -> (B, S, H/n, D) local via tiled all-to-all."""
    n = lax.axis_size(axis_name)
    assert x.shape[2] % n == 0, (
        f"num_heads {x.shape[2]} not divisible by sp axis size {n}")
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _heads_to_seq(x, axis_name: str):
    """(B, S, H/n, D) local -> (B, S/n, H, D) local via tiled all-to-all."""
    n = lax.axis_size(axis_name)
    assert x.shape[1] % n == 0
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = True,
                      attn_fn=None):
    """Exact attention with sequence sharded over ``axis_name``.

    Call inside shard_map manual over ``axis_name``; q/k/v are local
    sequence shards (B, S_local, H, D).  ``attn_fn`` is any full-sequence
    attention (default: the einsum reference; pass flash_attention for the
    pallas kernel).
    """
    if attn_fn is None:
        from alpa_tpu.model.gpt_model import reference_attention
        attn_fn = partial(reference_attention)
    q = _seq_to_heads(q, axis_name)
    k = _seq_to_heads(k, axis_name)
    v = _seq_to_heads(v, axis_name)
    o = attn_fn(q, k, v, causal=causal)
    return _heads_to_seq(o, axis_name)


def make_ulysses_attention_fn(mesh, sp_axis: str, attn_fn=None):
    """Build an attention fn with Ulysses sequence parallelism over
    ``sp_axis`` (counterpart of ring_attention.make_ring_attention_fn)."""
    from jax.sharding import PartitionSpec as P

    def attention(q, k, v, *, causal: bool = True, offset: int = 0):
        del offset

        def inner(q_, k_, v_):
            return ulysses_attention(q_, k_, v_, axis_name=sp_axis,
                                     causal=causal, attn_fn=attn_fn)

        sm = jax.shard_map(inner,
                           mesh=mesh,
                           in_specs=(P(None, sp_axis), P(None, sp_axis),
                                     P(None, sp_axis)),
                           out_specs=P(None, sp_axis),
                           axis_names={sp_axis},
                           check_vma=False)
        return sm(q, k, v)

    return attention
