"""Test utilities: numeric comparison + model fixtures.

Analog of ref ``alpa/testing.py`` (SURVEY.md §4): the core oracle is
serial-vs-parallel numeric equivalence, plus structural assertions on
compiled HLO.
"""
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax.training import train_state

import alpa_tpu
from alpa_tpu.pipeline_parallel.primitive_def import mark_pipeline_boundary


def jax_version_tuple() -> tuple:
    """(major, minor, patch) of the installed jax, non-numeric tails
    dropped (``0.4.37.dev20241201`` -> (0, 4, 37))."""
    parts = []
    for p in jax.__version__.split("."):
        if not p.isdigit():
            break
        parts.append(int(p))
    return tuple(parts[:3])


#: True on the pinned old-jax toolchain (< 0.5).  A handful of tier-1
#: tests exercise behavior this jax/jaxlib cannot deliver (partial-auto
#: shard_map sharding rank propagation, cross-jit donation aliasing,
#: disjoint-mesh collectives in multi-controller mode, HLO text
#: spellings); they skip with a reason instead of failing, and run again
#: once the toolchain moves to a modern jax.
OLD_JAX = jax_version_tuple() < (0, 5, 0)


def skip_if_old_jax(reason: str):
    """``pytest.mark.skipif`` gated on the old-jax toolchain, tagged with
    the concrete jax limitation the test trips over."""
    import pytest
    return pytest.mark.skipif(
        OLD_JAX, reason=f"known jax {jax.__version__} limitation: {reason}")


def assert_allclose(x: Any, y: Any, rtol=1e-4, atol=1e-4):
    """Recursive pytree comparison (ref testing.py:28)."""
    if isinstance(x, dict):
        assert isinstance(y, dict) and set(x) == set(y)
        for k in x:
            assert_allclose(x[k], y[k], rtol, atol)
    elif isinstance(x, (tuple, list)):
        assert isinstance(y, (tuple, list)) and len(x) == len(y)
        for a, b in zip(x, y):
            assert_allclose(a, b, rtol, atol)
    elif hasattr(x, "__array__") or np.isscalar(x):
        assert hasattr(y, "__array__") or np.isscalar(y), f"{x} vs {y}"
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol, atol)
    elif x is None:
        assert y is None
    else:
        assert isinstance(y, type(x)) or isinstance(x, type(y))
        if hasattr(x, "__dict__"):
            assert_allclose(x.__dict__, y.__dict__, rtol, atol)


class MLPModel(nn.Module):
    """Simple MLP fixture (ref testing.py:54)."""
    hidden_dim: int
    output_dim: int
    num_layers: int = 2
    manual_pipeline_layer: bool = False

    @nn.compact
    def __call__(self, x):
        for i in range(self.num_layers):
            if self.manual_pipeline_layer and i == self.num_layers // 2:
                mark_pipeline_boundary()
            dim = (self.output_dim
                   if i == self.num_layers - 1 else self.hidden_dim)
            x = nn.Dense(features=dim)(x)
            if i != self.num_layers - 1:
                x = nn.relu(x)
        return x


def create_train_state(rngkey, model, inputs, learning_rate=1e-2):
    params = model.init(rngkey, *inputs)
    tx = optax.sgd(learning_rate=learning_rate, momentum=0.9)
    return train_state.TrainState.create(apply_fn=model.apply,
                                         params=params,
                                         tx=tx)


def create_mlp_train_state_and_batch(batch_size=64,
                                     input_dim=32,
                                     hidden_dim=32,
                                     output_dim=32,
                                     num_layers=2,
                                     manual_pipeline_layer=False):
    rngkey = jax.random.PRNGKey(0)
    x = jax.random.normal(rngkey, (batch_size, input_dim), jnp.float32)
    y = jax.random.normal(rngkey, (batch_size, output_dim), jnp.float32)
    model = MLPModel(hidden_dim=hidden_dim,
                     output_dim=output_dim,
                     num_layers=num_layers,
                     manual_pipeline_layer=manual_pipeline_layer)
    state = create_train_state(rngkey, model, [x])
    return state, {"x": x, "y": y}


def get_mlp_train_step(parallel_method=None, use_value_and_grad=False):
    """Build a train step; with a method -> parallelized, else plain jit."""

    def train_step(state, batch):

        def loss_func(params):
            out = state.apply_fn(params, batch["x"])
            return jnp.mean((out - batch["y"])**2)

        if parallel_method is not None:
            if use_value_and_grad:
                val, grads = alpa_tpu.value_and_grad(loss_func)(state.params)
            else:
                grads = alpa_tpu.grad(loss_func)(state.params)
                val = jnp.zeros((), jnp.float32)
        else:
            val, grads = jax.value_and_grad(loss_func)(state.params)
        new_state = state.apply_gradients(grads=grads)
        return new_state, val

    if parallel_method is not None:
        return alpa_tpu.parallelize(train_step, method=parallel_method)
    return jax.jit(train_step)


def data_loader_input_iter_func(start, end, batch_size):
    """Deterministic fake-data iterator used by data loader tests."""
    num = (end - start) // batch_size
    for i in range(num):
        yield (np.full((batch_size, 32), i, np.float32),
               np.full((batch_size,), i, np.int32))
