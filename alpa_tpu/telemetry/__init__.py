"""Unified telemetry layer (ISSUE 5).

Two pillars:

* :mod:`alpa_tpu.telemetry.trace` — thread-safe span tracing with
  Chrome-trace (Perfetto) export.  Zero-cost when off.
* :mod:`alpa_tpu.telemetry.metrics` — central Counter/Gauge/Histogram
  registry with Prometheus text exposition; every ad-hoc stat in the
  repo is a view over it.
* :mod:`alpa_tpu.telemetry.flight` — always-on flight recorder (ISSUE
  6): fixed-size lock-free ring of the last N instruction events,
  auto-dumped on step failure / fault fire / SUSPECT transition.
* :mod:`alpa_tpu.telemetry.perf` — post-step analysis (ISSUE 9):
  critical path, pipeline-bubble and MFU attribution over the recorded
  spans, published as ``alpa_stage_mfu``/``alpa_step_bubble_fraction``/
  ``alpa_critical_path_us``.

See docs/observability.md for the span model, category taxonomy and
knob table (``ALPA_TPU_TRACE`` / ``ALPA_TPU_TRACE_DIR`` /
``global_config.telemetry_*``).
"""
from alpa_tpu.telemetry.metrics import (       # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, DEFAULT_BUCKETS,
    get_registry, reset_registry)
from alpa_tpu.telemetry.trace import (         # noqa: F401
    CATEGORIES, TraceRecorder, begin, counter, enabled, end,
    get_recorder, instant, merge_chrome_traces, set_enabled,
    set_recorder, span)
from alpa_tpu.telemetry.flight import FlightRecorder  # noqa: F401
from alpa_tpu.telemetry.perf import (          # noqa: F401
    StepPerfReport, build_step_report, compute_mfu, device_peak_tflops,
    mfu_from_time, report_from_trace, stage_flops)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "DEFAULT_BUCKETS", "get_registry", "reset_registry",
    "CATEGORIES", "TraceRecorder", "begin", "counter", "enabled",
    "end", "get_recorder", "instant", "merge_chrome_traces",
    "set_enabled", "set_recorder", "span", "FlightRecorder",
    "StepPerfReport", "build_step_report", "compute_mfu",
    "device_peak_tflops", "mfu_from_time", "report_from_trace",
    "stage_flops",
]
