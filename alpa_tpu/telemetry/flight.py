"""Flight recorder: a fixed-size lock-free ring of instruction events.

The post-mortem half of the observability story (ISSUE 6): the unified
graph executor (and the interpreter) append one event per replayed
instruction — ``(node id, mesh, opcode, slot set, t_start/t_end,
outcome)`` — into a preallocated ring buffer.  Recording is a single
``itertools.count`` bump (atomic under the GIL — no lock on the hot
path) plus one list-slot store, cheap enough to leave on in production;
the ring holds only the last ``capacity`` events, so memory is fixed.

When something goes wrong the ring is dumped automatically:

* a pipeshard step raises (``PipeshardDriverExecutable.launch_on_driver``),
* a fault-injection site fires (``fault.fire``), or
* the watchdog's recovery manager declares a mesh SUSPECT
  (``fault.RecoveryManager``).

``auto_dump`` is the shared trigger: it writes a JSON post-mortem into
``global_config.flight_dump_dir`` (falling back to the debug-dump dir,
then the system temp dir), records the path for ``/healthz`` and
``monitoring.dump_debug_info``, and de-duplicates — a trigger with no
new events since the last dump writes nothing, so a raising fault site
inside a raising step produces one dump, and unit tests that fire
faults without running the executor produce none.

Read dumps with ``scripts/trace_tool.py flight DUMP.json``.

Knobs: ``ALPA_TPU_FLIGHT`` (default on) / ``global_config.
flight_recorder``, ``ALPA_TPU_FLIGHT_CAPACITY`` (ring size, rounded up
to a power of two), ``ALPA_TPU_FLIGHT_DIR``.
"""
import itertools
import json
import logging
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from alpa_tpu.global_env import global_config
from alpa_tpu.telemetry.trace import _now_us

logger = logging.getLogger(__name__)

__all__ = [
    "FlightRecorder", "get_recorder", "set_recorder", "enabled",
    "auto_dump", "last_dump_path", "load_dump", "annotate",
    "get_annotations", "clear_annotations",
]

#: on-disk dump schema version (bump on breaking change)
DUMP_VERSION = 1

# event tuple layout: (seq, kind, name, mesh, node, slots, t0_us,
# t1_us, outcome) — kept positional so record() allocates one tuple
_FIELDS = ("seq", "kind", "name", "mesh", "node", "slots",
           "t_start_us", "t_end_us", "outcome")


class FlightRecorder:
    """Fixed-size ring of the last N instruction events.

    Lock-free recording: the sequence counter is an ``itertools.count``
    (a single C-level increment, atomic under the GIL) and each event is
    one store into a preallocated list slot — concurrent recorders from
    the driver and transfer-pool threads never block each other.  A
    racing pair of writers can at worst overwrite one ring slot, which
    is exactly the ring's semantic anyway.
    """

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(getattr(global_config,
                                   "flight_recorder_capacity", 4096))
        cap = 1
        while cap < max(2, int(capacity)):
            cap <<= 1
        self.capacity = cap
        self._mask = cap - 1
        self._buf: List[Optional[tuple]] = [None] * cap
        self._seq = itertools.count()
        # highest seq included in the last auto_dump (dedupe marker)
        self._last_dumped_seq = -1

    # ---- recording (hot path) ---------------------------------------

    def record(self, kind: str, name: str, mesh: int, node: int,
               slots: Tuple[int, ...], t0_us: float, t1_us: float,
               outcome: str):
        i = next(self._seq)
        self._buf[i & self._mask] = (i, kind, name, mesh, node, slots,
                                     t0_us, t1_us, outcome)

    # ---- introspection ----------------------------------------------

    def snapshot(self) -> List[tuple]:
        """Surviving events, oldest first (stable under concurrent
        recording: a torn read only drops/duplicates ring-edge events)."""
        events = [e for e in list(self._buf) if e is not None]
        events.sort(key=lambda e: e[0])
        return events

    @property
    def n_events(self) -> int:
        return sum(1 for e in self._buf if e is not None)

    def clear(self):
        self._buf = [None] * self.capacity
        self._seq = itertools.count()
        self._last_dumped_seq = -1

    # ---- dumping ----------------------------------------------------

    def dump(self, path: Optional[str] = None,
             reason: str = "") -> Optional[str]:
        """Write the ring as JSON; returns the path (None when empty).
        Sets the module-level last-dump pointer."""
        events = self.snapshot()
        if not events:
            return None
        if path is None:
            path = os.path.join(
                _dump_dir(),
                f"alpa_flight_{os.getpid()}_{events[-1][0]}.json")
        payload = {
            "version": DUMP_VERSION,
            "reason": reason,
            "capacity": self.capacity,
            "n_events": len(events),
            "first_seq": events[0][0],
            "last_seq": events[-1][0],
            "written_at": time.time(),
            # compile-time analysis notes (ISSUE 8): e.g. the plan
            # verifier's leaked-slot var names, so a post-mortem dump
            # says which values vanished silently at step end
            "annotations": dict(_ANNOTATIONS),
            "events": [dict(zip(_FIELDS, e)) for e in events],
        }
        for ev in payload["events"]:
            ev["slots"] = list(ev["slots"] or ())
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        global _LAST_DUMP_PATH
        _LAST_DUMP_PATH = path
        self._last_dumped_seq = events[-1][0]
        return path


# ---- module-level recorder + trigger front door ----------------------

_RECORDER: Optional[FlightRecorder] = None
_LOCK = threading.Lock()
_LAST_DUMP_PATH: Optional[str] = None

# sticky analysis annotations included in every dump (survive recorder
# swaps: the verifier runs at compile time, dumps happen much later)
_ANNOTATIONS: Dict[str, Any] = {}


def annotate(key: str, value: Any) -> None:
    """Attach a compile-time note to every subsequent flight dump (the
    plan verifier posts ``leaked_slots`` here).  Values must be
    JSON-able."""
    _ANNOTATIONS[key] = value


def get_annotations() -> Dict[str, Any]:
    return dict(_ANNOTATIONS)


def clear_annotations() -> None:
    _ANNOTATIONS.clear()


def _dump_dir() -> str:
    d = (getattr(global_config, "flight_dump_dir", None) or
         getattr(global_config, "dump_debug_info_dir", None) or
         tempfile.gettempdir())
    os.makedirs(d, exist_ok=True)
    return d


def get_recorder() -> FlightRecorder:
    global _RECORDER
    if _RECORDER is None:
        with _LOCK:
            if _RECORDER is None:
                _RECORDER = FlightRecorder()
    return _RECORDER


def set_recorder(rec: Optional[FlightRecorder]
                 ) -> Optional[FlightRecorder]:
    """Swap the process recorder (tests install a fresh one); returns
    the previous recorder."""
    global _RECORDER
    prev = _RECORDER
    _RECORDER = rec
    return prev


def enabled() -> bool:
    return bool(getattr(global_config, "flight_recorder", True))


def now_us() -> float:
    """Timestamp on the same axis as the span trace (shared epoch)."""
    return _now_us()


def last_dump_path() -> Optional[str]:
    return _LAST_DUMP_PATH


def auto_dump(reason: str) -> Optional[str]:
    """Failure-triggered dump: step raised, fault site fired, or a mesh
    went SUSPECT.  Never raises; returns the dump path, or None when the
    recorder is disabled, empty, or has nothing new since the last dump
    (so stacked triggers from one failure produce one file)."""
    try:
        if not enabled() or _RECORDER is None:
            return None
        rec = _RECORDER
        events = rec.snapshot()
        if not events or events[-1][0] <= rec._last_dumped_seq:
            return None
        path = rec.dump(reason=reason)
        if path:
            logger.warning(
                "flight recorder: dumped %d instruction events to %s "
                "(%s) — inspect with scripts/trace_tool.py flight",
                len(events), path, reason)
        return path
    except Exception:  # pylint: disable=broad-except
        logger.exception("flight recorder auto-dump failed")
        return None


def load_dump(path: str) -> Dict[str, Any]:
    """Read a dump file back (trace_tool / tests); validates the shape."""
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    if "events" not in payload or "capacity" not in payload:
        raise ValueError(f"{path}: not a flight recorder dump")
    return payload
