"""Span tracing: thread-safe recorder exporting Chrome-trace JSON.

One :class:`TraceRecorder` per process collects *spans* (named,
categorized, nested intervals), *instants* (point events — the legacy
``timer.Tracer`` bridge lands here) and *counter* samples (e.g. the
overlap transfer pool's in-flight window).  Every event carries a
*track*: a stable ``tid`` in the exported trace.  By default the track
is the recording thread (``"driver"`` for the main thread, the thread
name otherwise — pool workers get their ``alpa-overlap-N`` names), but
call sites that know better pass one explicitly (``"mesh 3"`` for
per-instruction spans).

``to_chrome_trace()`` emits the Chrome trace event format
(``{"traceEvents": [...]}``) with ``B``/``E`` duration pairs, ``M``
thread-name metadata, ``i`` instants and ``C`` counters — loadable
directly in Perfetto / chrome://tracing.  ``merge_chrome_traces``
combines per-mesh / per-process files onto distinct pids.

Zero-cost-when-off: the module-level ``_ENABLED`` flag (seeded from
``ALPA_TPU_TRACE`` via ``global_config.telemetry_enabled``) is checked
before *any* allocation — ``span()`` returns a shared no-op singleton
when tracing is off, and the register-file replay checks the flag once
per step, not per instruction (guarded by a <2% overhead test).
"""
import json
import threading
import time
from typing import Any, Dict, List, Optional

from alpa_tpu.global_env import global_config

__all__ = [
    "TraceRecorder", "get_recorder", "set_recorder", "enabled",
    "set_enabled", "span", "instant", "counter", "begin", "end",
    "now_us", "merge_chrome_traces", "CATEGORIES",
]

# category taxonomy (docs/observability.md) — free-form strings are
# accepted; these are the ones the built-in instrumentation uses.
CATEGORIES = ("compile", "instruction", "transfer", "resharding",
              "checkpoint", "serving", "runtime", "legacy")

# perf_counter epoch shared by every event in this process so that
# timestamps from different threads land on one comparable axis.
_EPOCH = time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() - _EPOCH) * 1e6


def now_us() -> float:
    """Current time on the recorder's shared epoch — pair with
    :meth:`TraceRecorder.complete` for externally-timed spans."""
    return _now_us()


class _NullSpan:
    """Shared do-nothing context manager returned while tracing is off.

    A singleton (``__slots__``, no state) so the disabled path allocates
    nothing — tests assert ``span("a") is span("b")``."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Live span token: context manager AND explicit begin/end handle."""
    __slots__ = ("_rec", "name", "category", "args", "track", "_t0")

    def __init__(self, rec: "TraceRecorder", name: str, category: str,
                 args: Optional[Dict[str, Any]], track: Optional[str]):
        self._rec = rec
        self.name = name
        self.category = category
        self.args = args
        self.track = track
        self._t0 = _now_us()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._rec._finish(self)
        return False


class TraceRecorder:
    """Thread-safe in-memory event store (bounded by ``max_events``)."""

    def __init__(self, max_events: Optional[int] = None):
        if max_events is None:
            max_events = int(getattr(global_config,
                                     "telemetry_max_events", 200000))
        self.max_events = max_events
        self._lock = threading.Lock()
        # completed spans: (name, category, ts_us, dur_us, tid, args)
        self._spans: List[tuple] = []
        # instants: (name, category, ts_us, tid, args)
        self._instants: List[tuple] = []
        # counters: (name, ts_us, value, tid)
        self._counters: List[tuple] = []
        self._tids: Dict[str, int] = {}
        self._dropped = 0

    # ---- track / tid bookkeeping ------------------------------------

    def _tid(self, track: Optional[str]) -> int:
        if track is None:
            t = threading.current_thread()
            track = ("driver" if t is threading.main_thread()
                     else t.name)
        tid = self._tids.get(track)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(track, len(self._tids) + 1)
        return tid

    def _room(self, store: List[tuple]) -> bool:
        if len(store) >= self.max_events:
            self._dropped += 1
            return False
        return True

    # ---- recording --------------------------------------------------

    def span(self, name: str, category: str = "runtime",
             args: Optional[Dict[str, Any]] = None,
             track: Optional[str] = None) -> _Span:
        return _Span(self, name, category, args, track)

    def _finish(self, s: _Span):
        t1 = _now_us()
        tid = self._tid(s.track)
        with self._lock:
            if self._room(self._spans):
                self._spans.append((s.name, s.category, s._t0,
                                    t1 - s._t0, tid, s.args))

    def begin(self, name: str, category: str = "runtime",
              args: Optional[Dict[str, Any]] = None,
              track: Optional[str] = None) -> _Span:
        """Explicit open for async work; close with :meth:`end`.  Pass
        ``track`` when begin and end run on different threads."""
        return self.span(name, category, args, track)

    def end(self, token: Optional[_Span]):
        if token is not None and token is not _NULL_SPAN:
            self._finish(token)

    def complete(self, name: str, category: str, ts_us: float,
                 dur_us: float, args: Optional[Dict[str, Any]] = None,
                 track: Optional[str] = None):
        """Record an already-timed span — async work whose start was
        stamped on another thread (e.g. the overlap pool's queue-wait
        child, whose begin is the driver-side submit).  ``ts_us`` must
        come from :func:`now_us` so it shares the process epoch."""
        tid = self._tid(track)
        with self._lock:
            if self._room(self._spans):
                self._spans.append((name, category, ts_us, dur_us, tid,
                                    args))

    def instant(self, name: str, category: str = "runtime",
                args: Optional[Dict[str, Any]] = None,
                track: Optional[str] = None):
        tid = self._tid(track)
        with self._lock:
            if self._room(self._instants):
                self._instants.append((name, category, _now_us(), tid,
                                       args))

    def counter(self, name: str, value: float,
                track: Optional[str] = None):
        tid = self._tid(track if track is not None else name)
        with self._lock:
            if self._room(self._counters):
                self._counters.append((name, _now_us(), value, tid))

    def clear(self):
        with self._lock:
            self._spans.clear()
            self._instants.clear()
            self._counters.clear()
            self._tids.clear()
            self._dropped = 0

    # ---- introspection / export -------------------------------------

    @property
    def n_events(self) -> int:
        with self._lock:
            return (len(self._spans) + len(self._instants) +
                    len(self._counters))

    def spans(self) -> List[Dict[str, Any]]:
        """Completed spans as dicts (test/tooling convenience)."""
        with self._lock:
            items = list(self._spans)
            tids = dict(self._tids)
        names = {v: k for k, v in tids.items()}
        return [{"name": n, "category": c, "ts_us": ts, "dur_us": dur,
                 "tid": tid, "track": names.get(tid), "args": args}
                for n, c, ts, dur, tid, args in items]

    def to_chrome_trace(self, pid: int = 0,
                        process_name: str = "alpa_tpu") -> Dict[str, Any]:
        with self._lock:
            spans = list(self._spans)
            instants = list(self._instants)
            counters = list(self._counters)
            tids = dict(self._tids)
            dropped = self._dropped
        events: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": process_name},
        }]
        for track, tid in sorted(tids.items(), key=lambda kv: kv[1]):
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": tid,
                           "args": {"name": track}})
        timed: List[Dict[str, Any]] = []
        for name, cat, ts, dur, tid, args in spans:
            b = {"name": name, "cat": cat, "ph": "B", "ts": ts,
                 "pid": pid, "tid": tid}
            if args:
                b["args"] = args
            timed.append(b)
            timed.append({"name": name, "cat": cat, "ph": "E",
                          "ts": ts + dur, "pid": pid, "tid": tid})
        for name, cat, ts, tid, args in instants:
            ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
                  "ts": ts, "pid": pid, "tid": tid}
            if args:
                ev["args"] = args
            timed.append(ev)
        for name, ts, value, tid in counters:
            timed.append({"name": name, "ph": "C", "ts": ts,
                          "pid": pid, "tid": tid,
                          "args": {"value": value}})
        # E before B on timestamp ties so a span ending exactly where a
        # sibling starts still nests; real perf_counter stamps are
        # strictly increasing per thread.
        timed.sort(key=lambda e: (e["ts"], 0 if e["ph"] == "E" else 1))
        events.extend(timed)
        trace = {"traceEvents": events,
                 "displayTimeUnit": "ms"}
        if dropped:
            trace["alpa_dropped_events"] = dropped
        return trace

    def save(self, path: str, pid: int = 0,
             process_name: str = "alpa_tpu"):
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_chrome_trace(pid, process_name), f)


def merge_chrome_traces(traces: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge chrome traces (e.g. one per mesh/process) onto distinct
    pids so every input keeps its own track group in Perfetto."""
    events: List[Dict[str, Any]] = []
    for pid, trace in enumerate(traces):
        for ev in trace.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---- module-level recorder + zero-cost-when-off front door -----------

_ENABLED = bool(getattr(global_config, "telemetry_enabled", False))
_RECORDER = TraceRecorder()


def get_recorder() -> TraceRecorder:
    return _RECORDER


def set_recorder(rec: TraceRecorder) -> TraceRecorder:
    """Swap the process recorder (tests install a fresh one); returns
    the previous recorder."""
    global _RECORDER
    prev = _RECORDER
    _RECORDER = rec
    return prev


def enabled() -> bool:
    return _ENABLED


def set_enabled(flag: bool) -> bool:
    """Flip tracing on/off; keeps ``global_config.telemetry_enabled`` in
    sync.  Returns the previous value."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(flag)
    global_config.telemetry_enabled = bool(flag)
    return prev


def span(name: str, category: str = "runtime",
         args: Optional[Dict[str, Any]] = None,
         track: Optional[str] = None):
    """Context manager recording a span — or the shared no-op singleton
    when tracing is off (no allocation on the disabled path)."""
    if not _ENABLED:
        return _NULL_SPAN
    return _RECORDER.span(name, category, args, track)


def begin(name: str, category: str = "runtime",
          args: Optional[Dict[str, Any]] = None,
          track: Optional[str] = None) -> Optional[_Span]:
    """Open an async span; returns None when tracing is off (safe to
    pass straight back to :func:`end`)."""
    if not _ENABLED:
        return None
    return _RECORDER.begin(name, category, args, track)


def end(token: Optional[_Span]):
    if token is not None:
        _RECORDER.end(token)


def instant(name: str, category: str = "runtime",
            args: Optional[Dict[str, Any]] = None,
            track: Optional[str] = None):
    if _ENABLED:
        _RECORDER.instant(name, category, args, track)


def counter(name: str, value: float, track: Optional[str] = None):
    if _ENABLED:
        _RECORDER.counter(name, value, track)
