"""Central metrics registry: Counter / Gauge / Histogram with labels.

The registry is the single home for every runtime statistic alpa_tpu
keeps (compile cache hit/miss, overlap dispatch totals, checkpoint
traffic, fault-layer retries, serving queue depth / batch size / TTFT /
tokens-per-second, watchdog liveness).  The pre-existing ad-hoc dicts
(``monitoring.get_*_stats()``, ``checkpoint.metrics``,
``runtime_emitter._overlap_totals``, ...) are thin views over it, so
every number shows up exactly once and ``GET /metrics`` on the serving
controller can export the whole registry in Prometheus text exposition
format.

Design notes:

* Metric *families* are created idempotently via
  ``registry.counter(name, ...)`` — repeated calls with the same name
  return the same family, so modules can declare their metrics at
  import time without coordination.
* Labeled families hand out children via ``family.labels(v1, ...)``;
  an unlabeled family is its own child.
* ``Histogram`` keeps fixed cumulative buckets (for Prometheus
  ``_bucket`` samples) plus a bounded ring of recent raw samples for
  exact nearest-rank p50/p95/p99 summaries.
* ``register_collector(fn)`` lets a module with live per-instance state
  (e.g. the process compile cache, which tests swap per-test) publish
  into the registry lazily: collectors run at collect time
  (``to_prometheus_text()`` / ``snapshot()``) and typically set gauges.
"""
import bisect
import math
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "reset_registry", "DEFAULT_BUCKETS",
]

# seconds-oriented default latency buckets (Prometheus-style)
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_SUMMARY_RING = 2048  # raw samples kept per histogram child for p50/p95/p99


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n").replace(
        '"', '\\"')


def _label_str(labelnames: Sequence[str],
               labelvalues: Sequence[str]) -> str:
    if not labelnames:
        return ""
    pairs = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in zip(labelnames, labelvalues))
    return "{" + pairs + "}"


class _Child:
    """Base for a single (labelset, metric) time series."""

    def __init__(self):
        self._lock = threading.Lock()


class _CounterChild(_Child):

    def __init__(self):
        super().__init__()
        self._value = 0.0

    def inc(self, value: float = 1.0):
        if value < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = 0.0


class _GaugeChild(_Child):

    def __init__(self):
        super().__init__()
        self._value = 0.0

    def set(self, value: float):
        with self._lock:
            self._value = float(value)

    def inc(self, value: float = 1.0):
        with self._lock:
            self._value += value

    def dec(self, value: float = 1.0):
        with self._lock:
            self._value -= value

    def set_max(self, value: float):
        """Keep the running maximum (used for high-watermark gauges)."""
        with self._lock:
            if value > self._value:
                self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = 0.0


class _HistogramChild(_Child):

    def __init__(self, buckets: Sequence[float]):
        super().__init__()
        self._buckets = tuple(buckets)
        self._counts = [0] * (len(self._buckets) + 1)  # +Inf tail
        self._sum = 0.0
        self._count = 0
        self._ring: List[float] = []
        self._ring_pos = 0

    def observe(self, value: float):
        v = float(value)
        with self._lock:
            i = bisect.bisect_left(self._buckets, v)
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if len(self._ring) < _SUMMARY_RING:
                self._ring.append(v)
            else:
                self._ring[self._ring_pos] = v
                self._ring_pos = (self._ring_pos + 1) % _SUMMARY_RING

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the recent-sample ring.

        Exact for the first ``_SUMMARY_RING`` observations; a sliding
        window afterwards."""
        with self._lock:
            data = sorted(self._ring)
        if not data:
            return 0.0
        k = max(0, min(len(data) - 1,
                       int(math.ceil(p / 100.0 * len(data))) - 1))
        return data[k]

    def summary(self) -> Dict[str, float]:
        return {"count": self.count, "sum": self.sum,
                "p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99)}

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """Cumulative (le, count) pairs, ending with +Inf."""
        with self._lock:
            out, cum = [], 0
            for le, c in zip(self._buckets + (float("inf"),),
                             self._counts):
                cum += c
                out.append((le, cum))
            return out

    def reset(self):
        with self._lock:
            self._counts = [0] * (len(self._buckets) + 1)
            self._sum = 0.0
            self._count = 0
            self._ring = []
            self._ring_pos = 0


_CHILD_TYPES = {"counter": _CounterChild, "gauge": _GaugeChild,
                "histogram": _HistogramChild}


class _Family:
    """A named metric family; with labels it fans out to children, without
    labels it proxies to a single implicit child."""

    kind = None  # "counter" | "gauge" | "histogram"

    def __init__(self, name: str, description: str,
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.description = description
        self.labelnames = tuple(labelnames)
        self._buckets = tuple(buckets) if buckets else DEFAULT_BUCKETS
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self) -> _Child:
        cls = _CHILD_TYPES[self.kind]
        if self.kind == "histogram":
            return cls(self._buckets)
        return cls()

    def labels(self, *labelvalues) -> _Child:
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{labelvalues}")
        key = tuple(str(v) for v in labelvalues)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def children(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return sorted(self._children.items())

    def reset(self):
        with self._lock:
            if self.labelnames:
                self._children.clear()
            else:
                self._children[()].reset()

    # unlabeled families proxy the child API
    def _solo(self) -> _Child:
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; call "
                ".labels(...) first")
        return self._children[()]


class Counter(_Family):
    kind = "counter"

    def inc(self, value: float = 1.0):
        self._solo().inc(value)

    @property
    def value(self) -> float:
        return self._solo().value


class Gauge(_Family):
    kind = "gauge"

    def set(self, value: float):
        self._solo().set(value)

    def inc(self, value: float = 1.0):
        self._solo().inc(value)

    def dec(self, value: float = 1.0):
        self._solo().dec(value)

    def set_max(self, value: float):
        self._solo().set_max(value)

    @property
    def value(self) -> float:
        return self._solo().value


class Histogram(_Family):
    kind = "histogram"

    def observe(self, value: float):
        self._solo().observe(value)

    def percentile(self, p: float) -> float:
        return self._solo().percentile(p)

    def summary(self) -> Dict[str, float]:
        return self._solo().summary()

    def bucket_counts(self) -> List[Tuple[float, int]]:
        return self._solo().bucket_counts()

    @property
    def count(self) -> int:
        return self._solo().count

    @property
    def sum(self) -> float:
        return self._solo().sum


_FAMILY_TYPES = {"counter": Counter, "gauge": Gauge,
                 "histogram": Histogram}


class MetricsRegistry:
    """Process-global (or test-local) collection of metric families."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self._collectors: List[Callable[["MetricsRegistry"], None]] = []

    def _get_or_create(self, kind: str, name: str, description: str,
                       labelnames: Sequence[str],
                       buckets: Optional[Sequence[float]]) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {kind}")
                return fam
            fam = _FAMILY_TYPES[kind](name, description, labelnames,
                                      buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, description: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create("counter", name, description,
                                   labelnames, None)

    def gauge(self, name: str, description: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create("gauge", name, description,
                                   labelnames, None)

    def histogram(self, name: str, description: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create("histogram", name, description,
                                   labelnames, buckets)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def register_collector(self, fn: Callable[["MetricsRegistry"], None]):
        """Run ``fn(registry)`` before every collection.  Collectors pull
        live module state (e.g. the current compile cache instance) into
        registry gauges.  Registering the same function twice is a
        no-op."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def _run_collectors(self):
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:  # pragma: no cover - defensive: a broken
                pass           # collector must not take down /metrics

    def families(self) -> List[_Family]:
        self._run_collectors()
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def snapshot(self) -> Dict[str, object]:
        """Plain-dict dump: ``name{labels}`` -> value (histograms ->
        summary dict).  Used by dump_debug_info and tests."""
        out: Dict[str, object] = {}
        for fam in self.families():
            for key, child in fam.children():
                sample = fam.name + _label_str(fam.labelnames, key)
                if fam.kind == "histogram":
                    out[sample] = child.summary()
                else:
                    out[sample] = child.value
        return out

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: List[str] = []
        for fam in self.families():
            if fam.description:
                lines.append(f"# HELP {fam.name} {fam.description}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in fam.children():
                labels = _label_str(fam.labelnames, key)
                if fam.kind == "histogram":
                    for le, cum in child.bucket_counts():
                        le_lbl = _label_str(
                            fam.labelnames + ("le",),
                            key + (_fmt_value(le),))
                        lines.append(
                            f"{fam.name}_bucket{le_lbl} {cum}")
                    lines.append(
                        f"{fam.name}_sum{labels} "
                        f"{_fmt_value(child.sum)}")
                    lines.append(
                        f"{fam.name}_count{labels} {child.count}")
                else:
                    lines.append(
                        f"{fam.name}{labels} "
                        f"{_fmt_value(child.value)}")
        return "\n".join(lines) + "\n"

    def reset(self, prefix: Optional[str] = None):
        """Zero every family (or only those whose name starts with
        ``prefix``).  Definitions and collectors survive."""
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            if prefix is None or fam.name.startswith(prefix):
                fam.reset()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def reset_registry(prefix: Optional[str] = None):
    _REGISTRY.reset(prefix)
