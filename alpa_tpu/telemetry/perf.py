"""Post-step performance analysis: StepPerfReport (ISSUE 9 tentpole).

Joins the raw telemetry PRs 5–6 collect — per-op trace spans from the
hooked graph executor (``op_meta``), or flight-ring events when full
tracing is off — back against the lowering-time
:class:`~alpa_tpu.pipeline_parallel.runtime_emitter.
InstructionDataflowGraph`, and turns one step's stream into answers:

* **critical path** — the measured longest chain through the step
  (:mod:`alpa_tpu.analysis.critical_path`), with a what-if re-simulator
  over the dependency DAG ("if this RESHARD were free, step −X%");
* **bubble accounting** — per-mesh busy/warmup/steady-idle/drain
  decomposition of the step envelope, keyed against the
  ``PipelineSchedule``'s expected warmup/drain depth, plus
  exposed-vs-hidden transfer time (extending PR 4's
  ``overlap_fraction``) split into queue-wait vs wire time by the
  ``reshard.wait`` / ``reshard.wire`` child spans;
* **MFU attribution** — per-stage analytic FLOPs
  (``util.jaxpr_eqn_flops`` over the stage's closed jaxpr) over measured
  RUN span time and the chip peak (``device_peak_tflops`` knob /
  ``ALPA_TPU_DEVICE_PEAK_TFLOPS``, auto-detected from
  ``TPU_GENERATION_SPECS`` otherwise).

Published to the central metrics registry as ``alpa_stage_mfu{stage}``,
``alpa_step_bubble_fraction{mesh}`` and ``alpa_critical_path_us``;
surfaced as ``perf_report.txt`` in debug dumps,
``PipeshardDriverExecutable.get_perf_report()``, and
``scripts/perf_tool.py``.  This module is also the single home of the
peak-FLOPs/MFU formula (``bench.py`` and ``scripts/mfu_breakdown.py``
are thin callers).
"""
import collections
import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from alpa_tpu.analysis.critical_path import (
    CriticalPathReport, TimedOp, measured_critical_path, simulate_dag,
    whatif as _whatif_dag)
from alpa_tpu.global_env import global_config
from alpa_tpu.telemetry import metrics as _tmetrics

__all__ = [
    "device_peak_tflops", "peak_flops_info", "stage_flops",
    "compute_mfu", "mfu_from_time",
    "JoinedStep", "MeshBubbles", "TransferBreakdown", "StageMfu",
    "StepPerfReport",
    "joined_from_recorder", "joined_from_flight", "spans_from_chrome",
    "build_step_report", "report_from_trace",
    "publish_report", "record_gate_verdict",
]


########################################
# the one peak-FLOPs / MFU formula (satellite S1)
########################################


def peak_flops_info(generation: Optional[str] = None) -> Dict[str, Any]:
    """Resolve the chip peak used for MFU: the ``device_peak_tflops``
    knob (``ALPA_TPU_DEVICE_PEAK_TFLOPS``) when set, else the detected
    TPU generation's published bf16 peak."""
    override = float(getattr(global_config, "device_peak_tflops", 0.0)
                     or 0.0)
    if override > 0:
        return {"generation": generation or "override",
                "peak_bf16_tflops": override}
    from alpa_tpu.mesh_profiling import (TPU_GENERATION_SPECS,
                                         detect_tpu_generation)
    gen = generation or detect_tpu_generation()
    return {"generation": gen,
            "peak_bf16_tflops": TPU_GENERATION_SPECS[gen]
            ["peak_bf16_tflops"]}


def device_peak_tflops(generation: Optional[str] = None) -> float:
    return peak_flops_info(generation)["peak_bf16_tflops"]


def compute_mfu(tflops_per_chip: float,
                peak_tflops: Optional[float] = None) -> float:
    """achieved TFLOPS per chip / peak TFLOPS per chip."""
    peak = peak_tflops if peak_tflops else device_peak_tflops()
    return tflops_per_chip / peak if peak > 0 else 0.0


def mfu_from_time(flops: float, seconds: float, n_devices: int,
                  peak_tflops: Optional[float] = None) -> float:
    """MFU from raw measurements: total model FLOPs over ``seconds``
    spread across ``n_devices`` chips."""
    if seconds <= 0 or n_devices <= 0:
        return 0.0
    return compute_mfu(flops / seconds / n_devices / 1e12, peak_tflops)


def stage_flops(closed_jaxpr) -> float:
    """Analytic FLOPs of one stage invocation (``util.jaxpr_eqn_flops``
    summed over the stage's closed jaxpr)."""
    from alpa_tpu.util import jaxpr_eqn_flops
    return float(sum(jaxpr_eqn_flops(eqn)
                     for eqn in closed_jaxpr.jaxpr.eqns))


########################################
# joining spans / flight events back to the lowered program
########################################


@dataclasses.dataclass
class JoinedStep:
    """One step's op samples on a common time axis, pre-report."""
    ops: List[TimedOp]
    t0_us: float
    envelope_us: float
    pool_spans: List[Dict[str, Any]]     # alpa-overlap-* track spans
    source: str                          # "trace" | "flight"
    aligned: bool                        # ops joined 1:1 to program hooks


def _kind_from_name(name: str) -> str:
    if name.startswith("LAUNCH"):
        return "launch"
    if name.startswith("WAIT"):
        return "wait"
    return "exec"


def _join_spans(spans: Sequence[Dict[str, Any]],
                program=None) -> Optional[JoinedStep]:
    """Window the span list to the last ``pipeshard.step`` envelope and
    align the per-op spans positionally against the program's
    ``op_meta``/``hooks`` (both are emitted in replay order)."""
    steps = [s for s in spans if s["name"] == "pipeshard.step"]
    w0 = w1 = None
    if steps:
        env = max(steps, key=lambda s: s["ts_us"])
        w0, w1 = env["ts_us"], env["ts_us"] + env["dur_us"]

    def in_window(s):
        return w0 is None or (s["ts_us"] >= w0 - 1.0 and
                              s["ts_us"] + s["dur_us"] <= w1 + 1.0)

    op_spans = sorted(
        (s for s in spans
         if s["category"] in ("instruction", "transfer") and
         (s.get("track") or "").startswith("mesh") and in_window(s)),
        key=lambda s: (s["ts_us"], s["ts_us"] + s["dur_us"]))
    if not op_spans:
        return None
    pool = [s for s in spans
            if (s.get("track") or "").startswith("alpa-overlap") and
            in_window(s)]
    hooks = getattr(program, "hooks", None) if program is not None \
        else None
    meta = getattr(program, "op_meta", None) if program is not None \
        else None
    aligned = (hooks is not None and meta is not None and
               len(op_spans) == len(meta) and
               all(s["name"] == m[0]
                   for s, m in zip(op_spans, meta)))
    ops = []
    for i, s in enumerate(op_spans):
        kind = hooks[i].kind if aligned else _kind_from_name(s["name"])
        ops.append(TimedOp(idx=i, name=s["name"], kind=kind,
                           track=s["track"], t0_us=s["ts_us"],
                           t1_us=s["ts_us"] + s["dur_us"]))
    if w0 is None:
        w0 = min(o.t0_us for o in ops)
        w1 = max(o.t1_us for o in ops)
    return JoinedStep(ops=ops, t0_us=w0, envelope_us=w1 - w0,
                      pool_spans=pool, source="trace", aligned=aligned)


def joined_from_recorder(rec, program=None) -> Optional[JoinedStep]:
    """Join the live trace recorder's spans (preferred source)."""
    return _join_spans(rec.spans(), program)


def joined_from_flight(events: Sequence[Any],
                       program=None) -> Optional[JoinedStep]:
    """Fallback join over flight-ring events (full tracing off).

    Events are ``(seq, kind, name, mesh, node, slots, t0, t1, outcome)``
    tuples (``flight._FIELDS``) or equivalent dicts from a dump."""
    rows = []
    for e in events:
        if isinstance(e, dict):
            rows.append((e["kind"], e["name"], e["mesh"],
                         e["t_start_us"], e["t_end_us"]))
        else:
            rows.append((e[1], e[2], e[3], e[6], e[7]))
    if not rows:
        return None
    hooks = getattr(program, "hooks", None) if program is not None \
        else None
    if hooks and len(rows) >= len(hooks):
        # the ring holds many steps; the trailing len(ops) events are
        # the last replay (each step appends exactly one event per op)
        tail = rows[-len(hooks):]
        if all(r[1] == h.name for r, h in zip(tail, hooks)):
            rows = tail
    aligned = bool(hooks) and len(rows) == len(hooks) and \
        all(r[1] == h.name for r, h in zip(rows, hooks))
    ops = []
    for i, (kind, name, mesh, t0, t1) in enumerate(rows):
        k = hooks[i].kind if aligned else (
            kind if kind in ("exec", "launch", "wait")
            else _kind_from_name(name))
        ops.append(TimedOp(idx=i, name=name, kind=k,
                           track=f"mesh {mesh}", t0_us=t0, t1_us=t1))
    w0 = min(o.t0_us for o in ops)
    w1 = max(o.t1_us for o in ops)
    return JoinedStep(ops=ops, t0_us=w0, envelope_us=w1 - w0,
                      pool_spans=[], source="flight", aligned=aligned)


def _op_dependencies(program, n_ops: int
                     ) -> Tuple[Dict[int, set], List[set]]:
    """Map dataflow-graph edges into op space.

    Returns ``(causal, sim_preds)``: ``causal[i]`` are the ops whose
    *retirement* (exec, or the wait of a launched transfer) gates op
    ``i`` — used by the measured walk; ``sim_preds`` additionally
    carries same-mesh issue order (each mesh is one serial instruction
    stream) and launch→wait edges — the re-simulation model."""
    graph, hooks = program.graph, program.hooks
    retire: Dict[int, int] = {}
    launch_of: Dict[int, int] = {}
    for i, h in enumerate(hooks):
        if h.kind in ("exec", "wait"):
            for m in h.members:
                retire[m] = i
        if h.kind == "launch":
            for m in h.members:
                launch_of[m] = i
    causal: Dict[int, set] = {i: set() for i in range(n_ops)}
    for i, h in enumerate(hooks):
        for m in h.members:
            for p in graph.preds[m]:
                j = retire.get(p)
                if j is not None and j != i:
                    causal[i].add(j)
        if h.kind == "wait":
            j = launch_of.get(h.members[0])
            if j is not None and j != i:
                causal[i].add(j)
    sim_preds = [set(causal[i]) for i in range(n_ops)]
    last_on_mesh: Dict[int, int] = {}
    for i, h in enumerate(hooks):
        p = last_on_mesh.get(h.mesh)
        if p is not None:
            sim_preds[i].add(p)
        last_on_mesh[h.mesh] = i
    return causal, sim_preds


########################################
# report pieces
########################################


@dataclasses.dataclass
class MeshBubbles:
    """One mesh's share of the step envelope."""
    mesh: str
    envelope_us: float
    busy_us: float
    warmup_us: float          # idle before the mesh's first op
    steady_idle_us: float     # gaps between ops
    drain_us: float           # idle after the mesh's last op
    n_ops: int
    stream_wait_us: float     # driver time blocked in WAIT ops here
    sched_warmup_ticks: Optional[int] = None
    sched_drain_ticks: Optional[int] = None
    sched_num_clock: Optional[int] = None

    def fractions(self) -> Dict[str, float]:
        e = self.envelope_us or 1.0
        return {"busy": self.busy_us / e,
                "warmup": self.warmup_us / e,
                "steady_idle": self.steady_idle_us / e,
                "drain": self.drain_us / e}

    @property
    def bubble_fraction(self) -> float:
        """1 − busy/envelope: the alpa_step_bubble_fraction gauge."""
        if self.envelope_us <= 0:
            return 0.0
        return max(0.0, 1.0 - self.busy_us / self.envelope_us)


@dataclasses.dataclass
class TransferBreakdown:
    """Exposed vs hidden transfer time (extends PR 4's
    overlap_fraction) with S2's queue-wait/wire split."""
    pool_busy_us: float = 0.0     # pool-side transfer occupancy
    wire_us: float = 0.0          # reshard.wire child spans
    queue_wait_us: float = 0.0    # reshard.wait child spans (scheduler
                                  # backpressure, NOT network time)
    exposed_wait_us: float = 0.0  # driver blocked in WAIT ops
    hidden_us: float = 0.0        # pool busy the driver never saw
    overlap_fraction: float = 1.0


@dataclasses.dataclass
class StageMfu:
    stage: str
    flops_per_run: float
    n_runs: int
    run_time_us: float
    n_devices: int
    peak_tflops: float
    tflops_per_chip: float
    mfu: float


@dataclasses.dataclass
class StepPerfReport:
    source: str                   # "trace" | "flight"
    mode: Optional[str]
    envelope_us: float
    n_ops: int
    aligned: bool                 # dataflow graph joined (vs track-only)
    critical_path: CriticalPathReport
    bubbles: Dict[str, MeshBubbles]
    transfers: TransferBreakdown
    stages: Dict[str, StageMfu]
    notes: List[str] = dataclasses.field(default_factory=list)
    # re-simulation model (kept for whatif; not part of the text report)
    sim_durs_us: List[float] = dataclasses.field(
        default_factory=list, repr=False)
    sim_preds: List[tuple] = dataclasses.field(
        default_factory=list, repr=False)
    sim_ops: List[TimedOp] = dataclasses.field(
        default_factory=list, repr=False)

    # ---- what-if re-simulation --------------------------------------

    def whatif(self, zero: str = "reshard",
               name_substr: Optional[str] = None) -> Dict[str, Any]:
        """Re-simulate the DAG with an op class made free.

        ``zero``: "reshard"/"transfer" (launch+wait+RESHARD execs),
        "run", "free", or "name" with ``name_substr``."""
        zeroed = {o.idx for o in self.sim_ops
                  if _matches_class(o, zero, name_substr)}
        baseline, _ = simulate_dag(self.sim_durs_us, self.sim_preds)
        after = _whatif_dag(self.sim_durs_us, self.sim_preds, zeroed)
        saving = max(0.0, baseline - after)
        return {
            "zero": zero if name_substr is None else f"name:{name_substr}",
            "n_zeroed": len(zeroed),
            "baseline_us": baseline,
            "whatif_us": after,
            "saving_us": saving,
            "saving_fraction": saving / baseline if baseline > 0 else 0.0,
        }

    # ---- serialization ----------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        """Flat-ish dict for perf_tool --json / perf_gate baselines."""
        return {
            "source": self.source,
            "mode": self.mode,
            "aligned": self.aligned,
            "n_ops": self.n_ops,
            "envelope_us": round(self.envelope_us, 3),
            "critical_path_us": round(self.critical_path.total_us, 3),
            "critical_path_coverage": round(self.critical_path.coverage,
                                            4),
            "critical_path_gap_us": round(self.critical_path.gap_us, 3),
            "bubbles": {
                m: {"bubble_fraction": round(b.bubble_fraction, 4),
                    "busy_us": round(b.busy_us, 3),
                    "n_ops": b.n_ops,
                    "stream_wait_us": round(b.stream_wait_us, 3),
                    **{f"{k}_fraction": round(v, 4)
                       for k, v in b.fractions().items()}}
                for m, b in sorted(self.bubbles.items())
            },
            "transfers": {
                "pool_busy_us": round(self.transfers.pool_busy_us, 3),
                "wire_us": round(self.transfers.wire_us, 3),
                "queue_wait_us": round(self.transfers.queue_wait_us, 3),
                "exposed_wait_us": round(self.transfers.exposed_wait_us,
                                         3),
                "hidden_us": round(self.transfers.hidden_us, 3),
                "overlap_fraction": round(self.transfers.overlap_fraction,
                                          4),
            },
            "stages": {
                name: {"mfu": round(s.mfu, 6),
                       "tflops_per_chip": round(s.tflops_per_chip, 6),
                       "flops_per_run": s.flops_per_run,
                       "n_runs": s.n_runs,
                       "run_time_us": round(s.run_time_us, 3),
                       "n_devices": s.n_devices,
                       "peak_tflops": s.peak_tflops}
                for name, s in sorted(self.stages.items())
            },
        }

    # ---- text report (perf_report.txt) ------------------------------

    def format_text(self, top: int = 10) -> str:
        lines = [
            f"step perf report ({self.source}"
            f"{', mode=' + self.mode if self.mode else ''}"
            f"{', graph-joined' if self.aligned else ', track-order only'}"
            f"): {self.n_ops} ops over {self.envelope_us:.1f} us",
            "",
            self.critical_path.format_table(top),
            "",
            "per-mesh bubbles (fractions of the step envelope):",
            f"  {'mesh':<8} {'busy':>7} {'warmup':>7} {'steady':>7} "
            f"{'drain':>7} {'bubble':>7} {'ops':>5} {'sched w/d':>10}",
        ]
        for m, b in sorted(self.bubbles.items()):
            f = b.fractions()
            sched = (f"{b.sched_warmup_ticks}/{b.sched_drain_ticks}"
                     if b.sched_warmup_ticks is not None else "-")
            lines.append(
                f"  {m:<8} {f['busy']:7.3f} {f['warmup']:7.3f} "
                f"{f['steady_idle']:7.3f} {f['drain']:7.3f} "
                f"{b.bubble_fraction:7.3f} {b.n_ops:5d} {sched:>10}")
        t = self.transfers
        lines += [
            "",
            f"transfers: pool busy {t.pool_busy_us:.1f} us "
            f"(wire {t.wire_us:.1f}, queue-wait {t.queue_wait_us:.1f}), "
            f"exposed {t.exposed_wait_us:.1f} us, hidden "
            f"{t.hidden_us:.1f} us, overlap fraction "
            f"{t.overlap_fraction:.3f}",
        ]
        if self.stages:
            lines += ["", "stage MFU:",
                      f"  {'stage':<24} {'runs':>5} {'time_us':>10} "
                      f"{'TFLOPS/chip':>12} {'MFU':>8}"]
            for name, s in sorted(self.stages.items()):
                lines.append(
                    f"  {name:<24} {s.n_runs:5d} {s.run_time_us:10.1f} "
                    f"{s.tflops_per_chip:12.4f} {s.mfu:8.4f}")
        if self.notes:
            lines += [""] + [f"note: {n}" for n in self.notes]
        return "\n".join(lines)


def _matches_class(op: TimedOp, zero: str,
                   name_substr: Optional[str]) -> bool:
    if name_substr is not None:
        return name_substr in op.name
    zero = zero.lower()
    if zero in ("reshard", "transfer"):
        return (op.kind in ("launch", "wait") or
                op.name.startswith("RESHARD"))
    if zero == "run":
        return op.name.startswith("RUN")
    if zero == "free":
        return op.name.startswith("FREE")
    raise ValueError(f"unknown what-if op class {zero!r} "
                     "(reshard|run|free, or pass name_substr)")


########################################
# report construction
########################################


def _mesh_bubbles(ops: Sequence[TimedOp], t0_us: float,
                  envelope_us: float,
                  schedule=None) -> Dict[str, MeshBubbles]:
    t1_env = t0_us + envelope_us
    by_track: Dict[str, List[TimedOp]] = collections.defaultdict(list)
    for o in ops:
        by_track[o.track].append(o)
    sched_first: Dict[int, int] = {}
    sched_last: Dict[int, int] = {}
    num_clock = None
    if schedule is not None:
        ticks = schedule.schedules
        num_clock = len(ticks)
        for t, tick in enumerate(ticks):
            for mesh_id, task in enumerate(tick):
                if task is not None:
                    sched_first.setdefault(mesh_id, t)
                    sched_last[mesh_id] = t
    out: Dict[str, MeshBubbles] = {}
    for track, group in by_track.items():
        group.sort(key=lambda o: o.t0_us)
        busy = sum(max(0.0, min(o.t1_us, t1_env) - max(o.t0_us, t0_us))
                   for o in group)
        first = max(t0_us, min(o.t0_us for o in group))
        last = min(t1_env, max(o.t1_us for o in group))
        warmup = max(0.0, first - t0_us)
        drain = max(0.0, t1_env - last)
        steady = max(0.0, envelope_us - busy - warmup - drain)
        wait_us = sum(o.dur_us for o in group if o.kind == "wait" or
                      o.name.startswith("WAIT"))
        mesh_id = None
        if track.startswith("mesh "):
            try:
                mesh_id = int(track.split()[1])
            except ValueError:
                pass
        out[track] = MeshBubbles(
            mesh=track, envelope_us=envelope_us, busy_us=busy,
            warmup_us=warmup, steady_idle_us=steady, drain_us=drain,
            n_ops=len(group), stream_wait_us=wait_us,
            sched_warmup_ticks=(sched_first.get(mesh_id)
                                if num_clock is not None and
                                mesh_id is not None else None),
            sched_drain_ticks=(num_clock - 1 - sched_last[mesh_id]
                               if num_clock is not None and
                               mesh_id in sched_last else None),
            sched_num_clock=num_clock)
    return out


def _transfer_breakdown(ops: Sequence[TimedOp],
                        pool_spans: Sequence[Dict[str, Any]],
                        run_stats: Optional[Dict[str, Any]] = None
                        ) -> TransferBreakdown:
    wire = sum(s["dur_us"] for s in pool_spans
               if s["name"] == "reshard.wire")
    queue = sum(s["dur_us"] for s in pool_spans
                if s["name"] == "reshard.wait")
    # parent submit→retire spans (the labeled LAUNCH payload spans);
    # reshard.* children and nested resharding-category spans excluded
    parent = sum(s["dur_us"] for s in pool_spans
                 if s["category"] == "transfer" and
                 not s["name"].startswith("reshard."))
    pool_busy = wire if wire > 0 else parent
    if pool_busy == 0 and run_stats:
        pool_busy = run_stats.get("transfer_busy_s", 0.0) * 1e6
    exposed = sum(o.dur_us for o in ops if o.kind == "wait" or
                  o.name.startswith("WAIT"))
    if exposed == 0 and run_stats:
        exposed = run_stats.get("wait_blocked_s", 0.0) * 1e6
    hidden = max(0.0, pool_busy - exposed)
    frac = max(0.0, min(1.0, 1.0 - exposed / pool_busy)) \
        if pool_busy > 0 else 1.0
    return TransferBreakdown(pool_busy_us=pool_busy, wire_us=wire,
                             queue_wait_us=queue,
                             exposed_wait_us=exposed, hidden_us=hidden,
                             overlap_fraction=frac)


def _n_devices(stage_exec) -> int:
    mesh = getattr(stage_exec, "_physical_mesh", None)
    n = getattr(mesh, "num_devices", None)
    if n:
        return int(n)
    jm = getattr(stage_exec, "jax_mesh", None)
    if jm is not None:
        try:
            return int(jm.devices.size)
        except Exception:  # pylint: disable=broad-except
            pass
    return 1


def _stage_mfu(ops: Sequence[TimedOp], stage_execs,
               peak_tflops: Optional[float] = None
               ) -> Dict[str, StageMfu]:
    if not stage_execs:
        return {}
    peak = peak_tflops if peak_tflops else device_peak_tflops()
    out: Dict[str, StageMfu] = {}
    for ex in stage_execs:
        name = getattr(ex, "name", None)
        if not name:
            continue
        spans = [o for o in ops if o.name == f"RUN {name}"]
        if not spans:
            continue
        t_us = sum(o.dur_us for o in spans)
        try:
            flops = stage_flops(ex.comp.closed_jaxpr())
        except Exception:  # pylint: disable=broad-except
            continue
        ndev = _n_devices(ex)
        tfpc = (flops * len(spans) / (t_us * 1e-6) / ndev / 1e12
                if t_us > 0 else 0.0)
        out[name] = StageMfu(stage=name, flops_per_run=flops,
                             n_runs=len(spans), run_time_us=t_us,
                             n_devices=ndev, peak_tflops=peak,
                             tflops_per_chip=tfpc,
                             mfu=tfpc / peak if peak > 0 else 0.0)
    return out


def build_step_report(joined: JoinedStep, program=None, schedule=None,
                      stage_execs=None, mode: Optional[str] = None,
                      run_stats: Optional[Dict[str, Any]] = None,
                      peak_tflops: Optional[float] = None
                      ) -> StepPerfReport:
    """Assemble the StepPerfReport from a joined step.

    ``program`` (when its hooks aligned) contributes the dataflow
    edges; without it the walk rides track order + issue order only.
    ``schedule`` keys the warmup/drain bubble expectation;
    ``stage_execs`` enable MFU attribution."""
    ops = joined.ops
    notes: List[str] = []
    causal: Dict[int, set] = {}
    if joined.aligned and program is not None and \
            program.graph is not None:
        causal, sim_preds = _op_dependencies(program, len(ops))
    else:
        if program is not None and not joined.aligned:
            notes.append("spans did not align 1:1 with the lowered "
                         "program; dataflow edges unavailable "
                         "(track-order analysis)")
        sim_preds = [set() for _ in ops]
        last_on_track: Dict[str, int] = {}
        for i, o in enumerate(ops):
            p = last_on_track.get(o.track)
            if p is not None:
                sim_preds[i].add(p)
            last_on_track[o.track] = i
    cp = measured_critical_path(ops, causal,
                                envelope_us=joined.envelope_us)
    bubbles = _mesh_bubbles(ops, joined.t0_us, joined.envelope_us,
                            schedule)
    transfers = _transfer_breakdown(ops, joined.pool_spans, run_stats)
    stages = _stage_mfu(ops, stage_execs, peak_tflops)
    return StepPerfReport(
        source=joined.source, mode=mode, envelope_us=joined.envelope_us,
        n_ops=len(ops), aligned=joined.aligned, critical_path=cp,
        bubbles=bubbles, transfers=transfers, stages=stages,
        notes=notes,
        sim_durs_us=[o.dur_us for o in ops],
        sim_preds=[tuple(sorted(p)) for p in sim_preds],
        sim_ops=list(ops))


########################################
# raw Chrome-trace entry point (scripts/perf_tool.py)
########################################


def spans_from_chrome(trace: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Reconstruct completed spans (name/category/ts_us/dur_us/track)
    from Chrome-trace B/E pairs, joining the ``M`` thread_name records
    so per-track identity survives the round trip."""
    track_of: Dict[Tuple[int, int], str] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            track_of[(e.get("pid", 0), e["tid"])] = e["args"]["name"]
    stacks: Dict[Tuple[int, int], List[Dict[str, Any]]] = \
        collections.defaultdict(list)
    spans: List[Dict[str, Any]] = []
    events = sorted(
        (e for e in trace.get("traceEvents", [])
         if e.get("ph") in ("B", "E")),
        key=lambda e: (e["ts"], 0 if e["ph"] == "E" else 1))
    for e in events:
        key = (e.get("pid", 0), e["tid"])
        if e["ph"] == "B":
            stacks[key].append(e)
        elif stacks[key]:
            b = stacks[key].pop()
            spans.append({
                "name": b["name"],
                "category": b.get("cat", ""),
                "ts_us": b["ts"],
                "dur_us": e["ts"] - b["ts"],
                "track": track_of.get(key, f"tid {key[1]}"),
                "args": b.get("args"),
            })
    spans.sort(key=lambda s: s["ts_us"])
    return spans


def report_from_trace(trace: Dict[str, Any],
                      peak_tflops: Optional[float] = None
                      ) -> Optional[StepPerfReport]:
    """Analyze a saved Chrome trace (no program/graph available —
    track-order analysis of the last ``pipeshard.step`` envelope)."""
    joined = _join_spans(spans_from_chrome(trace), None)
    if joined is None:
        return None
    return build_step_report(joined, peak_tflops=peak_tflops)


########################################
# registry gauges (ISSUE 9 metric families)
########################################

_PERF_REG = _tmetrics.get_registry()
_STAGE_MFU_GAUGE = _PERF_REG.gauge(
    "alpa_stage_mfu",
    "Last analyzed step's model-FLOPs utilization per pipeline stage",
    labelnames=("stage",))
_BUBBLE_GAUGE = _PERF_REG.gauge(
    "alpa_step_bubble_fraction",
    "Last analyzed step's per-mesh idle fraction of the step envelope",
    labelnames=("mesh",))
_CRITICAL_PATH_GAUGE = _PERF_REG.gauge(
    "alpa_critical_path_us",
    "Last analyzed step's measured critical-path op time")
_GATE_TOTAL = _PERF_REG.counter(
    "alpa_perf_gate_total",
    "Perf regression gate verdicts (benchmark/perf_gate.py)",
    labelnames=("result",))


def publish_report(report: StepPerfReport) -> None:
    """Fold one report into the central registry (GET /metrics)."""
    _CRITICAL_PATH_GAUGE.set(report.critical_path.total_us)
    for track, b in report.bubbles.items():
        label = track.split()[1] if track.startswith("mesh ") else track
        _BUBBLE_GAUGE.labels(label).set(b.bubble_fraction)
    for name, s in report.stages.items():
        _STAGE_MFU_GAUGE.labels(name).set(s.mfu)


def record_gate_verdict(passed: bool) -> None:
    _GATE_TOTAL.labels("pass" if passed else "fail").inc()
