"""Measured-cost calibration store + model-drift observability (ISSUE 12).

The planners (stage DP + intra-op ILP + the resharding strategy chooser)
plan from analytic alpha-beta cost models, while every production step
already *measures* the truth (ISSUE 9: per-stage RUN spans,
``reshard.wire`` spans, the step critical path) — and threw it away.
This module closes the loop:

* **CalibrationStore** — a persistent, content-addressed store (one JSON
  file per entry under ``ALPA_TPU_CALIBRATION_DIR``, atomic writes like
  ``compile_cache.py``) that ingests :class:`StepPerfReport` spans and
  accumulates robust statistics (median / p90 / EWMA / sample count) per
  stable signature:

  - ``stage_run`` — per-stage RUN cost, keyed by the stage label
    (``stage:<name>``) for observability/replays and by the stage cost
    fingerprint (``stage_cost:flops=…|ndev=…``) for planner consult;
  - ``reshard_wire`` — per-edge wire cost, keyed by the edge label
    (``edge:<src>-><dst>``) and by the PR 7 reshard-edge signature
    (``wire:<shape>x<itemsize>|<src>-><dst>|<strategy>``);
  - ``collective`` — intra-mesh collective cost keyed like
    ``mesh_profiling``'s alpha-beta tables
    (``collective:<kind>|bytes=2^k``).

* **Drift observability** — every calibrated entry carries the analytic
  prediction it supersedes; the worst measured/modeled divergence per
  kind is exported live as ``alpa_cost_model_drift_ratio{kind}`` and
  sample totals as ``alpa_calibration_samples_total{kind}``, dumped as
  ``calibration.txt`` by ``monitoring.dump_debug_info``, and printed by
  ``scripts/perf_tool.py drift``.

* **Replan keying** — :func:`calibration_cache_token` folds the store
  fingerprint into the stage-DP / ILP / reshard-strategy cache keys
  *only* when ``replan_mode != "off"``, so off-mode plans and cache
  keys stay byte-identical to a build without calibration, while a warm
  restart against an unchanged store replays every calibrated solve
  from the compile cache (0 solves, identical fingerprints).

Consumers: ``cross_mesh_resharding.choose_strategy`` (wire + collective
legs), ``mesh_profiling.estimate_stage_cost`` (stage compute), and
``PipeshardDriverExecutable.consider_replan`` (the suggest/auto replan
driver).  ``benchmark/replan_bench.py`` replays the committed fixture
trace through calibrate→replan and gates the result.
"""
import dataclasses
import hashlib
import json
import logging
import math
import os
import re
import tempfile
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from alpa_tpu.telemetry import metrics as _tmetrics

logger = logging.getLogger(__name__)

__all__ = [
    "CALIBRATION_FORMAT_VERSION", "CalibrationEntry", "CalibrationStore",
    "get_calibration_store", "reset_calibration_store", "replan_active",
    "calibration_cache_token",
    "stage_signature", "stage_cost_signature", "wire_signature",
    "edge_signature", "collective_signature",
    "ingest_joined", "ingest_report", "ingest_chrome_trace",
    "drift_table", "format_calibration_report",
]

# Bump to invalidate persisted entries on layout changes; entries with a
# different stamp are skipped (warned), never mis-parsed.  v2 (ISSUE 19)
# split the reshard_wire signature on the transfer codec (``|codec=``):
# before that a quantized edge's measured samples silently re-priced the
# full-precision signature.  v1 entries migrate on load (wire entries
# get ``|codec=none`` appended; everything else just re-stamps) with a
# format warning, like the PROF_DB legacy path.
CALIBRATION_FORMAT_VERSION = 2

# Bounded reservoir: the most recent N samples back the median/p90 so
# one entry file stays O(1) and old regimes age out.
MAX_SAMPLES = 64

# EWMA smoothing factor for the trend statistic.
EWMA_ALPHA = 0.25

_RESHARD_NAME_RE = re.compile(
    r"RESHARD\s+(\S+?)->(\S+?)(?:\s+mb\d+)?(?:\s+\[.*\])?$")
_RUN_NAME_RE = re.compile(r"RUN\s+(\S+?)(?:\s+mb\d+)?$")


########################################
# signatures
########################################


def stage_signature(stage_name: str) -> str:
    """Label-keyed stage signature (what a trace span names)."""
    return f"stage:{stage_name}"


def stage_cost_signature(flops: float, n_devices: int) -> str:
    """Planner-consult stage signature: the same (flops, submesh size)
    fingerprint ``estimate_stage_cost`` computes at plan time — content
    addressed, so it matches across compile and runtime without names."""
    return f"stage_cost:flops={float(flops):.6e}|ndev={int(n_devices)}"


def edge_signature(src: str, dst: str) -> str:
    """Label-keyed reshard-edge signature (what a trace span names)."""
    return f"edge:{src}->{dst}"


def wire_signature(shape, itemsize, src_key: str, dst_key: str,
                   strategy: str, codec: Optional[str] = None) -> str:
    """Planner-consult edge signature: the PR 7 reshard-edge identity
    (shape, itemsize, device-id-free sharding keys) plus the executed
    strategy — only the strategy that actually ran gets its cost
    overridden; the alternatives stay analytic.  ``codec`` (ISSUE 19)
    keeps quantized and full-precision prices in separate buckets: a
    quantized edge moves ~4x fewer bytes, so its measured samples must
    never re-price the lossless signature."""
    return (f"wire:{tuple(shape)}x{int(itemsize)}|"
            f"{src_key}->{dst_key}|{strategy}|codec={codec or 'none'}")


def collective_signature(kind: str, nbytes: float) -> str:
    """Collective cost signature, keyed like mesh_profiling's alpha-beta
    tables: kind + a power-of-two byte bucket (so nearby sizes share an
    entry the way an (alpha, beta) fit shares a line)."""
    bucket = int(math.log2(max(float(nbytes), 1.0)))
    return f"collective:{kind}|bytes=2^{bucket}"


########################################
# store
########################################


@dataclasses.dataclass
class CalibrationEntry:
    """Robust statistics for one (kind, signature) cost."""
    kind: str
    signature: str
    samples: List[float] = dataclasses.field(default_factory=list)
    count: int = 0
    ewma_us: float = 0.0
    # the analytic prediction this entry supersedes (drift denominator);
    # None when the caller could not price the op analytically
    modeled_us: Optional[float] = None
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def _quantile(self, q: float) -> float:
        s = sorted(self.samples)
        if not s:
            return 0.0
        idx = q * (len(s) - 1)
        lo = int(idx)
        hi = min(lo + 1, len(s) - 1)
        return s[lo] + (s[hi] - s[lo]) * (idx - lo)

    @property
    def median_us(self) -> float:
        return self._quantile(0.5)

    @property
    def p90_us(self) -> float:
        return self._quantile(0.9)

    @property
    def drift_ratio(self) -> Optional[float]:
        """measured median / analytic prediction; >1 = the model was
        optimistic, <1 = pessimistic, None = no prediction on file."""
        if self.modeled_us is None or self.modeled_us <= 0:
            return None
        return self.median_us / self.modeled_us

    def observe(self, measured_us: float,
                modeled_us: Optional[float] = None,
                meta: Optional[Dict[str, Any]] = None):
        self.samples.append(float(measured_us))
        if len(self.samples) > MAX_SAMPLES:
            del self.samples[:len(self.samples) - MAX_SAMPLES]
        self.count += 1
        self.ewma_us = (float(measured_us) if self.count == 1 else
                        (1 - EWMA_ALPHA) * self.ewma_us +
                        EWMA_ALPHA * float(measured_us))
        if modeled_us is not None:
            self.modeled_us = float(modeled_us)
        if meta:
            self.meta.update(meta)

    def to_json(self) -> Dict[str, Any]:
        return {
            "format": CALIBRATION_FORMAT_VERSION,
            "kind": self.kind,
            "signature": self.signature,
            "samples": [round(s, 4) for s in self.samples],
            "count": self.count,
            "ewma_us": round(self.ewma_us, 4),
            "modeled_us": (round(self.modeled_us, 4)
                           if self.modeled_us is not None else None),
            "meta": self.meta,
        }

    @classmethod
    def from_json(cls, data: Dict[str, Any]) -> "CalibrationEntry":
        return cls(kind=data["kind"], signature=data["signature"],
                   samples=[float(s) for s in data.get("samples", [])],
                   count=int(data.get("count", 0)),
                   ewma_us=float(data.get("ewma_us", 0.0)),
                   modeled_us=data.get("modeled_us"),
                   meta=dict(data.get("meta", {})))


class CalibrationStore:
    """In-memory mirror + optional on-disk tier of calibrated costs.

    Disk layout mirrors ``compile_cache.py``: one file per entry named
    ``<kind>-<sha256(signature)[:16]>.json``, published with tempfile +
    ``os.replace`` so concurrent readers only ever see complete JSON.
    """

    def __init__(self, store_dir: Optional[str] = None):
        self.store_dir = store_dir or None
        self._entries: Dict[Tuple[str, str], CalibrationEntry] = {}
        self._lock = threading.Lock()
        if self.store_dir:
            self._load_dir()

    # -- persistence ---------------------------------------------------

    def _path_of(self, entry: CalibrationEntry) -> Optional[str]:
        if not self.store_dir:
            return None
        digest = hashlib.sha256(entry.signature.encode()).hexdigest()[:16]
        return os.path.join(self.store_dir, f"{entry.kind}-{digest}.json")

    def _load_dir(self):
        if not os.path.isdir(self.store_dir):
            return
        for name in sorted(os.listdir(self.store_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.store_dir, name)
            try:
                with open(path, encoding="utf-8") as f:
                    data = json.load(f)
                fmt = int(data.get("format", 0))
                if fmt == 1:
                    # v1 -> v2 migration (ISSUE 19): wire signatures
                    # gained a ``|codec=`` suffix; pre-split samples
                    # were necessarily full-precision, so they land in
                    # the ``codec=none`` bucket.  Other kinds are
                    # layout-identical and just re-stamp.
                    sig = str(data.get("signature", ""))
                    if (sig.startswith("wire:") and
                            "|codec=" not in sig):
                        data["signature"] = sig + "|codec=none"
                    logger.warning(
                        "calibration entry %s has format 1 (want %s); "
                        "migrating and re-stamping", path,
                        CALIBRATION_FORMAT_VERSION)
                    entry = CalibrationEntry.from_json(data)
                    self._entries[(entry.kind, entry.signature)] = entry
                    new_path = self._path_of(entry)
                    self._save_entry(entry)
                    if new_path and new_path != path:
                        try:
                            os.remove(path)
                        except OSError:
                            pass
                    continue
                if fmt != CALIBRATION_FORMAT_VERSION:
                    logger.warning(
                        "calibration entry %s has format %s (want %s); "
                        "skipping", path, data.get("format"),
                        CALIBRATION_FORMAT_VERSION)
                    continue
                entry = CalibrationEntry.from_json(data)
                self._entries[(entry.kind, entry.signature)] = entry
            except Exception as e:  # pylint: disable=broad-except
                logger.warning("calibration entry %s unreadable (%s); "
                               "skipping", path, e)

    def _save_entry(self, entry: CalibrationEntry):
        path = self._path_of(entry)
        if not path:
            return
        try:
            os.makedirs(self.store_dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.store_dir,
                                       prefix=".tmp-" + entry.kind)
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    json.dump(entry.to_json(), f, indent=1)
                os.replace(tmp, path)  # atomic publish
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
        except Exception as e:  # pylint: disable=broad-except
            # the disk tier is an optimization; a read-only disk must
            # never fail a step
            logger.warning("calibration store write %s failed: %s",
                           path, e)

    # -- core API ------------------------------------------------------

    def observe(self, kind: str, signature: str, measured_us: float,
                modeled_us: Optional[float] = None,
                meta: Optional[Dict[str, Any]] = None) -> CalibrationEntry:
        """Fold one measured sample into the store (and its disk tier)."""
        with self._lock:
            entry = self._entries.get((kind, signature))
            if entry is None:
                entry = CalibrationEntry(kind=kind, signature=signature)
                self._entries[(kind, signature)] = entry
            entry.observe(measured_us, modeled_us=modeled_us, meta=meta)
        self._save_entry(entry)
        return entry

    def set_modeled(self, kind: str, signature: str, modeled_us: float):
        """Attach/overwrite the analytic prediction an entry supersedes
        (callers that learn the model's price after ingesting spans)."""
        with self._lock:
            entry = self._entries.get((kind, signature))
            if entry is None:
                return
            entry.modeled_us = float(modeled_us)
        self._save_entry(entry)

    def get(self, kind: str, signature: str) -> Optional[CalibrationEntry]:
        with self._lock:
            return self._entries.get((kind, signature))

    def entries(self) -> List[CalibrationEntry]:
        with self._lock:
            return sorted(self._entries.values(),
                          key=lambda e: (e.kind, e.signature))

    def __len__(self) -> int:
        return len(self._entries)

    def measured_us(self, kind: str, signature: str,
                    min_samples: Optional[int] = None) -> Optional[float]:
        """The calibrated cost (median µs), or None below the sample
        floor (``calibration_min_samples``) — the analytic fallback."""
        entry = self.get(kind, signature)
        if entry is None:
            return None
        if min_samples is None:
            from alpa_tpu.global_env import global_config
            min_samples = int(getattr(global_config,
                                      "calibration_min_samples", 3))
        if entry.count < max(int(min_samples), 1):
            return None
        return entry.median_us

    def fingerprint(self) -> str:
        """Content hash over the calibrated costs the planners would
        consult: (kind, signature, rounded median/p90).  Counts are
        deliberately excluded so re-ingesting an identical workload does
        not churn cache keys; a cost that actually moved does."""
        h = hashlib.sha256()
        for e in self.entries():
            h.update(f"{e.kind}|{e.signature}|{e.median_us:.3f}|"
                     f"{e.p90_us:.3f}\n".encode())
        return h.hexdigest()

    def clear(self):
        with self._lock:
            self._entries.clear()
        if self.store_dir and os.path.isdir(self.store_dir):
            for name in os.listdir(self.store_dir):
                if name.endswith(".json"):
                    try:
                        os.remove(os.path.join(self.store_dir, name))
                    except OSError:
                        pass


########################################
# process-global store
########################################

_global_store: Optional[CalibrationStore] = None
_global_lock = threading.Lock()


def get_calibration_store() -> CalibrationStore:
    """The process-global store, built from
    ``global_config.calibration_dir`` on first use."""
    global _global_store
    with _global_lock:
        if _global_store is None:
            from alpa_tpu.global_env import global_config
            _global_store = CalibrationStore(
                store_dir=getattr(global_config, "calibration_dir", None))
        return _global_store


def reset_calibration_store(store: Optional[CalibrationStore] = None):
    """Install ``store`` (or lazily rebuild from global_config) — test
    isolation and ``calibration_dir`` changes."""
    global _global_store
    with _global_lock:
        _global_store = store


def replan_active() -> bool:
    """True when measured costs may influence planning
    (``replan_mode`` is ``suggest`` or ``auto``)."""
    from alpa_tpu.global_env import global_config
    return getattr(global_config, "replan_mode", "off") != "off"


def calibration_cache_token() -> Optional[str]:
    """The cache-key part planners append when replanning is active:
    ``None`` under ``replan_mode=off`` (keys stay byte-identical to a
    build without calibration), else ``cal:<store fingerprint>`` — so a
    calibrated re-solve caches like any other plan and a warm restart
    with an unchanged store replays it with zero solves."""
    if not replan_active():
        return None
    return f"cal:{get_calibration_store().fingerprint()}"


########################################
# ingestion: trace / flight spans -> store entries
########################################


def _edge_from_name(name: str) -> Optional[Tuple[str, str]]:
    m = _RESHARD_NAME_RE.search(name)
    if m is None:
        return None
    return m.group(1), m.group(2)


def _stage_from_name(name: str) -> Optional[str]:
    m = _RUN_NAME_RE.match(name)
    if m is None:
        return None
    return m.group(1)


def _wire_samples_from_pool(pool_spans: Sequence[Dict[str, Any]]
                            ) -> Dict[Tuple[str, str], List[float]]:
    """Per-edge wire samples from the overlap pool tracks: each labeled
    parent transfer span (``RESHARD a->b …``) names the edge; its
    ``reshard.wire`` child (contained in the parent window, same track)
    carries the actual transfer execution time."""
    parents = []
    wires = []
    for s in pool_spans:
        edge = _edge_from_name(s.get("name", ""))
        if edge is not None:
            parents.append((s, edge))
        elif s.get("name") == "reshard.wire":
            wires.append(s)
    out: Dict[Tuple[str, str], List[float]] = {}
    used = set()
    for parent, edge in parents:
        p0 = parent["ts_us"]
        p1 = p0 + parent["dur_us"]
        for i, w in enumerate(wires):
            if i in used or w.get("track") != parent.get("track"):
                continue
            if w["ts_us"] >= p0 - 1e-6 and \
                    w["ts_us"] + w["dur_us"] <= p1 + 1e-6:
                used.add(i)
                out.setdefault(edge, []).append(w["dur_us"])
                break
    return out


def _wire_samples_from_ops(ops) -> Dict[Tuple[str, str], List[float]]:
    """Flight-ring fallback (no pool tracks): one wire sample per
    matched LAUNCH/WAIT pair — submit-to-retire minus nothing, i.e. the
    driver-visible envelope of the transfer.  Coarser than the pool's
    ``reshard.wire`` split, but the keys and sample counts match the
    traced path, so a store fed only from the flight ring calibrates
    the same signatures."""
    launches: Dict[str, Any] = {}
    out: Dict[Tuple[str, str], List[float]] = {}
    for op in ops:
        name = op.name
        if name.startswith("LAUNCH"):
            launches[name.replace("LAUNCH", "", 1).strip()] = op
        elif name.startswith("WAIT"):
            body = name.replace("WAIT", "", 1).strip()
            edge = _edge_from_name(body)
            if edge is None:
                continue
            launch = launches.pop(body, None)
            t0 = launch.t0_us if launch is not None else op.t0_us
            out.setdefault(edge, []).append(max(0.0, op.t1_us - t0))
    return out


def _quantile_of(samples: Sequence[float], q: float) -> float:
    s = sorted(samples)
    if not s:
        return 0.0
    idx = q * (len(s) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(s) - 1)
    return s[lo] + (s[hi] - s[lo]) * (idx - lo)


_STRATEGY_TAG_RE = re.compile(r"\[(\S+)\]\s*$")


def _strategy_from_name(name: str) -> str:
    """The runtime labels non-default edges ``RESHARD a->b [strategy]``
    (runtime_emitter); an untagged label means the planner's default
    direct_p2p path."""
    m = _STRATEGY_TAG_RE.search(name)
    return m.group(1) if m else "direct_p2p"


def _bytes_from_args(args: Optional[Dict[str, Any]]) -> Optional[float]:
    if not isinstance(args, dict):
        return None
    for key in ("wire_bytes", "nbytes", "bytes"):
        v = args.get(key)
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
    return None


def edge_wire_table(joined) -> List[Dict[str, Any]]:
    """Per-reshard-edge wire rows for one joined step — the
    human-readable view of exactly what :func:`ingest_joined` stores
    under ``reshard_wire``.  Prefers the pool tracks' ``reshard.wire``
    children (matched to their labeled parent like the ingest path);
    falls back to LAUNCH/WAIT envelopes when the trace has no pool
    tracks.  ``bytes``/``gbps`` are filled from span args when the
    producer recorded them, else ``None``."""
    rows: Dict[Tuple[str, str, str], Dict[str, Any]] = {}

    def add(src, dst, strategy, wire_us, nbytes):
        key = (src, dst, strategy)
        row = rows.setdefault(key, {
            "src": src, "dst": dst, "strategy": strategy,
            "samples": [], "bytes": None,
        })
        row["samples"].append(wire_us)
        if nbytes is not None:
            row["bytes"] = nbytes

    parents = []
    wires = []
    for s in joined.pool_spans:
        edge = _edge_from_name(s.get("name", ""))
        if edge is not None:
            parents.append((s, edge))
        elif s.get("name") == "reshard.wire":
            wires.append(s)
    used = set()
    for parent, edge in parents:
        p0 = parent["ts_us"]
        p1 = p0 + parent["dur_us"]
        for i, w in enumerate(wires):
            if i in used or w.get("track") != parent.get("track"):
                continue
            if w["ts_us"] >= p0 - 1e-6 and \
                    w["ts_us"] + w["dur_us"] <= p1 + 1e-6:
                used.add(i)
                add(edge[0], edge[1],
                    _strategy_from_name(parent.get("name", "")),
                    w["dur_us"],
                    _bytes_from_args(w.get("args"))
                    or _bytes_from_args(parent.get("args")))
                break
    if not rows:
        launches: Dict[str, Any] = {}
        for op in joined.ops:
            name = op.name
            if name.startswith("LAUNCH"):
                launches[name.replace("LAUNCH", "", 1).strip()] = op
            elif name.startswith("WAIT"):
                body = name.replace("WAIT", "", 1).strip()
                edge = _edge_from_name(body)
                if edge is None:
                    continue
                launch = launches.pop(body, None)
                t0 = launch.t0_us if launch is not None else op.t0_us
                add(edge[0], edge[1], _strategy_from_name(body),
                    max(0.0, op.t1_us - t0), None)

    out = []
    for (src, dst, strategy), row in sorted(rows.items()):
        samples = sorted(row["samples"])
        median = _quantile_of(samples, 0.5)
        nbytes = row["bytes"]
        gbps = None
        if nbytes is not None and median > 0:
            gbps = nbytes / (median * 1e-6) / 1e9
        out.append({
            "src": src, "dst": dst, "strategy": strategy,
            "n": len(samples),
            "median_us": median,
            "p90_us": _quantile_of(samples, 0.9),
            "total_us": sum(samples),
            "bytes": nbytes,
            "gbps": gbps,
        })
    out.sort(key=lambda r: -r["total_us"])
    return out


def format_edge_table(rows: Sequence[Dict[str, Any]]) -> str:
    """Fixed-width render of :func:`edge_wire_table` rows."""
    if not rows:
        return "no reshard wire spans in step"
    lines = [f"{'edge':<28} {'strategy':<14} {'n':>3} "
             f"{'median us':>10} {'p90 us':>10} {'bytes':>10} "
             f"{'GB/s':>7}"]
    for r in rows:
        nbytes = ("-" if r["bytes"] is None
                  else f"{int(r['bytes'])}")
        gbps = "-" if r["gbps"] is None else f"{r['gbps']:.2f}"
        lines.append(
            f"{r['src'] + '->' + r['dst']:<28} {r['strategy']:<14} "
            f"{r['n']:>3} {r['median_us']:>10.1f} {r['p90_us']:>10.1f} "
            f"{nbytes:>10} {gbps:>7}")
    return "\n".join(lines)


def ingest_joined(joined, store: Optional[CalibrationStore] = None,
                  modeled: Optional[Dict[str, float]] = None
                  ) -> Dict[str, int]:
    """Ingest one joined step (trace or flight source) into the store.

    ``modeled`` optionally maps label signatures (``stage:…`` /
    ``edge:…``) to the analytic prediction in µs, recorded as the drift
    denominator.  Returns ``{signature: n_new_samples}``."""
    store = store if store is not None else get_calibration_store()
    modeled = modeled or {}
    ingested: Dict[str, int] = {}

    def put(kind, sig, samples, meta=None):
        for v in samples:
            store.observe(kind, sig, v, modeled_us=modeled.get(sig),
                          meta=meta)
        if samples:
            ingested[sig] = ingested.get(sig, 0) + len(samples)

    by_stage: Dict[str, List[float]] = {}
    for op in joined.ops:
        stage = _stage_from_name(op.name)
        if stage is not None:
            by_stage.setdefault(stage, []).append(op.dur_us)
    for stage, samples in sorted(by_stage.items()):
        put("stage_run", stage_signature(stage), samples,
            meta={"stage": stage, "source": joined.source})

    wire = _wire_samples_from_pool(joined.pool_spans)
    if not wire:
        wire = _wire_samples_from_ops(joined.ops)
    for (src, dst), samples in sorted(wire.items()):
        put("reshard_wire", edge_signature(src, dst), samples,
            meta={"src": src, "dst": dst, "source": joined.source})
    return ingested


def ingest_report(report, store: Optional[CalibrationStore] = None,
                  modeled: Optional[Dict[str, float]] = None
                  ) -> Dict[str, int]:
    """Ingest a built :class:`StepPerfReport` via its re-simulation ops.

    Wire-leg detail (``reshard.wire`` pool spans) is not carried on the
    report, so edges ingest through the LAUNCH/WAIT fallback — callers
    holding the :class:`JoinedStep` should prefer :func:`ingest_joined`.
    """
    store = store if store is not None else get_calibration_store()

    class _Shim:
        ops = report.sim_ops
        pool_spans: List[Dict[str, Any]] = []
        source = report.source

    return ingest_joined(_Shim, store=store, modeled=modeled)


def ingest_chrome_trace(trace: Dict[str, Any],
                        store: Optional[CalibrationStore] = None,
                        modeled: Optional[Dict[str, float]] = None
                        ) -> Dict[str, int]:
    """Ingest a saved Chrome trace (scripts / replan_bench entry point):
    the last ``pipeshard.step`` envelope's spans, joined exactly like
    the perf analyzer joins them."""
    from alpa_tpu.telemetry import perf as _perf
    joined = _perf._join_spans(  # pylint: disable=protected-access
        _perf.spans_from_chrome(trace), None)
    if joined is None:
        return {}
    return ingest_joined(joined, store=store, modeled=modeled)


########################################
# drift observability
########################################


def drift_table(store: Optional[CalibrationStore] = None,
                top: int = 0) -> List[Dict[str, Any]]:
    """Calibrated entries ranked by divergence from their analytic
    prediction (worst first; entries without a prediction sort last).
    ``top`` truncates (0 = all)."""
    store = store if store is not None else get_calibration_store()
    rows = []
    for e in store.entries():
        ratio = e.drift_ratio
        rows.append({
            "kind": e.kind,
            "signature": e.signature,
            "count": e.count,
            "median_us": round(e.median_us, 3),
            "p90_us": round(e.p90_us, 3),
            "ewma_us": round(e.ewma_us, 3),
            "modeled_us": (round(e.modeled_us, 3)
                           if e.modeled_us is not None else None),
            "drift_ratio": (round(ratio, 4) if ratio is not None
                            else None),
        })
    rows.sort(key=lambda r: (-abs(math.log(r["drift_ratio"]))
                             if r["drift_ratio"] else 0.0,
                             r["kind"], r["signature"]))
    return rows[:top] if top else rows


def format_calibration_report(store: Optional[CalibrationStore] = None
                              ) -> str:
    """``calibration.txt`` content for ``dump_debug_info`` (and
    ``scripts/perf_tool.py drift``)."""
    from alpa_tpu.global_env import global_config
    store = store if store is not None else get_calibration_store()
    rows = drift_table(store)
    mode = getattr(global_config, "replan_mode", "off")
    head = (f"calibration store: {len(rows)} entries, "
            f"replan_mode={mode}, "
            f"min_samples={getattr(global_config, 'calibration_min_samples', 3)}, "
            f"dir={store.store_dir or '(memory-only)'}")
    if not rows:
        return head + "\n(no measurements ingested yet)"
    lines = [head, f"fingerprint: {store.fingerprint()[:16]}", "",
             f"{'kind':<13} {'n':>4} {'median_us':>10} {'p90_us':>10} "
             f"{'modeled_us':>10} {'drift':>7}  signature"]
    for r in rows:
        modeled = (f"{r['modeled_us']:10.3f}"
                   if r["modeled_us"] is not None else f"{'-':>10}")
        drift = (f"{r['drift_ratio']:7.3f}"
                 if r["drift_ratio"] is not None else f"{'-':>7}")
        lines.append(
            f"{r['kind']:<13} {r['count']:>4} {r['median_us']:>10.3f} "
            f"{r['p90_us']:>10.3f} {modeled} {drift}  {r['signature']}")
    return "\n".join(lines)


########################################
# registry gauges (live on GET /metrics)
########################################
# The store object is swapped per-test (reset_calibration_store), so the
# registry pulls the LIVE instance's stats at collect time — the same
# collector pattern compile_cache.py uses.

_REG = _tmetrics.get_registry()
_DRIFT_GAUGE = _REG.gauge(
    "alpa_cost_model_drift_ratio",
    "Worst measured/modeled cost divergence per calibration kind "
    "(>1 = analytic model optimistic)",
    labelnames=("kind",))
_SAMPLES_GAUGE = _REG.gauge(
    "alpa_calibration_samples_total",
    "Measured cost samples ingested into the calibration store, per kind",
    labelnames=("kind",))


def _collect_calibration(_registry):
    store = _global_store
    _DRIFT_GAUGE.reset()
    _SAMPLES_GAUGE.reset()
    if store is None:
        return
    samples: Dict[str, int] = {}
    worst: Dict[str, float] = {}
    for e in store.entries():
        samples[e.kind] = samples.get(e.kind, 0) + e.count
        ratio = e.drift_ratio
        if ratio is not None and ratio > 0:
            prev = worst.get(e.kind)
            if prev is None or abs(math.log(ratio)) > abs(math.log(prev)):
                worst[e.kind] = ratio
    for kind, n in samples.items():
        _SAMPLES_GAUGE.labels(kind).set(n)
    for kind, ratio in worst.items():
        _DRIFT_GAUGE.labels(kind).set(ratio)


_REG.register_collector(_collect_calibration)
