"""Device mesh abstractions for alpa_tpu.

TPU-native redesign of the reference's ``alpa/device_mesh.py`` (2506 LoC of
Ray actors + uuid buffer dicts).  The class ladder survives —

  DeviceCluster -> PhysicalDeviceMeshGroup -> PhysicalDeviceMesh
  (+ compile-time VirtualPhysicalMesh / LogicalDeviceMesh)

— but the runtime underneath is jax single-controller:

* ``MeshHostWorker`` Ray actors (ref device_mesh.py:107) are gone.  Under
  ``jax.distributed`` every host runs the same program; per-host work is
  expressed with global ``jax.Array``s and shardings, not RPCs.
* uuid->PyLocalBuffer dicts (ref device_mesh.py:165-237) become ``jax.Array``
  handles; ``DistributedArray`` (ref :1509) IS ``jax.Array`` with a
  ``NamedSharding`` — we keep a thin alias plus helpers.
* The XLA gRPC distributed service bring-up (ref :1057-1148) maps to
  ``jax.distributed.initialize`` on TPU pods.

``LogicalDeviceMesh`` keeps the alpha-beta collective cost model role
(ref device_mesh.py:686-772 + shard_parallel/auto_sharding.py:81-141) with
ICI/DCN constants instead of NVLink/EFA ones.
"""
import itertools
import logging
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from alpa_tpu.global_env import global_config

logger = logging.getLogger(__name__)

# "DistributedArray" in the reference is a driver-side wrapper over per-host
# shards (ref device_mesh.py:1509).  jax.Array already is exactly that.
DistributedArray = jax.Array


def prefetch(tree):
    """Start async device->host copies for every array in ``tree`` (ref
    ``alpa.prefetch``, device_mesh.py: fetches DistributedArray data
    ahead of use).  Under the single-controller design this is
    ``copy_to_host_async`` on each jax.Array leaf."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                leaf.copy_to_host_async()
            except Exception:  # pylint: disable=broad-except
                pass  # already-deleted/committed-host arrays


def get_global_num_devices() -> int:
    """Device count of the active cluster (ref
    ``alpa.get_global_num_devices``); falls back to jax.device_count()
    before init()."""
    cluster = get_global_cluster()
    if cluster is not None:
        return cluster.num_devices
    # honor the configured backend exactly as DeviceCluster would, so
    # the count cannot change across init()
    return jax.device_count(global_config.backend) \
        if global_config.backend else jax.device_count()


########################################
# Logical mesh + collective cost model
########################################

class LogicalDeviceMesh:
    """A multi-dimensional logical view of devices with an alpha-beta
    collective cost model per mesh axis.

    Mirrors the role of ref ``shard_parallel/auto_sharding.py:81`` (cost
    queries: all_gather/all_reduce/reduce_scatter/all_to_all) and
    ``device_mesh.py:686-772`` (construction from a physical mesh).  Costs are
    in abstract seconds: ``alpha`` latency per hop, ``beta`` inverse-bandwidth
    seconds/byte along that axis.
    """

    def __init__(self,
                 physical_mesh: Optional["PhysicalDeviceMesh"],
                 id_mesh: np.ndarray,
                 mesh_alpha: Optional[Sequence[float]] = None,
                 mesh_beta: Optional[Sequence[float]] = None,
                 calibration: Optional[Any] = None):
        self.physical_mesh = physical_mesh
        self.id_mesh = np.asarray(id_mesh)
        # Default constants: axis 0 = slower axis (DCN / cross-host),
        # axis 1.. = ICI.  Values chosen so the ratio (not scale) drives
        # decisions, as in the reference's (1, 0.01)/(1, 0.1) defaults.
        ndim = self.id_mesh.ndim
        self.mesh_alpha = tuple(mesh_alpha) if mesh_alpha else (1.0,) * ndim
        if mesh_beta:
            self.mesh_beta = tuple(mesh_beta)
        else:
            self.mesh_beta = tuple([0.1] + [0.01] * (ndim - 1))[:ndim]
        # Measured per-collective (alpha s, beta s/byte) fits
        # (mesh_profiling.CalibratedCostModel); when present every cost
        # query returns real seconds instead of abstract units.
        self.calibration = calibration

    @property
    def calibrated(self) -> bool:
        """True only when collective costs come back in real seconds.
        A dot-only calibration (e.g. profiled on a single chip) must NOT
        count: estimate_stage_cost would read abstract alpha-beta units
        as seconds and inflate comm costs ~1e7x."""
        return (self.calibration is not None and
                bool(self.calibration.collective_ab))

    def _ab(self, kind: str, mesh_dim: int):
        """(alpha, beta, tie) for one collective kind on one axis.  The
        tie term keeps the abstract model's AG > AR > RS > A2A bias; with
        a measured calibration the real numbers differentiate choices, so
        the tie is dropped.  The calibration is measured on the fast
        (intra-host/ICI) fabric; a slower axis (higher abstract beta,
        e.g. DCN) scales the measured beta by the abstract ratio so the
        cross-host penalty survives calibration.  A kind that was not
        measured borrows the most expensive measured kind's fit so every
        cost query stays in one unit system (seconds)."""
        if self.calibrated:
            ab = self.calibration.alpha_beta(kind)
            if ab is None:
                ab = max(self.calibration.collective_ab.values(),
                         key=lambda p: p[1])
            ratio = self.mesh_beta[mesh_dim] / min(self.mesh_beta)
            return ab[0], ab[1] * ratio, 0.0
        ties = {"all_gather": 0.1, "all_reduce": 0.01,
                "reduce_scatter": 0.001, "all_to_all": 0.001,
                "ppermute": 0.0005}
        return (self.mesh_alpha[mesh_dim], self.mesh_beta[mesh_dim],
                ties[kind])

    @property
    def shape(self):
        return self.id_mesh.shape

    @property
    def num_devices(self):
        return int(self.id_mesh.size)

    # ----- alpha-beta collective costs (per-byte, along one mesh dim) -----
    # Standard ring-algorithm cost model.  0.1 base latency term matches the
    # spirit of the reference's constant overhead addend.

    def all_gather_cost(self, num_bytes: float, mesh_dim: int) -> float:
        n = self.shape[mesh_dim]
        if n == 1:
            return 0.0
        a, b, tie = self._ab("all_gather", mesh_dim)
        return a + b * (n - 1) / n * num_bytes + tie

    def all_reduce_cost(self, num_bytes: float, mesh_dim: int) -> float:
        n = self.shape[mesh_dim]
        if n == 1:
            return 0.0
        a, b, tie = self._ab("all_reduce", mesh_dim)
        return a + b * 2 * (n - 1) / n * num_bytes + tie

    def reduce_scatter_cost(self, num_bytes: float, mesh_dim: int) -> float:
        n = self.shape[mesh_dim]
        if n == 1:
            return 0.0
        a, b, tie = self._ab("reduce_scatter", mesh_dim)
        return a + b * (n - 1) / n * num_bytes + tie

    # -- quantized twins (ISSUE 19): gradient collectives through the
    # blockwise codec.  Wire bytes shrink to 1 byte/element + one fp32
    # scale per 256-element block (reshard_codec.wire_bytes); encode +
    # decode each cost roughly one collective launch, charged as a
    # fixed 2*alpha addend so tiny tensors never flip.

    def _quantized_wire_bytes(self, num_bytes: float,
                              itemsize: int = 4) -> float:
        from alpa_tpu.mesh_profiling import quantized_wire_bytes
        return quantized_wire_bytes(num_bytes, itemsize)

    def all_reduce_cost_quantized(self, num_bytes: float, mesh_dim: int,
                                  itemsize: int = 4) -> float:
        n = self.shape[mesh_dim]
        if n == 1:
            return 0.0
        a, b, tie = self._ab("all_reduce", mesh_dim)
        qb = self._quantized_wire_bytes(num_bytes, itemsize)
        return 3 * a + b * 2 * (n - 1) / n * qb + tie

    def reduce_scatter_cost_quantized(self, num_bytes: float,
                                      mesh_dim: int,
                                      itemsize: int = 4) -> float:
        n = self.shape[mesh_dim]
        if n == 1:
            return 0.0
        a, b, tie = self._ab("reduce_scatter", mesh_dim)
        qb = self._quantized_wire_bytes(num_bytes, itemsize)
        return 3 * a + b * (n - 1) / n * qb + tie

    def all_to_all_cost(self, num_bytes: float, mesh_dim: int) -> float:
        n = self.shape[mesh_dim]
        if n == 1:
            return 0.0
        a, b, tie = self._ab("all_to_all", mesh_dim)
        return a + b * (n - 1) / (n * n) * num_bytes + tie

    def ppermute_cost(self, num_bytes: float, mesh_dim: int) -> float:
        """Neighbor exchange (halo) along one axis: one hop, no ring
        factor.  Used by the conv planner's spatial (halo-exchange)
        strategies."""
        n = self.shape[mesh_dim]
        if n == 1:
            return 0.0
        a, b, tie = self._ab("ppermute", mesh_dim)
        return a + b * num_bytes + tie

    def resharding_cost_mixed(self, num_bytes: float) -> float:
        """Cost of an unmodeled layout change (conservative: allgather all)."""
        return sum(
            self.all_gather_cost(num_bytes, d) for d in range(len(self.shape)))

    def get_jax_mesh(self, axis_names: Sequence[str]) -> Mesh:
        assert self.physical_mesh is not None
        devices = np.asarray(self.physical_mesh.devices).flatten()
        dev_mesh = devices[self.id_mesh.reshape(-1)].reshape(self.id_mesh.shape)
        return Mesh(dev_mesh, axis_names=tuple(axis_names))

    def __repr__(self):
        return f"LogicalDeviceMesh(shape={self.shape})"


########################################
# Physical meshes
########################################

class PhysicalDeviceMesh:
    """A 2-D (host x devices-per-host) slice of real jax devices.

    Single-controller analog of ref ``device_mesh.py:633``.  ``devices`` is an
    np.ndarray[host, device] of jax Device objects.
    """

    def __init__(self, devices: np.ndarray):
        devices = np.asarray(devices)
        if devices.ndim == 1:
            devices = devices.reshape(1, -1)
        assert devices.ndim == 2
        self.devices = devices

    @property
    def num_hosts(self) -> int:
        return self.devices.shape[0]

    @property
    def num_devices_per_host(self) -> int:
        return self.devices.shape[1]

    @property
    def num_devices(self) -> int:
        return int(self.devices.size)

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.num_hosts, self.num_devices_per_host)

    @property
    def flat_devices(self) -> List[Any]:
        return list(self.devices.flatten())

    def get_logical_mesh(self,
                         mesh_shape: Optional[Sequence[int]] = None,
                         mesh_alpha=None,
                         mesh_beta=None) -> LogicalDeviceMesh:
        """Build a logical mesh of the given shape over this physical mesh.

        Default alpha/beta: the first logical dim maps to the host axis (DCN,
        higher beta) when it spans hosts, matching ref device_mesh.py:686-772.
        """
        if mesh_shape is None:
            mesh_shape = self.shape
        mesh_shape = tuple(int(x) for x in mesh_shape)
        assert int(np.prod(mesh_shape)) == self.num_devices, (
            f"logical shape {mesh_shape} != {self.num_devices} devices")
        id_mesh = np.arange(self.num_devices).reshape(mesh_shape)
        if mesh_alpha is None:
            mesh_alpha = (1.0,) * len(mesh_shape)
        if mesh_beta is None:
            # A logical dim pays the DCN (cross-host) beta if stepping along
            # it crosses a host boundary in the host-major flat device order:
            # elements along dim i are `stride` apart; the dim touches
            # multiple hosts iff its extent covers more than one host row.
            betas = []
            ndph = self.num_devices_per_host
            for i, s in enumerate(mesh_shape):
                stride = int(np.prod(mesh_shape[i + 1:]))
                crosses_host = (self.num_hosts > 1 and s > 1 and
                                stride * s > ndph)
                betas.append(0.1 if crosses_host else 0.01)
            mesh_beta = tuple(betas)
        # attach the process-global measured calibration (if a profiling
        # DB is loaded) so ILP costs are real seconds
        from alpa_tpu.mesh_profiling import get_global_calibration
        return LogicalDeviceMesh(self, id_mesh, mesh_alpha, mesh_beta,
                                 calibration=get_global_calibration())

    def get_jax_mesh(self,
                     axis_names: Sequence[str] = ("data", "model"),
                     mesh_shape: Optional[Sequence[int]] = None) -> Mesh:
        if mesh_shape is None:
            mesh_shape = self.shape
        devs = np.array(self.flat_devices).reshape(tuple(mesh_shape))
        return Mesh(devs, axis_names=tuple(axis_names))

    def shard_args(self, args, shardings):
        """Place host arrays onto the mesh with the given shardings
        (ref shard_args_to_bufs, device_mesh.py:776/1287)."""
        return jax.device_put(args, shardings)

    # -- memory stats (ref device_mesh.py:255-270) --
    def get_memory_stats(self):
        stats = {}
        for d in self.flat_devices:
            try:
                stats[str(d)] = d.memory_stats()
            except Exception:  # pylint: disable=broad-except
                stats[str(d)] = None
        return stats

    def sync_workers(self):
        """Block until all outstanding work on this mesh is done."""
        jax.effects_barrier()
        me = jax.process_index()
        local = [d for d in self.flat_devices if d.process_index == me]
        if local:
            (jax.device_put(0.0, local[0]) + 0).block_until_ready()

    def __repr__(self):
        return f"PhysicalDeviceMesh(shape={self.shape})"


class LocalPhysicalDeviceMesh(PhysicalDeviceMesh):
    """Mesh over this process's local devices (ref device_mesh.py:860)."""

    def __init__(self, devices: Optional[Sequence] = None):
        if devices is None:
            devices = jax.local_devices()
        super().__init__(np.array(list(devices)).reshape(1, -1))


# The reference's DistributedPhysicalDeviceMesh is the Ray-actor-backed
# multi-host mesh; under the single-controller jax runtime a multi-host
# mesh is just PhysicalDeviceMesh over the global device grid (bring-up
# via jax.distributed — see distributed.py), so the name is an alias
# for API compatibility.
DistributedPhysicalDeviceMesh = PhysicalDeviceMesh


########################################
# Virtual (compile-time) mesh
########################################

class VirtualPhysicalMesh:
    """Compile-time mesh: shape + host topology, no resource binding until
    ``get_physical_mesh`` (ref device_mesh.py:1792).

    Carries the list of backing jax devices so that slicing produces
    launchable submeshes, but performs no allocation.
    """

    def __init__(self,
                 num_hosts: int,
                 num_devices_per_host: int,
                 devices: Optional[np.ndarray] = None,
                 parent: Optional["VirtualPhysicalMesh"] = None):
        self.num_hosts = num_hosts
        self.num_devices_per_host = num_devices_per_host
        if devices is None:
            devices = np.full((num_hosts, num_devices_per_host), None)
        self.devices = np.asarray(devices).reshape(num_hosts,
                                                   num_devices_per_host)
        self.parent = parent
        self.launched_physical_mesh = None
        self.launched_physical_mesh_group = None

    @property
    def shape(self):
        return (self.num_hosts, self.num_devices_per_host)

    @property
    def num_devices(self):
        return self.num_hosts * self.num_devices_per_host

    def slice_1d(self, dim: int, indices: Sequence[Sequence[int]]
                 ) -> List["VirtualPhysicalMesh"]:
        """Slice along one dim into several submeshes (ref :1854)."""
        out = []
        for idx in indices:
            if dim == 0:
                sub = self.devices[list(idx), :]
            else:
                sub = self.devices[:, list(idx)]
            out.append(
                VirtualPhysicalMesh(sub.shape[0], sub.shape[1], sub, self))
        return out

    def slice_2d(self, host_indices, device_indices) -> "VirtualPhysicalMesh":
        sub = self.devices[np.ix_(list(host_indices), list(device_indices))]
        return VirtualPhysicalMesh(sub.shape[0], sub.shape[1], sub, self)

    def get_logical_mesh(self, mesh_shape=None, mesh_alpha=None,
                         mesh_beta=None) -> LogicalDeviceMesh:
        if mesh_shape is None:
            mesh_shape = self.shape
        mesh_shape = tuple(int(x) for x in mesh_shape)
        assert int(np.prod(mesh_shape)) == self.num_devices
        id_mesh = np.arange(self.num_devices).reshape(mesh_shape)
        phys = None
        if self.devices.flatten()[0] is not None:
            phys = PhysicalDeviceMesh(self.devices)
        if mesh_beta is None:
            mesh_beta = tuple([0.1 if (self.num_hosts > 1 and i == 0) else 0.01
                               for i in range(len(mesh_shape))])
        from alpa_tpu.mesh_profiling import get_global_calibration
        return LogicalDeviceMesh(phys, id_mesh, mesh_alpha, mesh_beta,
                                 calibration=get_global_calibration())

    def get_physical_mesh(self) -> PhysicalDeviceMesh:
        """Bind to real devices (ref :1940)."""
        if self.launched_physical_mesh is None:
            assert self.devices.flatten()[0] is not None, (
                "VirtualPhysicalMesh has no backing devices")
            self.launched_physical_mesh = PhysicalDeviceMesh(self.devices)
        return self.launched_physical_mesh

    def get_physical_mesh_group(
            self, sliced_meshes: Sequence["VirtualPhysicalMesh"]
    ) -> "PhysicalDeviceMeshGroup":
        """Launch a group of submeshes (ref :1954)."""
        self.launched_physical_mesh_group = PhysicalDeviceMeshGroup(
            [m.get_physical_mesh() for m in sliced_meshes], self)
        return self.launched_physical_mesh_group

    def __repr__(self):
        return f"VirtualPhysicalMesh(shape={self.shape})"


class PhysicalDeviceMeshGroup:
    """An ordered list of launched physical meshes, one per pipeline stage
    group (ref device_mesh.py:1979).  NCCL group management is gone: the jax
    runtime moves arrays between meshes via ``jax.device_put``."""

    def __init__(self,
                 meshes: Sequence[PhysicalDeviceMesh],
                 parent: Optional[VirtualPhysicalMesh] = None):
        self.meshes = list(meshes)
        self.parent = parent

    def __getitem__(self, i) -> PhysicalDeviceMesh:
        return self.meshes[i]

    def __len__(self):
        return len(self.meshes)

    def __iter__(self):
        return iter(self.meshes)

    def index(self, mesh: PhysicalDeviceMesh) -> int:
        return self.meshes.index(mesh)

    def sync_workers(self):
        jax.effects_barrier()
        for m in self.meshes:
            m.sync_workers()


########################################
# Device cluster
########################################

class DeviceCluster:
    """The whole visible device pool, grouped by host/process
    (ref device_mesh.py:2131, minus Ray placement groups)."""

    def __init__(self, devices: Optional[Sequence] = None):
        if devices is None:
            devices = jax.devices(global_config.backend) \
                if global_config.backend else jax.devices()
        devices = list(devices)
        # Group by process index (host).
        by_proc = {}
        for d in devices:
            by_proc.setdefault(d.process_index, []).append(d)
        procs = sorted(by_proc)
        per_host = min(len(by_proc[p]) for p in procs)
        grid = np.array([by_proc[p][:per_host] for p in procs], dtype=object)
        self.devices = grid
        self.num_hosts = grid.shape[0]
        self.num_devices_per_host = grid.shape[1]

    @property
    def num_devices(self):
        return int(self.devices.size)

    def get_physical_mesh(self,
                          host_ids: Optional[Sequence[int]] = None,
                          num_devices_per_host: Optional[int] = None
                          ) -> PhysicalDeviceMesh:
        host_ids = list(host_ids) if host_ids is not None else list(
            range(self.num_hosts))
        n = num_devices_per_host or self.num_devices_per_host
        return PhysicalDeviceMesh(self.devices[host_ids, :n])

    def get_virtual_physical_mesh(self,
                                  host_ids: Optional[Sequence[int]] = None,
                                  num_devices_per_host: Optional[int] = None
                                  ) -> VirtualPhysicalMesh:
        host_ids = list(host_ids) if host_ids is not None else list(
            range(self.num_hosts))
        n = num_devices_per_host or self.num_devices_per_host
        sub = self.devices[host_ids, :n]
        return VirtualPhysicalMesh(len(host_ids), n, sub)

    def __repr__(self):
        return (f"DeviceCluster(num_hosts={self.num_hosts}, "
                f"num_devices_per_host={self.num_devices_per_host})")


########################################
# Globals (ref device_mesh.py:2314-2395)
########################################

global_cluster: Optional[DeviceCluster] = None
global_physical_mesh: Optional[PhysicalDeviceMesh] = None
global_virtual_physical_mesh: Optional[VirtualPhysicalMesh] = None


def init_global_cluster(cluster: str = "local",
                        devices: Optional[Sequence] = None,
                        num_nodes: Optional[int] = None,
                        num_devices_per_node: Optional[int] = None):
    """Bring up the global cluster state.

    ``cluster='local'`` uses this process's devices.  ``cluster='distributed'``
    assumes ``jax.distributed.initialize`` has been (or can be) called and uses
    the global device view across hosts — the TPU-pod analog of the reference's
    ``ray`` mode (ref api.py:25 / device_mesh.py:2314).
    """
    global global_cluster, global_physical_mesh, global_virtual_physical_mesh
    if cluster == "distributed" and jax.process_count() == 1:
        try:
            jax.distributed.initialize()
        except Exception as e:  # already initialized / single process
            logger.debug("jax.distributed.initialize skipped: %s", e)
    global_cluster = DeviceCluster(devices)
    global_virtual_physical_mesh = global_cluster.get_virtual_physical_mesh(
        list(range(num_nodes)) if num_nodes else None, num_devices_per_node)
    global_physical_mesh = None


def shutdown_global_cluster():
    global global_cluster, global_physical_mesh, global_virtual_physical_mesh
    global_cluster = None
    global_physical_mesh = None
    global_virtual_physical_mesh = None


def get_global_cluster() -> Optional[DeviceCluster]:
    return global_cluster


def get_global_physical_mesh(create_if_not_exist=False
                             ) -> Optional[PhysicalDeviceMesh]:
    global global_physical_mesh
    if global_physical_mesh is None and create_if_not_exist:
        if global_cluster is None:
            global_physical_mesh = LocalPhysicalDeviceMesh()
        else:
            global_physical_mesh = global_cluster.get_physical_mesh()
    return global_physical_mesh


def set_global_physical_mesh(mesh: Optional[PhysicalDeviceMesh]):
    global global_physical_mesh
    global_physical_mesh = mesh


def get_global_virtual_physical_mesh() -> Optional[VirtualPhysicalMesh]:
    return global_virtual_physical_mesh


def set_global_virtual_physical_mesh(mesh: Optional[VirtualPhysicalMesh]):
    global global_virtual_physical_mesh
    global_virtual_physical_mesh = mesh


def get_global_num_devices() -> int:
    if global_cluster is not None:
        return global_cluster.num_devices
    return len(jax.devices())


_global_seed = 42


def set_seed(seed: int):
    global _global_seed
    _global_seed = seed


def get_seed() -> int:
    return _global_seed
