"""Distributed checkpointing.

Analog of ref ``alpa/serialization.py`` (SURVEY.md §5 checkpoint/resume):
per-leaf directories containing per-shard files + an index, written by each
host for its addressable shards in parallel, restored by reading only the
slices each host needs.  Cross-topology restore (save on one mesh shape,
load on another) is supported via slice assembly.

Layout (flax-state-dict tree paths, ref tree-path directories):

  ckpt_dir/
    metadata.json                      # tree structure + leaf info
    <leaf-path>/shard_<k>.npy          # one file per saved shard
    <leaf-path>/index.json             # shard index -> global slice

An optional node-local cache dir is drained to the shared FS by a
background thread (ref DaemonMoveWorker, device_mesh.py:90).
"""
import json
import logging
import os
import shutil
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np
from flax.serialization import from_state_dict, to_state_dict
from jax.tree_util import tree_flatten, tree_unflatten

logger = logging.getLogger(__name__)

_SEP = "."


class CheckpointCorruptError(RuntimeError):
    """The checkpoint directory is incomplete or inconsistent (missing
    shard files, truncated index, metadata/shard mismatch).  Raised by
    validation up front, with the offending leaf named — instead of a
    bare shape-mismatch error deep inside ``jax.device_put``."""


def _leaf_dirname(path_parts) -> str:
    return _SEP.join(str(p) for p in path_parts) or "_root"


def _flatten_state_dict(sd, prefix=()):
    out = {}
    if isinstance(sd, dict):
        for k, v in sd.items():
            out.update(_flatten_state_dict(v, prefix + (k,)))
    else:
        out[prefix] = sd
    return out


class _AsyncMover:
    """Background mover from local cache to the final directory
    (ref DaemonMoveWorker).

    Failures are NOT fire-and-forget: every background move exception is
    recorded and the first one re-raises from ``wait()`` (i.e.
    ``checkpoint_wait()``), after removing the failed move's partial
    destination — a half-drained leaf dir must not masquerade as a
    complete checkpoint on the shared FS."""

    def __init__(self):
        self.threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        self._errors: List[BaseException] = []

    def submit(self, src: str, dst: str):
        t = threading.Thread(target=self._move_safe, args=(src, dst),
                             daemon=True)
        t.start()
        self.threads.append(t)

    def _move_safe(self, src, dst):
        try:
            self._move(src, dst)
        except BaseException as e:  # pylint: disable=broad-except
            logger.exception("async checkpoint drain %s -> %s failed",
                             src, dst)
            # drop the partial destination: a leaf dir holding only some
            # of its shards would restore as silently-wrong zeros
            try:
                if os.path.isdir(dst):
                    shutil.rmtree(dst, ignore_errors=True)
                elif os.path.exists(dst):
                    os.unlink(dst)
            except OSError:
                logger.exception("cleanup of partial %s failed", dst)
            with self._lock:
                self._errors.append(e)

    @staticmethod
    def _move(src, dst):
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.isdir(src):
            # Merge per-file into the leaf dir (concurrent processes flush
            # the same leaf) — a bare shutil.move would nest src INSIDE an
            # existing dst and its shards would never be found.  makedirs
            # first so the check-then-move race cannot reintroduce nesting.
            os.makedirs(dst, exist_ok=True)
            for name in os.listdir(src):
                shutil.move(os.path.join(src, name),
                            os.path.join(dst, name))
            os.rmdir(src)
        else:
            shutil.move(src, dst)

    def wait(self):
        for t in self.threads:
            t.join()
        self.threads = []
        with self._lock:
            errors, self._errors = self._errors, []
        if errors:
            raise CheckpointCorruptError(
                f"{len(errors)} async checkpoint move(s) failed; first: "
                f"{type(errors[0]).__name__}: {errors[0]}") from errors[0]


_mover = _AsyncMover()


def save_checkpoint(ckpt_dir: str,
                    target: Any,
                    step: int,
                    local_cache_dir: Optional[str] = None):
    """Save a pytree of (possibly distributed) arrays (ref
    serialization.py:75).

    Every process writes the shards it can address; on a single-controller
    runtime that is all of them.  ``local_cache_dir`` writes locally first
    and drains asynchronously to ``ckpt_dir``.
    """
    sd = to_state_dict(target)
    flat = _flatten_state_dict(sd)
    write_dir = local_cache_dir or ckpt_dir
    os.makedirs(write_dir, exist_ok=True)

    proc = jax.process_index()
    metadata = {"step": step, "leaves": {},
                "n_processes": jax.process_count()}
    for path, leaf in flat.items():
        name = _leaf_dirname(path)
        leaf_dir = os.path.join(write_dir, name)
        os.makedirs(leaf_dir, exist_ok=True)
        index = []
        if isinstance(leaf, jax.Array):
            # Each process writes only its addressable shards (a global
            # multi-host array is never fully addressable — do NOT fall
            # back to np.asarray, which raises on such arrays).  Shard
            # files are process-unique; replica_id!=0 shards are skipped
            # so each distinct slice is written exactly once cluster-wide.
            k = 0
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                sl = tuple((s.start or 0,
                            s.stop if s.stop is not None else dim)
                           for s, dim in zip(shard.index, leaf.shape)) \
                    if leaf.ndim else ()
                fname = f"shard_p{proc}_{k}.npy"
                np.save(os.path.join(leaf_dir, fname),
                        np.asarray(shard.data))
                index.append({"file": fname,
                              "slice": [list(x) for x in sl]})
                k += 1
            shape, dtype = list(leaf.shape), str(leaf.dtype)
        else:
            arr = np.asarray(leaf)
            if proc == 0:
                np.save(os.path.join(leaf_dir, "shard_p0_0.npy"), arr)
                index.append({"file": "shard_p0_0.npy",
                              "slice": [[0, d] for d in arr.shape]})
            shape, dtype = list(arr.shape), str(arr.dtype)
        with open(os.path.join(leaf_dir, f"index_p{proc}.json"), "w",
                  encoding="utf-8") as f:
            json.dump(index, f)
        metadata["leaves"][name] = {"shape": shape, "dtype": dtype}

    if proc == 0:
        with open(os.path.join(write_dir, "metadata.json"), "w",
                  encoding="utf-8") as f:
            json.dump(metadata, f)

    if local_cache_dir:
        os.makedirs(ckpt_dir, exist_ok=True)
        names = list(metadata["leaves"].keys())
        if proc == 0:
            names.append("metadata.json")
        for name in names:
            _mover.submit(os.path.join(write_dir, name),
                          os.path.join(ckpt_dir, name))


def checkpoint_wait():
    """Block until async cache drains finish."""
    _mover.wait()


def _read_index(leaf_dir: str, n_processes: Optional[int] = None):
    """Merge per-process index files.

    ``n_processes`` (from metadata) bounds which ``index_p<i>.json`` files
    belong to this save — files from an earlier save with more processes
    would otherwise resurrect stale shards.  Legacy single-file
    ``index.json`` checkpoints are read when no per-process files exist.
    """
    names = sorted(
        f for f in os.listdir(leaf_dir)
        if f.startswith("index") and f.endswith(".json"))
    per_proc = [f for f in names if f.startswith("index_p")]
    if per_proc:
        if n_processes is not None:
            keep = {f"index_p{i}.json" for i in range(n_processes)}
            per_proc = [f for f in per_proc if f in keep]
        names = per_proc
    index = []
    for fname in names:
        with open(os.path.join(leaf_dir, fname), encoding="utf-8") as f:
            index.extend(json.load(f))
    return index


def _load_leaf(leaf_dir: str, shape, dtype, sharding=None,
               n_processes: Optional[int] = None):
    index = _read_index(leaf_dir, n_processes)
    if sharding is None:
        # assemble the full array
        out = np.zeros(shape, dtype)
        for ent in index:
            arr = np.load(os.path.join(leaf_dir, ent["file"]))
            sl = tuple(slice(a, b) for a, b in ent["slice"])
            out[sl] = arr
        return out
    # sharded restore: build per-device slices from saved shards
    full = None

    def read_slice(global_slice):
        nonlocal full
        # exact-match fast path
        for ent in index:
            if tuple(tuple(x) for x in ent["slice"]) == global_slice:
                return np.load(os.path.join(leaf_dir, ent["file"]))
        if full is None:
            full = _load_leaf(leaf_dir, shape, dtype, None, n_processes)
        return full[tuple(slice(a, b) for a, b in global_slice)]

    ndim = len(shape)

    def cb(idx):
        sl = tuple((s.start or 0, s.stop if s.stop is not None else d)
                   for s, d in zip(idx, shape)) if ndim else ()
        return jax.numpy.asarray(read_slice(sl), dtype=dtype)

    return jax.make_array_from_callback(
        tuple(shape), sharding,
        lambda idx: cb(idx))


def validate_checkpoint(ckpt_dir: str, metadata: Optional[Dict] = None):
    """Cross-check the checkpoint's index files against what is actually
    on disk, BEFORE any array assembly: every leaf dir present, every
    index entry's shard file present and non-empty, slices in bounds,
    and the union of slices voluminous enough to cover the leaf.  Raises
    :class:`CheckpointCorruptError` naming the first offending leaf."""
    if metadata is None:
        metadata = load_checkpoint_metadata(ckpt_dir)
    n_proc = metadata.get("n_processes")
    for name, info in metadata.get("leaves", {}).items():
        leaf_dir = os.path.join(ckpt_dir, name)
        if not os.path.isdir(leaf_dir):
            raise CheckpointCorruptError(
                f"checkpoint {ckpt_dir} is missing leaf directory "
                f"{name!r} (listed in metadata.json) — truncated or "
                "partially-drained save")
        index = _read_index(leaf_dir, n_proc)
        if not index:
            raise CheckpointCorruptError(
                f"leaf {name!r} has no usable index entries in "
                f"{leaf_dir} — empty or stale index files")
        shape = tuple(info["shape"])
        total = 1
        for d in shape:
            total *= d
        covered = 0
        for ent in index:
            path = os.path.join(leaf_dir, ent["file"])
            if not os.path.exists(path) or os.path.getsize(path) == 0:
                raise CheckpointCorruptError(
                    f"leaf {name!r}: shard file {ent['file']} is "
                    f"missing or empty in {leaf_dir} — the index refers "
                    "to a shard that never finished writing")
            vol = 1
            for (a, b), dim in zip(ent["slice"], shape):
                if not 0 <= a < b <= dim:
                    raise CheckpointCorruptError(
                        f"leaf {name!r}: shard {ent['file']} covers "
                        f"slice {ent['slice']} outside the leaf shape "
                        f"{list(shape)} — index/metadata mismatch")
                vol *= b - a
            covered += vol
        if covered < total:
            raise CheckpointCorruptError(
                f"leaf {name!r}: shards cover {covered} of {total} "
                f"elements of shape {list(shape)} — missing shard "
                "files (e.g. a process's flush never landed)")


def restore_checkpoint(ckpt_dir: str,
                       target: Any,
                       shardings: Optional[Any] = None):
    """Restore into the structure of ``target``
    (ref serialization.py:137).  ``shardings``: optional pytree (matching
    target) of NamedShardings; each host reads only its slices.

    The on-disk index is validated against the actual shard files first
    (``validate_checkpoint``): a corrupt/truncated checkpoint raises
    :class:`CheckpointCorruptError` up front."""
    metadata = load_checkpoint_metadata(ckpt_dir)
    validate_checkpoint(ckpt_dir, metadata)
    sd = to_state_dict(target)
    flat = _flatten_state_dict(sd)
    shard_flat = {}
    if shardings is not None:
        shard_sd = to_state_dict(
            jax.tree_util.tree_map(lambda x: x, shardings))
        shard_flat = _flatten_state_dict(shard_sd)

    new_flat = {}
    for path in flat:
        name = _leaf_dirname(path)
        info = metadata["leaves"].get(name)
        if info is None:
            raise KeyError(f"checkpoint missing leaf {name}")
        leaf_dir = os.path.join(ckpt_dir, name)
        sharding = shard_flat.get(path)
        new_flat[path] = _load_leaf(leaf_dir, tuple(info["shape"]),
                                    np.dtype(info["dtype"]), sharding,
                                    metadata.get("n_processes"))

    def rebuild(tree_path, sd_node):
        if isinstance(sd_node, dict):
            return {k: rebuild(tree_path + (k,), v)
                    for k, v in sd_node.items()}
        return new_flat[tree_path]

    new_sd = rebuild((), sd)
    return from_state_dict(target, new_sd)


def load_checkpoint_metadata(ckpt_dir: str) -> Dict:
    """Read and sanity-check ``metadata.json``.  A missing, unparsable,
    or structurally-wrong file raises :class:`CheckpointCorruptError`
    with the path named (instead of a stray ``JSONDecodeError`` or
    ``KeyError`` later)."""
    path = os.path.join(ckpt_dir, "metadata.json")
    if not os.path.exists(path):
        raise CheckpointCorruptError(
            f"no metadata.json in {ckpt_dir} — not a checkpoint "
            "directory, or the save died before metadata was written")
    try:
        with open(path, encoding="utf-8") as f:
            metadata = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointCorruptError(
            f"metadata.json in {ckpt_dir} is unreadable ({e}) — "
            "truncated write") from e
    if not isinstance(metadata, dict) or \
            not isinstance(metadata.get("leaves"), dict):
        raise CheckpointCorruptError(
            f"metadata.json in {ckpt_dir} lacks a 'leaves' table — "
            "not a checkpoint metadata file")
    return metadata
