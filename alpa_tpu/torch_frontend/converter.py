"""torch.fx graph -> jax function conversion.

Analog of ref ``alpa/torch/ops/mapping.py`` + ``alpa/torch/nn/``: an op
mapping table from torch functions/modules to jax equivalents, driven over
an ``fx.GraphModule``.  Parameters/buffers become a flat dict pytree keyed
by their state_dict names; the returned function is pure:

  fn(params: dict[str, jax.Array], *inputs) -> outputs

Coverage targets the reference's functionalized nn surface: Linear, conv,
norms (eval), embeddings, activations, elementwise/matmul/reshape ops,
dropout (eval = identity).
"""
import logging
import operator
import warnings
from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)


def torch_to_jax_array(t):
    # np.array (not asarray): tensor.numpy() SHARES the torch storage and
    # jax's CPU backend can zero-copy it — later in-place torch mutation
    # (e.g. torch optimizer steps) would silently change the jax array
    return jnp.asarray(np.array(t.detach().cpu().numpy()))


########################################
# op mappings
########################################


def _linear(x, w, b=None):
    y = x @ w.T
    return y + b if b is not None else y


def _layer_norm(x, shape, w, b, eps):
    axes = tuple(range(x.ndim - len(shape), x.ndim))
    mean = x.mean(axes, keepdims=True)
    var = ((x - mean)**2).mean(axes, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + eps)
    if w is not None:
        y = y * w
    if b is not None:
        y = y + b
    return y


def _conv2d(x, w, b, stride, padding, dilation, groups):
    # torch NCHW / OIHW
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    pad = [(padding[0], padding[0]), (padding[1], padding[1])]
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding=pad,
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if b is not None:
        y = y + b[None, :, None, None]
    return y


def _embedding(ids, weight):
    return weight[ids]


def _flatten(x, start_dim=0, end_dim=-1):
    if end_dim < 0:
        end_dim += x.ndim
    return jnp.reshape(
        x, x.shape[:start_dim] + (-1,) + x.shape[end_dim + 1:])


def _adaptive_avg_pool2d(x, out):
    out = tuple(np.ravel(out))
    if out in ((1,), (1, 1)):
        return jnp.mean(x, axis=(2, 3), keepdims=True)
    oh, ow = (out[0], out[0]) if len(out) == 1 else out
    h, w = x.shape[2], x.shape[3]
    if h % oh == 0 and w % ow == 0:
        return x.reshape(x.shape[0], x.shape[1], oh, h // oh, ow,
                         w // ow).mean(axis=(3, 5))
    raise NotImplementedError(
        f"adaptive_avg_pool2d to {out} from {(h, w)} (non-divisible) has "
        "no jax mapping yet")

def _softmax(x, dim=-1, **_):
    return jax.nn.softmax(x, axis=dim)


def _mean(x, dim=None, keepdim=False, **_):
    return jnp.mean(x, axis=dim, keepdims=keepdim)


def _sum(x, dim=None, keepdim=False, **_):
    return jnp.sum(x, axis=dim, keepdims=keepdim)


def _permute(x, *dims):
    if len(dims) == 1 and isinstance(dims[0], (tuple, list)):
        dims = tuple(dims[0])
    return jnp.transpose(x, dims)


def _view(x, *shape):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return jnp.reshape(x, shape)


def _transpose2(x, d0, d1):
    perm = list(range(x.ndim))
    perm[d0], perm[d1] = perm[d1], perm[d0]
    return jnp.transpose(x, perm)


def _contiguous(x):
    return x


def _torch_max(x, dim=None, keepdim=False):
    if dim is None:
        return jnp.max(x)
    # torch returns (values, indices) when dim is given
    return (jnp.max(x, axis=dim, keepdims=keepdim),
            jnp.argmax(x, axis=dim, keepdims=keepdim))


def _torch_min(x, dim=None, keepdim=False):
    if dim is None:
        return jnp.min(x)
    return (jnp.min(x, axis=dim, keepdims=keepdim),
            jnp.argmin(x, axis=dim, keepdims=keepdim))


def _max_pool2d(x, kernel_size, stride=None, padding=0, **_):
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max,
        (1, 1) + tuple(kernel_size), (1, 1) + tuple(stride),
        [(0, 0), (0, 0), (padding[0], padding[0]),
         (padding[1], padding[1])])


def _avg_pool2d(x, kernel_size, stride=None, padding=0,
                count_include_pad=True, **_):
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    pads = [(0, 0), (0, 0), (padding[0], padding[0]),
            (padding[1], padding[1])]
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add,
                              (1, 1) + tuple(kernel_size),
                              (1, 1) + tuple(stride), pads)
    if count_include_pad or padding == (0, 0):
        return s / (kernel_size[0] * kernel_size[1])
    ones = jnp.ones_like(x)
    denom = jax.lax.reduce_window(ones, 0.0, jax.lax.add,
                                  (1, 1) + tuple(kernel_size),
                                  (1, 1) + tuple(stride), pads)
    return s / denom


def _conv_transpose2d(x, w, b=None, stride=1, padding=0, output_padding=0,
                      groups=1, dilation=1):
    """torch F.conv_transpose2d: weight is (I, O/g, kH, kW); realized as a
    fractionally-strided conv (lhs_dilation) of the spatially-flipped,
    transposed kernel."""
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    stride, padding = pair(stride), pair(padding)
    output_padding, dilation = pair(output_padding), pair(dilation)
    i_total, o_per_g, kh, kw = w.shape
    i_per_g = i_total // groups
    # (I, O/g, kh, kw) -> (O, I/g, kh, kw), flipped spatially
    wt = w.reshape(groups, i_per_g, o_per_g, kh, kw)
    wt = jnp.flip(wt, axis=(-2, -1)).transpose(0, 2, 1, 3, 4)
    wt = wt.reshape(groups * o_per_g, i_per_g, kh, kw)
    dkh, dkw = (kh - 1) * dilation[0] + 1, (kw - 1) * dilation[1] + 1
    pads = [(dkh - 1 - padding[0], dkh - 1 - padding[0] + output_padding[0]),
            (dkw - 1 - padding[1], dkw - 1 - padding[1] + output_padding[1])]
    y = jax.lax.conv_general_dilated(
        x, wt, window_strides=(1, 1), padding=pads,
        lhs_dilation=stride, rhs_dilation=dilation,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if b is not None:
        y = y + b[None, :, None, None]
    return y


def _group_norm(x, num_groups, w=None, b=None, eps=1e-5):
    n, c = x.shape[0], x.shape[1]
    g = x.reshape(n, num_groups, c // num_groups, *x.shape[2:])
    axes = tuple(range(2, g.ndim))
    mean = g.mean(axes, keepdims=True)
    var = ((g - mean)**2).mean(axes, keepdims=True)
    y = ((g - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
    shape = (1, c) + (1,) * (x.ndim - 2)
    if w is not None:
        y = y * w.reshape(shape)
    if b is not None:
        y = y + b.reshape(shape)
    return y


def _batch_stats(x):
    """Per-channel batch mean/variance (+ channel broadcast shape and
    count), torch BatchNorm train-mode numerics; raises torch's n<=1
    error."""
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    n = x.size // x.shape[1] if x.ndim > 1 else x.size
    if n <= 1:
        # torch raises here too: var==0 would silently train on bias
        raise ValueError(
            "Expected more than 1 value per channel when training, "
            f"got input size {tuple(x.shape)}")
    axes = (0,) + tuple(range(2, x.ndim))
    mean = x.mean(axes)
    var = ((x - mean.reshape(shape)) ** 2).mean(axes)
    return mean, var, n, shape


def _bn_normalize(x, mean, var, weight, bias, eps, shape):
    y = (x - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + eps)
    if weight is not None:
        y = y * weight.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y


def _batch_norm(x, running_mean, running_var, weight=None, bias=None,
                training=False, momentum=0.1, eps=1e-5):
    if training:
        # batch statistics, matching torch train-mode numerics.  The
        # running-stat update is a side effect the functional trace cannot
        # express HERE, so running_mean/var stay frozen — warn when there
        # are stats being left behind (track_running_stats=False has
        # none).  nn.BatchNorm* module sites get the update captured via
        # fx_to_jax(track_buffer_updates=True) instead.
        if running_mean is not None:
            warnings.warn(
                "F.batch_norm traced with training=True: batch statistics "
                "are used, but running-stat updates (momentum) are dropped "
                "by the functional trace", stacklevel=2)
        mean, var, _n, shape = _batch_stats(x)
    else:
        # eval-mode semantics: normalize with running statistics
        mean, var = running_mean, running_var
        shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    return _bn_normalize(x, mean, var, weight, bias, eps, shape)


def _torch_dtype_to_jnp(dtype):
    """torch.dtype -> jnp dtype (None passes through; an unmapped torch
    dtype raises rather than silently producing the wrong dtype)."""
    if dtype is None:
        return None
    name = str(dtype).replace("torch.", "")
    try:
        return jnp.dtype(name)
    except TypeError as e:
        raise NotImplementedError(
            f"torch dtype {dtype} has no jnp mapping") from e


def _scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                  is_causal=False, scale=None, **_):
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if is_causal:
        # torch's is_causal uses a top-left aligned mask (tril diagonal 0)
        # even when query and key lengths differ
        lq, lk = scores.shape[-2], scores.shape[-1]
        causal = jnp.tril(jnp.ones((lq, lk), bool))
        scores = jnp.where(causal, scores, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, -jnp.inf)
        else:
            scores = scores + attn_mask
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", probs, v)


# name -> callable; covers torch.nn.functional + tensor methods + operators
FUNCTION_MAP: Dict[str, Callable] = {
    "linear": _linear,
    "relu": jax.nn.relu,
    "gelu": lambda x, approximate="none": jax.nn.gelu(
        x, approximate=(approximate == "tanh")),
    "silu": jax.nn.silu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softmax": _softmax,
    "log_softmax": lambda x, dim=-1, **_: jax.nn.log_softmax(x, axis=dim),
    # NOTE: "dropout" is intentionally absent — every dropout node is
    # intercepted by fx_to_jax's dropout_site handling (one source of
    # truth for the explicit dropout policy)
    "layer_norm": _layer_norm,
    "group_norm": _group_norm,
    "batch_norm": _batch_norm,
    "embedding": _embedding,
    "conv2d": _conv2d,
    "conv_transpose2d": _conv_transpose2d,
    "scaled_dot_product_attention": _scaled_dot_product_attention,
    "max_pool2d": _max_pool2d,
    "avg_pool2d": _avg_pool2d,
    "adaptive_avg_pool2d": lambda x, out: _adaptive_avg_pool2d(x, out),
    "matmul": jnp.matmul,
    "bmm": jnp.matmul,
    "add": operator.add,
    "sub": operator.sub,
    "mul": operator.mul,
    "truediv": operator.truediv,
    "floordiv": operator.floordiv,
    "div": jnp.divide,
    "neg": operator.neg,
    "pow": operator.pow,
    # in-place torch ops are pure in fx-to-jax land
    "iadd": operator.add,
    "isub": operator.sub,
    "imul": operator.mul,
    "itruediv": operator.truediv,
    "relu_": jax.nn.relu,
    "add_": operator.add,
    "mul_": operator.mul,
    "clamp": lambda x, min=None, max=None: jnp.clip(x, min, max),
    "clamp_": lambda x, min=None, max=None: jnp.clip(x, min, max),
    "cos": jnp.cos,
    "sin": jnp.sin,
    "where": jnp.where,
    "tril": jnp.tril,
    "triu": jnp.triu,
    "cumsum": lambda x, dim=-1, **_: jnp.cumsum(x, axis=dim),
    "argmax": lambda x, dim=None, keepdim=False: jnp.argmax(
        x, axis=dim, keepdims=keepdim),
    "argmin": lambda x, dim=None, keepdim=False: jnp.argmin(
        x, axis=dim, keepdims=keepdim),
    "arange": lambda *a, dtype=None, device=None, **_: jnp.arange(
        *a, dtype=_torch_dtype_to_jnp(dtype)),
    "ones": lambda *s, dtype=None, device=None, **_: jnp.ones(
        s[0] if len(s) == 1 and isinstance(s[0], (tuple, list)) else s),
    "zeros": lambda *s, dtype=None, device=None, **_: jnp.zeros(
        s[0] if len(s) == 1 and isinstance(s[0], (tuple, list)) else s),
    "repeat": lambda x, *reps: jnp.tile(
        x, reps[0] if len(reps) == 1 and isinstance(reps[0], (tuple, list))
        else reps),
    "exp": jnp.exp,
    "log": jnp.log,
    "sqrt": jnp.sqrt,
    "rsqrt": jax.lax.rsqrt,
    "abs": jnp.abs,
    "mean": _mean,
    "sum": _sum,
    "max": _torch_max,
    "min": _torch_min,
    "cat": lambda ts, dim=0: jnp.concatenate(ts, axis=dim),
    "stack": lambda ts, dim=0: jnp.stack(ts, axis=dim),
    "split": lambda x, n, dim=0: jnp.split(
        x, range(n, x.shape[dim], n), axis=dim),
    "chunk": lambda x, n, dim=0: jnp.split(x, n, axis=dim),
    "flatten": lambda x, start_dim=0, end_dim=-1: _flatten(
        x, start_dim, end_dim),
    "view": _view,
    "reshape": _view,
    "permute": _permute,
    "transpose": _transpose2,
    "contiguous": _contiguous,
    "expand": lambda x, *s: jnp.broadcast_to(
        x, tuple(xs if ss == -1 else ss for ss, xs in
                 zip(s, x.shape)) if len(s) == x.ndim else s),
    "unsqueeze": lambda x, dim: jnp.expand_dims(x, dim),
    "squeeze": lambda x, dim=None: jnp.squeeze(x, dim),
    "masked_fill": lambda x, mask, val: jnp.where(mask, val, x),
    "getitem": operator.getitem,
    # tensor attribute reads; .device has no jax analog (torch code uses
    # it only to place new tensors, which jax tracing makes moot)
    "getattr": lambda x, name: None if name == "device" else getattr(
        x, name),
    "float": lambda x: x.astype(jnp.float32),
    "size": lambda x, d=None: x.shape if d is None else x.shape[d],
    "to": lambda x, *a, **k: x,
    "type_as": lambda x, y: x.astype(y.dtype),
    "clone": lambda x: x,
    "detach": lambda x: jax.lax.stop_gradient(x),
}


########################################
# module-level mappings (call_module nodes)
########################################


def _convert_module(mod, params_prefix: str):
    """Return fn(params, *args) for a leaf torch module."""
    import torch

    if isinstance(mod, torch.nn.Linear):
        def f(p, x):
            return _linear(x, p[f"{params_prefix}weight"],
                           p.get(f"{params_prefix}bias"))
        return f
    if isinstance(mod, torch.nn.Embedding):
        return lambda p, ids: _embedding(ids, p[f"{params_prefix}weight"])
    if isinstance(mod, torch.nn.LayerNorm):
        shape = tuple(mod.normalized_shape)
        eps = mod.eps
        def f(p, x):
            return _layer_norm(x, shape, p.get(f"{params_prefix}weight"),
                               p.get(f"{params_prefix}bias"), eps)
        return f
    if isinstance(mod, torch.nn.Conv2d):
        stride, padding = mod.stride, mod.padding
        dilation, groups = mod.dilation, mod.groups
        def f(p, x):
            return _conv2d(x, p[f"{params_prefix}weight"],
                           p.get(f"{params_prefix}bias"), stride, padding,
                           dilation, groups)
        return f
    if isinstance(mod, (torch.nn.ReLU,)):
        return lambda p, x: jax.nn.relu(x)
    if isinstance(mod, (torch.nn.GELU,)):
        approx = getattr(mod, "approximate", "none") == "tanh"
        return lambda p, x: jax.nn.gelu(x, approximate=approx)
    if isinstance(mod, (torch.nn.SiLU,)):
        return lambda p, x: jax.nn.silu(x)
    if isinstance(mod, (torch.nn.Tanh,)):
        return lambda p, x: jnp.tanh(x)
    if isinstance(mod, (torch.nn.Sigmoid,)):
        return lambda p, x: jax.nn.sigmoid(x)
    if isinstance(mod, (torch.nn.Dropout,)):
        return lambda p, x: x  # eval mode
    if isinstance(mod, (torch.nn.Softmax,)):
        dim = mod.dim if mod.dim is not None else -1
        return lambda p, x: jax.nn.softmax(x, axis=dim)
    if isinstance(mod, (torch.nn.Flatten,)):
        sd, ed = mod.start_dim, mod.end_dim
        return lambda p, x: _flatten(x, sd, ed)
    if isinstance(mod, torch.nn.MaxPool2d):
        ks, st, pd = mod.kernel_size, mod.stride, mod.padding
        return lambda p, x: _max_pool2d(x, ks, st, pd)
    if isinstance(mod, (torch.nn.BatchNorm1d, torch.nn.BatchNorm2d,
                        torch.nn.BatchNorm3d)):
        # torch semantics: batch statistics in train mode AND whenever
        # running stats aren't tracked (running_mean is None even in eval)
        eps = mod.eps
        use_batch_stats = mod.training or not mod.track_running_stats
        def f(p, x):
            return _batch_norm(x, p.get(f"{params_prefix}running_mean"),
                               p.get(f"{params_prefix}running_var"),
                               p.get(f"{params_prefix}weight"),
                               p.get(f"{params_prefix}bias"),
                               training=use_batch_stats, eps=eps)
        return f
    if isinstance(mod, torch.nn.GroupNorm):
        ng, eps = mod.num_groups, mod.eps
        def f(p, x):
            return _group_norm(x, ng, p.get(f"{params_prefix}weight"),
                               p.get(f"{params_prefix}bias"), eps)
        return f
    if isinstance(mod, torch.nn.ConvTranspose2d):
        stride, padding = mod.stride, mod.padding
        output_padding, groups = mod.output_padding, mod.groups
        dilation = mod.dilation
        def f(p, x):
            return _conv_transpose2d(x, p[f"{params_prefix}weight"],
                                     p.get(f"{params_prefix}bias"), stride,
                                     padding, output_padding, groups,
                                     dilation)
        return f
    if isinstance(mod, torch.nn.AvgPool2d):
        ks, st, pd = mod.kernel_size, mod.stride, mod.padding
        cip = mod.count_include_pad
        return lambda p, x: _avg_pool2d(x, ks, st, pd, cip)
    if isinstance(mod, torch.nn.AdaptiveAvgPool2d):
        out = mod.output_size
        return lambda p, x: _adaptive_avg_pool2d(x, out)
    if isinstance(mod, torch.nn.Identity):
        return lambda p, x: x
    if type(mod).__name__ == "GPT2Block":
        return _convert_gpt2_block(mod, params_prefix)
    if isinstance(mod, torch.nn.MultiheadAttention):
        if not mod._qkv_same_embed_dim:
            raise NotImplementedError(
                "MultiheadAttention with distinct kdim/vdim has no jax "
                "mapping yet")
        nh, e, batch_first = mod.num_heads, mod.embed_dim, mod.batch_first

        def f(p, q, k, v, key_padding_mask=None, need_weights=True,
              attn_mask=None, average_attn_weights=True, is_causal=False):
            w_in = p[f"{params_prefix}in_proj_weight"]
            b_in = p.get(f"{params_prefix}in_proj_bias")
            w_out = p[f"{params_prefix}out_proj.weight"]
            b_out = p.get(f"{params_prefix}out_proj.bias")
            if not batch_first:  # torch default: (L, B, E)
                q, k, v = (jnp.swapaxes(t, 0, 1) for t in (q, k, v))

            def proj(x, lo):
                y = x @ w_in[lo:lo + e].T
                return y + b_in[lo:lo + e] if b_in is not None else y

            qp, kp, vp = proj(q, 0), proj(k, e), proj(v, 2 * e)

            def split(x):  # (B, L, E) -> (B, nh, L, E/nh)
                b_, l_, _ = x.shape
                return x.reshape(b_, l_, nh, e // nh).transpose(0, 2, 1, 3)

            mask = None
            if key_padding_mask is not None:
                # True = ignore, torch convention -> additive -inf
                mask = jnp.where(key_padding_mask[:, None, None, :],
                                 -jnp.inf, 0.0)
            if attn_mask is not None:
                am = (jnp.where(attn_mask, -jnp.inf, 0.0)
                      if attn_mask.dtype == jnp.bool_ else attn_mask)
                mask = am if mask is None else mask + am
            out = _scaled_dot_product_attention(
                split(qp), split(kp), split(vp), attn_mask=mask,
                is_causal=is_causal)
            b_, _, l_, _ = out.shape
            out = out.transpose(0, 2, 1, 3).reshape(b_, l_, e)
            out = out @ w_out.T
            if b_out is not None:
                out = out + b_out
            if not batch_first:
                out = jnp.swapaxes(out, 0, 1)
            return out, None  # need_weights path returns no weights

        return f
    raise NotImplementedError(
        f"torch module {type(mod).__name__} has no jax mapping yet")


def _convert_gpt2_block(mod, params_prefix: str):
    """transformers ``GPT2Block`` as a LEAF module (HF GPT-2 family
    support; the block's own fx graph is untraceable across transformers
    versions — its mask/shape helpers iterate proxies).  Weights are the
    block's own state_dict entries (Conv1D convention: weight is
    (in, out), applied as x @ w + b).  Causality must arrive via the
    additive ``attention_mask`` the caller passes — matching the modern
    eager path where ``create_causal_mask`` supplies it.
    Verified logit-exact against transformers in
    tests/torch_frontend/test_gpt2.py."""
    attn = mod.attn
    if getattr(attn, "is_cross_attention", False):
        raise NotImplementedError("GPT2Block cross-attention")
    if getattr(mod, "training", False):
        # leaf modules evade _find_active_dropout (the tracer never
        # descends into them), and this mapping is deterministic — a
        # train-mode block with live dropout would silently mistrain,
        # the exact failure functionalize's explicit-policy refusal
        # exists to prevent
        sites = {"attn.attn_dropout": getattr(attn, "attn_dropout", None),
                 "attn.resid_dropout": getattr(attn, "resid_dropout",
                                               None),
                 "mlp.dropout": getattr(mod.mlp, "dropout", None)}
        active = sorted(name for name, drop in sites.items()
                        if drop is not None and
                        getattr(drop, "p", 0.0) > 0)
        if active:
            raise ValueError(
                "GPT2Block leaf conversion: train-mode block has active "
                f"dropout ({active}) which the deterministic leaf "
                "mapping would silently drop — .eval() the block or "
                "construct it with zero attn_pdrop/resid_pdrop")
    if getattr(attn, "scale_attn_by_inverse_layer_idx", False) or \
            getattr(attn, "reorder_and_upcast_attn", False):
        raise NotImplementedError(
            "GPT2Block with scale_attn_by_inverse_layer_idx / "
            "reorder_and_upcast_attn")
    nh = attn.num_heads
    hd = attn.head_dim
    scale = (1.0 / np.sqrt(hd)) if getattr(attn, "scale_attn_weights",
                                           True) else 1.0
    eps1, eps2 = mod.ln_1.eps, mod.ln_2.eps
    act_name = type(mod.mlp.act).__name__
    if act_name not in ("NewGELUActivation", "GELUActivation"):
        raise NotImplementedError(f"GPT2 MLP activation {act_name}")
    approximate = act_name == "NewGELUActivation"
    pf = params_prefix

    def f(p, x, attention_mask=None, **_ignored):
        if attention_mask is None:
            # On transformers versions where causality lives inside
            # GPT2Attention (bias buffer) the traced caller may pass no
            # mask; running unmasked would be silently NON-causal.
            raise ValueError(
                "GPT2Block leaf conversion requires an explicit "
                "additive attention_mask carrying causality (e.g. "
                "0 / finfo.min lower-triangular, shape (1,1,S,S))")
        e = x.shape[-1]
        a = _layer_norm(x, (e,), p[pf + "ln_1.weight"],
                        p[pf + "ln_1.bias"], eps1)
        qkv = a @ p[pf + "attn.c_attn.weight"] + p[pf + "attn.c_attn.bias"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            b, s, _ = t.shape
            return t.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        scores = scores + attention_mask
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        b, _, s, _ = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, s, nh * hd)
        out = out @ p[pf + "attn.c_proj.weight"] + \
            p[pf + "attn.c_proj.bias"]
        x = x + out
        m = _layer_norm(x, (e,), p[pf + "ln_2.weight"],
                        p[pf + "ln_2.bias"], eps2)
        h = m @ p[pf + "mlp.c_fc.weight"] + p[pf + "mlp.c_fc.bias"]
        h = jax.nn.gelu(h, approximate=approximate)
        h = h @ p[pf + "mlp.c_proj.weight"] + p[pf + "mlp.c_proj.bias"]
        return (x + h,)

    return f


########################################
# graph conversion
########################################


def fx_to_jax(gm, params: Dict[str, Any],
              dropout_mode: str = "identity",
              track_buffer_updates: bool = False) -> Callable:
    """Convert an fx.GraphModule into fn(params, *inputs, rng=None).

    ``params`` is used to validate at conversion time that every
    ``get_attr`` target has a backing entry, so missing-parameter errors
    surface here rather than on first call.

    ``dropout_mode`` decides what ACTIVE dropout sites (train-mode
    nn.Dropout / F.dropout(training=True) with p > 0) do — an explicit
    policy instead of silently dropping the op:
      * "identity": dropout disabled; the trace is deterministic.
      * "rng": real inverted dropout; the converted function takes a
        ``rng`` keyword (a jax PRNG key) and derives one independent key
        per site via fold_in.  Calling without ``rng`` raises.
    Inactive sites (eval mode or p == 0) are identity either way.

    ``track_buffer_updates=True`` makes the converted function return
    ``(out, buffer_updates)``: train-mode nn.BatchNorm* sites with
    tracked running stats emit their momentum-updated
    running_mean/running_var (+ num_batches_tracked) into the dict —
    torch's in-place side effect, functionalized.  Callers fold the
    updates into their buffers between steps:
    ``buffers = {**buffers, **updates}``."""
    import torch

    if dropout_mode not in ("identity", "rng"):
        raise ValueError(f"dropout_mode {dropout_mode!r}: expected "
                         "'identity' or 'rng'")
    modules = dict(gm.named_modules())
    missing = [n.target for n in gm.graph.nodes
               if n.op == "get_attr" and n.target not in params]
    if missing:
        raise KeyError(f"params dict missing fx get_attr targets: "
                       f"{missing}")
    # Convert every module once at conversion time: unmapped modules fail
    # here (the documented contract), and calls avoid per-invocation
    # isinstance dispatch.  nn.Dropout is handled inline (its behavior
    # depends on dropout_mode + the per-site rng), so it is excluded.
    module_fns = {
        n.target: _convert_module(modules[n.target], n.target + ".")
        for n in gm.graph.nodes if n.op == "call_module"
        and not isinstance(modules[n.target], torch.nn.Dropout)
    }
    # train-mode tracked-stats BatchNorm module sites whose running-stat
    # updates are captured when track_buffer_updates is on (functional
    # F.batch_norm calls keep the warn-and-freeze behavior)
    from torch.nn.modules.batchnorm import _BatchNorm
    bn_update_sites = {
        n.name: n.target for n in gm.graph.nodes
        if track_buffer_updates and n.op == "call_module"
        and isinstance(modules.get(n.target), _BatchNorm)
        and modules[n.target].training
        and modules[n.target].track_running_stats
    }
    for target in bn_update_sites.values():
        if modules[target].momentum is None:
            raise NotImplementedError(
                f"BatchNorm {target}: momentum=None (cumulative moving "
                "average) is not supported for tracked buffer updates")

    # stable per-site indices for rng fold_in
    dropout_site = {
        n.name: i
        for i, n in enumerate(
            n for n in gm.graph.nodes
            if (n.op == "call_module" and
                isinstance(modules.get(n.target), torch.nn.Dropout)) or
            (n.op in ("call_function", "call_method") and
             getattr(n.target, "__name__", str(n.target)) == "dropout"))
    }

    def _apply_dropout(x, p_drop, training, node_name, rng):
        if not training or p_drop <= 0.0:
            return x
        if dropout_mode == "identity":
            return x
        if rng is None:
            raise ValueError(
                "this converted function has active dropout under "
                "dropout_mode='rng'; pass fn(params, *inputs, rng=key)")
        key = jax.random.fold_in(rng, dropout_site[node_name])
        keep = jax.random.bernoulli(key, 1.0 - p_drop, x.shape)
        return jnp.where(keep, x / (1.0 - p_drop), jnp.zeros_like(x))

    def _bn_with_updates(x, target, p, buf_updates):
        """Train-mode BatchNorm with the running-stat side effect made
        explicit: normalize with batch stats (shared _batch_stats /
        _bn_normalize numerics) and emit the momentum-updated running
        stats.  Reads compose through buf_updates so a weight-SHARED
        module called at several sites compounds sequentially, exactly
        as torch's in-place updates do."""
        mod = modules[target]
        pf = target + "."

        def cur(key):
            return buf_updates.get(key, p[key])

        mean, var, n, shape = _batch_stats(x)
        m = mod.momentum
        buf_updates[pf + "running_mean"] = \
            (1 - m) * cur(pf + "running_mean") + m * mean
        # torch updates running_var with the UNBIASED batch variance
        buf_updates[pf + "running_var"] = \
            (1 - m) * cur(pf + "running_var") + m * var * (n / (n - 1))
        nbt = pf + "num_batches_tracked"
        if nbt in p:
            buf_updates[nbt] = cur(nbt) + 1
        return _bn_normalize(x, mean, var, p.get(pf + "weight"),
                             p.get(pf + "bias"), mod.eps, shape)

    def fn(p, *inputs, rng=None):
        env: Dict[str, Any] = {}
        buf_updates: Dict[str, Any] = {}
        input_iter = iter(inputs)

        def lookup(a):
            import torch as _t
            if isinstance(a, torch.fx.Node):
                return env[a.name]
            if isinstance(a, (list, tuple)):
                return type(a)(lookup(x) for x in a)
            if isinstance(a, _t.Tensor):
                return torch_to_jax_array(a)
            return a

        out = None
        for node in gm.graph.nodes:
            if node.op == "placeholder":
                env[node.name] = next(input_iter)
            elif node.op == "get_attr":
                key = node.target
                env[node.name] = p[key]
            elif node.op in ("call_function", "call_method"):
                fname = (getattr(node.target, "__name__", str(node.target))
                         if node.op == "call_function" else node.target)
                args = [lookup(a) for a in node.args]
                kwargs = {k: lookup(v) for k, v in node.kwargs.items()}
                if node.name in dropout_site:
                    # torch.nn.functional.dropout defaults training=TRUE
                    env[node.name] = _apply_dropout(
                        args[0],
                        kwargs.get("p", args[1] if len(args) > 1 else 0.5),
                        kwargs.get("training",
                                   args[2] if len(args) > 2 else True),
                        node.name, rng)
                    continue
                f = FUNCTION_MAP.get(fname)
                if f is None:
                    raise NotImplementedError(
                        f"torch {node.op} {fname} has no jax mapping yet")
                env[node.name] = f(*args, **kwargs)
            elif node.op == "call_module":
                args = [lookup(a) for a in node.args]
                if node.name in dropout_site:
                    mod = modules[node.target]
                    env[node.name] = _apply_dropout(
                        args[0], mod.p, mod.training, node.name, rng)
                    continue
                if node.name in bn_update_sites:
                    env[node.name] = _bn_with_updates(
                        args[0], node.target, p, buf_updates)
                    continue
                mf = module_fns[node.target]
                kwargs = {k: lookup(v) for k, v in node.kwargs.items()}
                env[node.name] = mf(p, *args, **kwargs)
            elif node.op == "output":
                out = lookup(node.args[0])
        if track_buffer_updates:
            return out, buf_updates
        return out

    return fn


def _find_active_dropout(gm) -> List[str]:
    """Dropout sites in a traced graph that would actually fire: train-
    mode nn.Dropout modules with p > 0, and functional F.dropout calls
    whose (traced-literal) training flag isn't False — torch's default
    is training=TRUE, and a proxied/unknown flag counts as active
    (conservative: the explicit-policy refusal must not be evadable)."""
    import torch
    import torch.fx

    mods = dict(gm.named_modules())
    active = []
    for n in gm.graph.nodes:
        if n.op == "call_module" and \
                isinstance(mods.get(n.target), torch.nn.Dropout):
            m = mods[n.target]
            if m.training and m.p > 0:
                active.append(n.target)
        elif n.op in ("call_function", "call_method") and \
                getattr(n.target, "__name__", str(n.target)) == "dropout":
            p = n.kwargs.get("p", n.args[1] if len(n.args) > 1 else 0.5)
            tr = n.kwargs.get("training",
                              n.args[2] if len(n.args) > 2 else True)
            p_active = not isinstance(p, (int, float)) or p > 0
            tr_active = not (tr is False)
            if p_active and tr_active:
                active.append(n.name)
    return active


def functionalize(module, concrete_args=None, split_buffers=False,
                  dropout=None, leaf_modules=(), mutable_buffers=False):
    """torch.nn.Module -> (jax_fn, params_dict).

    jax_fn(params, *jax_inputs) reproduces module.forward in the module's
    CURRENT train/eval mode (ref: the functionalized nn of alpa/torch/nn/).
    Train-mode tracing warns: BatchNorm uses batch statistics (matching
    torch), but the running-stat update is a side effect the functional
    trace drops — UNLESS ``mutable_buffers=True``, in which case the
    converted function returns ``(out, buffer_updates)`` with the
    momentum-updated running stats of every train-mode nn.BatchNorm*
    (fold them in between steps: ``buffers = {**buffers, **updates}``;
    pairs naturally with ``split_buffers=True``).

    ``dropout`` is the EXPLICIT policy for train-mode dropout (a
    train-mode module containing active dropout refuses to convert
    without one — silently dropping randomness mistrains):
      * "identity": dropout off, deterministic trace.
      * "rng": real dropout; call ``jax_fn(params, *inputs, rng=key)``.

    ``leaf_modules``: extra module CLASSES the fx tracer must not
    descend into — they convert via ``_convert_module``'s explicit
    mappings instead (e.g. transformers' GPT2Block, whose internals
    resist symbolic tracing).

    With ``split_buffers=True`` returns (jax_fn, trainable, buffers):
    ``trainable`` holds entries backed by torch Parameters, ``buffers``
    the rest (BatchNorm running stats, ``num_batches_tracked``, ...).
    Differentiate w.r.t. ``trainable`` only and call
    ``jax_fn({**trainable, **buffers}, ...)`` — integer buffers would
    otherwise break jax.grad and running stats must not receive updates.
    """
    import torch
    import torch.fx

    if module.training and not mutable_buffers:
        warnings.warn(
            "functionalize: tracing a train-mode module — BatchNorm uses "
            "batch statistics but running-stat updates are dropped by "
            "the functional trace; pass mutable_buffers=True to capture "
            "them, or call .eval() first for eval semantics",
            stacklevel=2)

    if leaf_modules:
        leaf_classes = tuple(leaf_modules)

        class _LeafTracer(torch.fx.Tracer):

            def is_leaf_module(self, m, qualname):
                return (isinstance(m, leaf_classes) or
                        super().is_leaf_module(m, qualname))

        graph = _LeafTracer().trace(module, concrete_args=concrete_args)
        gm = torch.fx.GraphModule(module, graph)
    else:
        gm = torch.fx.symbolic_trace(module, concrete_args=concrete_args)

    if dropout is None:
        active = _find_active_dropout(gm)
        if active:
            raise ValueError(
                "functionalize: module has active dropout "
                f"({active}); choose an explicit policy: "
                "dropout='identity' (deterministic, dropout off) or "
                "dropout='rng' (real dropout, pass rng=key per call) — "
                "or .eval() the module")
    params = {
        k: torch_to_jax_array(v)
        for k, v in {**dict(module.state_dict())}.items()
    }
    fn = fx_to_jax(gm, params, dropout_mode=dropout or "identity",
                   track_buffer_updates=mutable_buffers)
    if split_buffers:
        pnames = {k for k, _ in module.named_parameters()}
        trainable = {k: v for k, v in params.items() if k in pnames}
        buffers = {k: v for k, v in params.items() if k not in pnames}
        return fn, trainable, buffers
    return fn, params
