"""Training loop over a functionalized torch module.

Analog of ref ``alpa/torch/trainer.py`` (``train_torch_module``): wire a
``torch.nn.Module``, a functional optimizer (``torch_frontend.optim``),
and a parallel method into one compiled train step; the user's code stays
pure PyTorch.
"""
import collections
import logging
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)

TrainState = collections.namedtuple("TrainState", ["params", "optim_state"])


class TorchTrainer:
    """(ref trainer.py:23 train_torch_module, as a reusable object)

    ``loss_func(out, target) -> scalar`` operates on jax arrays.
    ``method``: any alpa_tpu ParallelMethod (None = ShardParallel).
    ``dropout``: the explicit train-mode dropout policy forwarded to
    ``functionalize`` ("identity" or "rng"; required when the module
    has active dropout).
    """

    def __init__(self, module, loss_func: Callable, optim_gen,
                 method: Optional[Any] = None, concrete_args=None,
                 dropout: Optional[str] = None):
        import alpa_tpu
        from alpa_tpu.torch_frontend import functionalize

        self.fn, params = functionalize(module, concrete_args,
                                        dropout=dropout)
        optim_func, _init, optim_state = optim_gen(params)
        self.state = TrainState(params, optim_state)
        fn = self.fn
        self._use_rng = dropout == "rng"

        use_rng = self._use_rng

        def step_body(state, batch, rng):
            inputs, target = batch

            def compute_loss(p):
                out = fn(p, inputs, rng=rng) if use_rng else fn(p, inputs)
                return loss_func(out, target)

            loss, grads = alpa_tpu.value_and_grad(compute_loss)(
                state.params)
            params2, optim2 = optim_func(state.params, state.optim_state,
                                         grads)
            return TrainState(params2, optim2), loss

        if use_rng:
            # real dropout: one fresh key per step, split host-side and
            # passed as a regular (non-batch) argument
            import jax
            self._key = jax.random.PRNGKey(0)

            def train_step(state, batch, rng):
                return step_body(state, batch, rng)
        else:
            def train_step(state, batch):
                return step_body(state, batch, None)

        method = method or alpa_tpu.ShardParallel()
        self.train_step = alpa_tpu.parallelize(train_step, method=method,
                                               batch_argnums=(1,))

    def step(self, inputs, target) -> float:
        """One parallel train step; returns the loss value."""
        import jax.numpy as jnp

        from alpa_tpu.torch_frontend.converter import torch_to_jax_array

        if hasattr(inputs, "detach"):
            inputs = torch_to_jax_array(inputs)
        if hasattr(target, "detach"):
            target = torch_to_jax_array(target)
        if self._use_rng:
            import jax
            self._key, sub = jax.random.split(self._key)
            self.state, loss = self.train_step(self.state,
                                               (inputs, target), sub)
        else:
            self.state, loss = self.train_step(self.state,
                                               (inputs, target))
        return float(loss)

    def fit(self, dataloader, num_epochs: int = 1):
        """(ref train_torch_module's loop)"""
        losses = []
        for _ in range(num_epochs):
            for inputs, target in dataloader:
                losses.append(self.step(inputs, target))
        return losses
