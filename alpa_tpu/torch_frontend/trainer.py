"""Training loop over a functionalized torch module.

Analog of ref ``alpa/torch/trainer.py`` (``train_torch_module``): wire a
``torch.nn.Module``, a functional optimizer (``torch_frontend.optim``),
and a parallel method into one compiled train step; the user's code stays
pure PyTorch.
"""
import collections
import logging
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)

TrainState = collections.namedtuple("TrainState", ["params", "optim_state"])


class TorchTrainer:
    """(ref trainer.py:23 train_torch_module, as a reusable object)

    ``loss_func(out, target) -> scalar`` operates on jax arrays.
    ``method``: any alpa_tpu ParallelMethod (None = ShardParallel).
    """

    def __init__(self, module, loss_func: Callable, optim_gen,
                 method: Optional[Any] = None, concrete_args=None):
        import alpa_tpu
        from alpa_tpu.torch_frontend import functionalize

        self.fn, params = functionalize(module, concrete_args)
        optim_func, _init, optim_state = optim_gen(params)
        self.state = TrainState(params, optim_state)
        fn = self.fn

        def train_step(state, batch):
            inputs, target = batch

            def compute_loss(p):
                out = fn(p, inputs)
                return loss_func(out, target)

            loss, grads = alpa_tpu.value_and_grad(compute_loss)(
                state.params)
            params2, optim2 = optim_func(state.params, state.optim_state,
                                         grads)
            return TrainState(params2, optim2), loss

        method = method or alpa_tpu.ShardParallel()
        self.train_step = alpa_tpu.parallelize(train_step, method=method,
                                               batch_argnums=(1,))

    def step(self, inputs, target) -> float:
        """One parallel train step; returns the loss value."""
        import jax.numpy as jnp

        from alpa_tpu.torch_frontend.converter import torch_to_jax_array

        if hasattr(inputs, "detach"):
            inputs = torch_to_jax_array(inputs)
        if hasattr(target, "detach"):
            target = torch_to_jax_array(target)
        self.state, loss = self.train_step(self.state, (inputs, target))
        return float(loss)

    def fit(self, dataloader, num_epochs: int = 1):
        """(ref train_torch_module's loop)"""
        losses = []
        for _ in range(num_epochs):
            for inputs, target in dataloader:
                losses.append(self.step(inputs, target))
        return losses
