"""PyTorch frontend: trace torch modules into jax functions.

Analog of ref ``alpa/torch/`` (SURVEY.md §2.8: fx-traces PyTorch to jax;
``set_mode("local"/"dist")`` ref torch/__init__.py:33).  A ``torch.fx``
symbolic trace is converted node-by-node into a pure jax function over a
params pytree (the module's state_dict), which then goes through
``@alpa_tpu.parallelize`` like any jax function.
"""
from alpa_tpu.torch_frontend.converter import (functionalize, fx_to_jax,
                                               torch_to_jax_array)

_mode = "local"


def set_mode(mode: str):
    """"local" = run converted functions on one device for debugging;
    "dist" = hand them to alpa_tpu.parallelize (ref torch/__init__.py:33).
    """
    global _mode
    assert mode in ("local", "dist")
    _mode = mode


def get_mode() -> str:
    return _mode
