"""PyTorch frontend: trace torch modules into jax functions.

Analog of ref ``alpa/torch/`` (SURVEY.md §2.8: fx-traces PyTorch to jax;
``set_mode("local"/"dist")`` ref torch/__init__.py:33).  A ``torch.fx``
symbolic trace is converted node-by-node into a pure jax function over a
params pytree (the module's state_dict), which then goes through
``@alpa_tpu.parallelize`` like any jax function.
"""
from alpa_tpu.torch_frontend.converter import (fx_to_jax,
                                               torch_to_jax_array)
from alpa_tpu.torch_frontend.converter import functionalize as _functionalize
from alpa_tpu.torch_frontend import optim


def __getattr__(name):
    # lazy: trainer pulls in alpa_tpu.api (heavier import)
    if name in ("TorchTrainer", "TrainState"):
        from alpa_tpu.torch_frontend import trainer
        return getattr(trainer, name)
    raise AttributeError(name)

_mode = "local"


def set_mode(mode: str):
    """"local": ``functionalize`` returns a jit-wrapped function for
    single-device debugging.  "dist": the function is returned pure, ready
    for ``@alpa_tpu.parallelize`` (ref torch/__init__.py:33)."""
    global _mode
    assert mode in ("local", "dist")
    _mode = mode


def get_mode() -> str:
    return _mode


def functionalize(module, concrete_args=None, split_buffers=False,
                  dropout=None, leaf_modules=(), mutable_buffers=False):
    """torch.nn.Module -> (jax_fn, params), or with ``split_buffers=True``
    (jax_fn, trainable, buffers) — see converter.functionalize (also for
    the ``dropout`` policy, ``leaf_modules``, and ``mutable_buffers``).

    The mode is consulted at CALL time, so ``set_mode`` may be called
    before or after conversion: "local" runs the function under jax.jit
    for single-device debugging; "dist" runs it pure (parallelize-ready).
    """
    import functools
    import jax
    out = _functionalize(module, concrete_args, split_buffers,
                         dropout=dropout, leaf_modules=leaf_modules,
                         mutable_buffers=mutable_buffers)
    fn = out[0]
    jitted = jax.jit(fn)

    @functools.wraps(fn)
    def dispatch(p, *inputs, **kw):
        return (jitted if _mode == "local" else fn)(p, *inputs, **kw)

    return (dispatch,) + tuple(out[1:])
