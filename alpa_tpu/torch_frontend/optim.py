"""Functional optimizers over the torch-frontend params dict.

Analog of ref ``alpa/torch/optim/adam.py`` (which ships a placeholder —
"TODO FIXME: properly implement Adam"; this is the real algorithm).  Each
factory returns ``optim_gen(params) -> (optim_func, init_func, state)``
matching the reference's functional contract:

  optim_func(params, optim_state, grads) -> (params, optim_state)

with no in-place ops and no data-dependent control flow, so the whole
update jit-compiles into the train step.
"""
from typing import Any, Dict

import jax
import jax.numpy as jnp


def adam(lr=1e-4, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0):
    """Adam / AdamW (decoupled decay when ``weight_decay`` > 0)."""

    def optim_gen(params: Dict[str, Any]):

        def init_func(optim_state):
            del optim_state
            zeros = {
                k: jnp.zeros(jnp.shape(v),
                             jnp.result_type(v) if jnp.issubdtype(
                                 jnp.result_type(v), jnp.floating)
                             else jnp.float32)
                for k, v in params.items()
            }
            return {
                "step": jnp.zeros((), jnp.int32),
                "mu": zeros,
                "nu": {k: jnp.zeros_like(v) for k, v in zeros.items()},
            }

        def optim_func(params, optim_state, grads):
            step = optim_state["step"] + 1
            t = step.astype(jnp.float32)
            new_mu, new_nu, new_params = {}, {}, {}
            for k, p in params.items():
                g = grads[k]
                mu = b1 * optim_state["mu"][k] + (1 - b1) * g
                nu = b2 * optim_state["nu"][k] + (1 - b2) * (g * g)
                mu_hat = mu / (1 - b1**t)
                nu_hat = nu / (1 - b2**t)
                update = mu_hat / (jnp.sqrt(nu_hat) + eps)
                if weight_decay:
                    update = update + weight_decay * p
                new_params[k] = p - lr * update
                new_mu[k] = mu
                new_nu[k] = nu
            return new_params, {"step": step, "mu": new_mu, "nu": new_nu}

        return optim_func, init_func, init_func(None)

    return optim_gen


def sgd(lr=1e-2, momentum=0.0):

    def optim_gen(params: Dict[str, Any]):

        def init_func(optim_state):
            del optim_state
            return {k: jnp.zeros_like(v) for k, v in params.items()}

        def optim_func(params, optim_state, grads):
            new_params, new_state = {}, {}
            for k, p in params.items():
                if momentum:
                    buf = momentum * optim_state[k] + grads[k]
                else:
                    buf = grads[k]
                new_state[k] = buf
                new_params[k] = p - lr * buf
            return new_params, new_state

        return optim_func, init_func, init_func(None)

    return optim_gen
