"""Mesh profiling database + calibrated cost estimation.

Analog of ref ``alpa/mesh_profiling.py`` (SURVEY.md §2.8): the cost-model
side of auto stage construction.  Two paths, like the reference:

* ``ProfilingResultDatabase`` — measured dot/collective costs per mesh
  signature, JSON-persisted, filled by ``profile_all`` on real hardware
  (ref ProfilingResultDatabase:162 / profile_all:725).  A
  ``CalibratedCostModel`` fitted from the measurements supplies
  seconds-per-flop (size-dependent) and per-collective alpha/beta in real
  seconds, which the ``LogicalDeviceMesh`` cost queries and the stage DP
  consume — so "auto" decisions trace back to measured numbers instead of
  abstract units.
* ``estimate_stage_cost`` — static model (ref
  ``estimate_hlo_module_cost:901`` / HloCostModelProfileWorker): analytic
  flops + the intra-op ILP objective, used as the default on TPU where
  spinning up submeshes to profile is slow (SURVEY.md §7 hard part 2).
  When the logical mesh carries a calibration, every term is in seconds.
"""
import dataclasses
import json
import logging
import math
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from alpa_tpu.device_mesh import LogicalDeviceMesh
from alpa_tpu.util import benchmark_func, jaxpr_eqn_flops

logger = logging.getLogger(__name__)

# Fallback per-chip peak when no profiling DB is loaded (abstract units are
# fine then: the DP only compares costs).  Seconds per flop.
DEFAULT_SEC_PER_FLOP = 1.0 / 100e12

COLLECTIVE_KINDS = ("all_reduce", "all_gather", "reduce_scatter",
                    "all_to_all")

# Blockwise-codec wire accounting (ISSUE 19): elements per scaling
# block, mirrored from pipeline_parallel/reshard_codec.BLOCK so the
# cost model and the codec agree on wire bytes without importing jax at
# module load.
QUANT_BLOCK = 256


def quantized_wire_bytes(num_bytes: float, itemsize: int = 4) -> float:
    """Wire bytes a gradient collective moves under the blockwise codec:
    1 byte per element plus one fp32 scale per 256-element block —
    ``n + 4 * ceil(n / 256)`` for ``n = num_bytes / itemsize`` elements
    (a ~3.94x cut for block-aligned fp32 payloads)."""
    itemsize = max(1, int(itemsize))
    elems = max(0.0, float(num_bytes)) / itemsize
    nblocks = math.ceil(elems / QUANT_BLOCK) if elems else 0
    return elems + 4.0 * nblocks

# Version stamp written into saved profiling DBs (ISSUE 12 satellite):
# load() validates it and warns on mismatch; stampless files are the
# pre-stamp legacy layout and load with a warning.
PROF_DB_SCHEMA_VERSION = 1

# (kind, key) pairs already warned for out-of-range lookups — one
# warning per distinct query, not one per call.
_warned_out_of_range: set = set()


@dataclasses.dataclass
class CalibratedCostModel:
    """Fitted from a MeshProfilingResult; all values in real seconds.

    ``dot_points``: sorted (flops, sec/flop) samples — matmul efficiency
    is size-dependent (small ops underutilize the MXU), so seconds-per-
    flop interpolates over the measured ladder.
    ``collective_ab``: kind -> (alpha latency s, beta s/byte), fitted by
    least squares on t = alpha + beta * ring_bytes.
    """
    dot_points: List[Tuple[float, float]]
    collective_ab: Dict[str, Tuple[float, float]]

    def __post_init__(self):
        pts = sorted(self.dot_points)
        self._dot_xs = np.array([p[0] for p in pts], float)
        self._dot_ys = np.array([p[1] for p in pts], float)

    def sec_per_flop(self, flops: float = 1e12) -> float:
        if not len(self._dot_xs):
            return DEFAULT_SEC_PER_FLOP
        return float(np.interp(flops, self._dot_xs, self._dot_ys))

    def alpha_beta(self, kind: str) -> Optional[Tuple[float, float]]:
        return self.collective_ab.get(kind)


class MeshProfilingResult:
    """Measured costs for one mesh signature (ref MeshProfilingResult:18).

    Collective entries record (ring_bytes, seconds) where ring_bytes
    already includes the ring factor ((n-1)/n per pass), so alpha-beta
    fits transfer across axis sizes.
    """

    def __init__(self):
        # kind -> key -> list[(size, seconds)]
        self.dot_cost_dict: Dict[Tuple, List] = {}
        self.all_reduce_cost_dict: Dict[Tuple, List] = {}
        self.all_gather_cost_dict: Dict[Tuple, List] = {}
        self.reduce_scatter_cost_dict: Dict[Tuple, List] = {}
        self.all_to_all_cost_dict: Dict[Tuple, List] = {}

    def record(self, kind: str, key: Tuple, size: float, seconds: float):
        getattr(self, f"{kind}_cost_dict").setdefault(tuple(key), []).append(
            (float(size), float(seconds)))

    def estimate(self, kind: str, key: Tuple, size: float) -> Optional[float]:
        """Linear interpolation on measured (size, time) points.

        A lookup outside the profiled size range WARNs (once per (kind,
        key) — the key carries the mesh/axis shape for collectives)
        instead of silently clamping to the nearest measured entry, so a
        query the DB cannot honestly answer is visible (ISSUE 12
        satellite)."""
        points = getattr(self, f"{kind}_cost_dict").get(tuple(key))
        if not points:
            return None
        points = sorted(points)
        sizes = np.array([p[0] for p in points], dtype=float)
        times = np.array([p[1] for p in points], dtype=float)
        if size < sizes[0] or size > sizes[-1]:
            wkey = (kind, tuple(key))
            if wkey not in _warned_out_of_range:
                _warned_out_of_range.add(wkey)
                logger.warning(
                    "profiling DB lookup out of measured range: kind=%s "
                    "key=%s size=%.3g not in [%.3g, %.3g] — clamping to "
                    "the nearest profiled entry", kind, tuple(key), size,
                    sizes[0], sizes[-1])
        return float(np.interp(size, sizes, times))

    def fit(self) -> CalibratedCostModel:
        """Least-squares alpha-beta per collective kind + dot efficiency
        curve (ref: the reference interpolates its profiled op dicts;
        here we additionally expose the fitted line so costs extrapolate
        to unmeasured sizes)."""
        dot_points = []
        for points in self.dot_cost_dict.values():
            for flops, sec in points:
                if flops > 0:
                    dot_points.append((float(flops), sec / flops))
        ab = {}
        for kind in COLLECTIVE_KINDS:
            pts = []
            for points in getattr(self, f"{kind}_cost_dict").values():
                pts.extend(points)
            if len(pts) >= 2:
                x = np.array([p[0] for p in pts], float)
                y = np.array([p[1] for p in pts], float)
                A = np.stack([np.ones_like(x), x], axis=1)
                (alpha, beta), *_ = np.linalg.lstsq(A, y, rcond=None)
                ab[kind] = (max(float(alpha), 0.0), max(float(beta), 1e-15))
            elif len(pts) == 1:
                size, sec = pts[0]
                ab[kind] = (0.0, max(sec / max(size, 1.0), 1e-15))
        return CalibratedCostModel(sorted(dot_points), ab)

    # ---- (de)serialization: JSON-friendly ----
    def to_json(self) -> Dict:
        out = {}
        for kind in ("dot",) + COLLECTIVE_KINDS:
            d = getattr(self, f"{kind}_cost_dict")
            out[kind] = {json.dumps(list(k)): v for k, v in d.items()}
        return out

    @classmethod
    def from_json(cls, data: Dict) -> "MeshProfilingResult":
        r = cls()
        for kind in ("dot",) + COLLECTIVE_KINDS:
            d = {}
            for k, v in data.get(kind, {}).items():
                d[tuple(json.loads(k))] = [tuple(p) for p in v]
            setattr(r, f"{kind}_cost_dict", d)
        return r


class ProfilingResultDatabase:
    """cluster-signature -> MeshProfilingResult (ref :162)."""

    def __init__(self, data: Optional[Dict] = None):
        self.data: Dict[str, MeshProfilingResult] = data or {}

    def query(self, cluster_key: str) -> Optional[MeshProfilingResult]:
        return self.data.get(cluster_key)

    def best_result(self) -> Optional[MeshProfilingResult]:
        """Any-mesh fallback: the entry with the most dot samples."""
        best = None
        for res in self.data.values():
            n = sum(len(v) for v in res.dot_cost_dict.values())
            if best is None or n > best[0]:
                best = (n, res)
        return best[1] if best else None

    def update_one_mesh(self, cluster_key: str,
                        result: MeshProfilingResult):
        self.data[cluster_key] = result

    def save(self, filename: str):
        with open(filename, "w", encoding="utf-8") as f:
            json.dump({"schema_version": PROF_DB_SCHEMA_VERSION,
                       "meshes": {k: v.to_json()
                                  for k, v in self.data.items()}}, f,
                      indent=1)

    @classmethod
    def load(cls, filename: str) -> "ProfilingResultDatabase":
        """Load + validate a profiling DB file (ISSUE 12 satellite):
        the stamped ``{"schema_version": N, "meshes": {...}}`` layout is
        checked against :data:`PROF_DB_SCHEMA_VERSION`; bare-dict legacy
        files (pre-stamp ``prof_database_*.json``) still load, with a
        warning suggesting a re-save."""
        with open(filename, encoding="utf-8") as f:
            raw = json.load(f)
        if "schema_version" in raw:
            version = raw["schema_version"]
            if version != PROF_DB_SCHEMA_VERSION:
                logger.warning(
                    "profiling DB %s has schema_version=%s (this build "
                    "reads %s); attempting to load anyway", filename,
                    version, PROF_DB_SCHEMA_VERSION)
            meshes = raw.get("meshes", {})
        else:
            logger.warning(
                "profiling DB %s has no schema_version stamp (legacy "
                "layout); re-save it to stamp schema_version=%s",
                filename, PROF_DB_SCHEMA_VERSION)
            meshes = raw
        return cls({k: MeshProfilingResult.from_json(v)
                    for k, v in meshes.items()})


# ---- analytic per-generation interconnect defaults ----
#
# Published single-chip/link characteristics per TPU generation (public
# spec sheets; same numbers the "How to Scale Your Model" book tabulates):
# one-way ICI bandwidth per link (GB/s), DCN per-host bandwidth (GB/s),
# and peak bf16 matmul TFLOPS.  These are the fallback where
# ``prof_database_tpu.json`` has no collective measurements (a single
# attached chip cannot measure multi-chip collectives) — the stage DP's
# comm terms then ride published link constants instead of abstract
# placeholder units (r2 VERDICT weak #4; the reference keeps an explicit
# per-cluster DB instead, ref alpa/mesh_profiling.py:162).
TPU_GENERATION_SPECS = {
    "v4": dict(ici_gbps=45.0, dcn_gbps=25.0, peak_bf16_tflops=275.0),
    "v5e": dict(ici_gbps=45.0, dcn_gbps=25.0, peak_bf16_tflops=197.0),
    "v5p": dict(ici_gbps=90.0, dcn_gbps=25.0, peak_bf16_tflops=459.0),
    "v6e": dict(ici_gbps=90.0, dcn_gbps=25.0, peak_bf16_tflops=918.0),
}
ICI_ALPHA_S = 1e-6    # per-hop launch latency over ICI
DCN_ALPHA_S = 10e-6   # cross-host (data-center network) latency

# MXU efficiency ladder for the analytic dot curve: tiny ops underfeed the
# systolic array, big ones approach (but don't reach) peak.
_ANALYTIC_DOT_EFFICIENCY = ((1e8, 0.15), (1e10, 0.40), (1e12, 0.55),
                            (1e14, 0.60))


def detect_tpu_generation(default: str = "v5e") -> str:
    """TPU generation from the environment (the axon plugin exports
    PALLAS_AXON_TPU_GEN) or the device kind string; ``default`` if
    neither identifies one."""
    import os
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    if gen in TPU_GENERATION_SPECS:
        return gen
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
        for g in TPU_GENERATION_SPECS:
            if g in kind:
                return g
    except Exception:  # pylint: disable=broad-except
        pass
    return default


def analytic_calibration(generation: str = "v5e",
                         fabric: str = "ici") -> CalibratedCostModel:
    """A CalibratedCostModel built from published link constants.

    Collective (alpha, beta) use the generation's one-way link bandwidth
    — the recorded x-values in this module already carry the ring factors,
    so beta is simply seconds-per-wire-byte.  The dot curve scales peak
    bf16 flops by the MXU-efficiency ladder.
    """
    spec = TPU_GENERATION_SPECS[generation]
    bw = spec["ici_gbps" if fabric == "ici" else "dcn_gbps"] * 1e9
    alpha = ICI_ALPHA_S if fabric == "ici" else DCN_ALPHA_S
    beta = 1.0 / bw
    ab = {kind: (alpha, beta) for kind in COLLECTIVE_KINDS}
    peak = spec["peak_bf16_tflops"] * 1e12
    dot_points = [(flops, 1.0 / (eff * peak))
                  for flops, eff in _ANALYTIC_DOT_EFFICIENCY]
    return CalibratedCostModel(dot_points, ab)


def merge_calibrations(primary: Optional[CalibratedCostModel],
                       fallback: CalibratedCostModel) -> CalibratedCostModel:
    """Measured entries win; the fallback fills what was never measured
    (dot curve or individual collective kinds)."""
    if primary is None:
        return fallback
    dot = primary.dot_points or fallback.dot_points
    ab = dict(fallback.collective_ab)
    ab.update(primary.collective_ab)
    return CalibratedCostModel(dot, ab)


def get_effective_calibration(platform: Optional[str] = None
                              ) -> Optional[CalibratedCostModel]:
    """The calibration cost queries should use on this process's backend:
    the configured/measured DB, backfilled with the analytic generation
    defaults on TPU (where single-chip rigs can't measure collectives).
    Non-TPU platforms return the measured DB as-is (CPU meshes have their
    own measured collective DB)."""
    cal = get_global_calibration()
    if platform is None:
        try:
            import jax
            platform = jax.default_backend()
        except Exception:  # pylint: disable=broad-except
            return cal
    if platform not in ("tpu", "axon"):
        return cal
    return merge_calibrations(
        cal, analytic_calibration(detect_tpu_generation()))


# ---- global calibration ----
_global_calibration: Optional[CalibratedCostModel] = None
_calibration_explicit = False
_calibration_loaded_from: Optional[str] = None


def calibration_from_file(fname: str) -> Optional[CalibratedCostModel]:
    """Load + fit a profiling DB file; None (with a warning) on failure."""
    try:
        res = ProfilingResultDatabase.load(fname).best_result()
        if res is None:
            return None
        cal = res.fit()
        logger.info("loaded profiling DB %s (sec/flop@1T=%.3e)", fname,
                    cal.sec_per_flop())
        return cal
    except Exception as e:  # pylint: disable=broad-except
        logger.warning("loading profiling DB %s failed: %s", fname, e)
        return None


def set_global_calibration(model: Optional[CalibratedCostModel]):
    global _global_calibration, _calibration_explicit
    _global_calibration = model
    _calibration_explicit = True


def get_global_calibration() -> Optional[CalibratedCostModel]:
    """The process-wide calibration from
    ``global_config.profiling_database_filename`` (re-read whenever the
    configured filename changes, so setting the flag after meshes were
    already created still takes effect) unless set explicitly."""
    global _global_calibration, _calibration_loaded_from
    if _calibration_explicit:
        return _global_calibration
    from alpa_tpu.global_env import global_config
    fname = global_config.profiling_database_filename
    # Cache key includes the file identity (ns mtime + size) so a DB
    # written later to the same path (e.g. profile_all saving to the
    # configured filename in this process) is picked up instead of the
    # stale/failed first load.
    try:
        import os
        st = os.stat(fname) if fname else None
        ident = (st.st_mtime_ns, st.st_size) if st else None
    except OSError:
        ident = None
    key = (fname, ident)
    if key != _calibration_loaded_from:
        _calibration_loaded_from = key
        _global_calibration = calibration_from_file(fname) if fname else None
    return _global_calibration


def profile_one_mesh(physical_mesh,
                     sizes=(1 << 16, 1 << 20, 1 << 23),
                     dot_ns=(512, 1024, 2048, 4096),
                     dtype=None) -> MeshProfilingResult:
    """Measure matmul + collective times on a live mesh
    (ref profile_one_hlo_op:392, simplified: jit-timed instead of
    while-loop executables).  Collectives run as explicit shard_map
    lax collectives so the measured op is exactly the modeled one.

    Stays inside small shapes (largest dot: 4096^2 bf16 = 32 MB/operand)
    so the remote-chip safe envelope is respected.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    result = MeshProfilingResult()
    n_dev = physical_mesh.num_devices
    dtype = dtype or (jnp.bfloat16
                      if physical_mesh.flat_devices[0].platform
                      in ("tpu", "axon") else jnp.float32)

    # dots: a ladder of sizes so MXU efficiency vs size is captured.
    # Timing protocol (ref _compile_profiling_executable_while_loop:274):
    # a dependent-chain fori_loop of k matmuls inside ONE program ending
    # in a scalar D2H readback, at two iteration counts — the difference
    # cancels both the fixed dispatch/readback cost (a ~70 ms round trip
    # on remote-attached chips, where block_until_ready is NOT a true
    # fence) and the loop setup.
    for n in dot_ns:
        # iteration counts scale inversely with op size so the measured
        # chain rises well above timing noise even for tiny matmuls
        k1 = 8
        k2 = max(40, int(2e11 / (2.0 * n**3)))
        a = jnp.asarray(np.random.RandomState(0).randn(n, n) * 0.01,
                        dtype)

        def chain(a, iters):
            def body(_, x):
                y = x @ a
                # keep magnitudes bounded without leaving the MXU path
                return y * jnp.asarray(0.5, dtype)
            out = jax.lax.fori_loop(0, iters, body, a)
            return out.astype(jnp.float32).sum()

        t = {}
        for k in (k1, k2):
            f = jax.jit(partial(chain, iters=k))
            t[k] = benchmark_func(lambda f=f: float(f(a)),
                                  warmup=2, repeat=2, number=3).min()
        sec = max((t[k2] - t[k1]) / (k2 - k1), 1e-9)
        result.record("dot", (np.dtype(dtype).name,), 2.0 * n**3, sec)

    if n_dev > 1:
        mesh = physical_mesh.get_logical_mesh((n_dev,)).get_jax_mesh(("x",))

        def _time(fn, x):
            f = jax.jit(shard_map(fn, mesh=mesh, in_specs=P("x"),
                                  out_specs=fn.out_specs,
                                  check_rep=False))
            return benchmark_func(
                lambda: jax.block_until_ready(f(x)),
                warmup=2, repeat=2, number=5).min()

        n = n_dev
        for size in sizes:
            # multiple of n*n so P("x") sharding and the all_to_all
            # reshape(n, -1) divide evenly on any device count
            elems = -(-max(size // 4, n * n) // (n * n)) * (n * n)
            x = jax.device_put(
                jnp.zeros((elems,), jnp.float32),
                NamedSharding(mesh, P("x")))
            nbytes = float(elems * 4)

            def ar(x):
                return jax.lax.psum(x, "x")
            ar.out_specs = P("x")

            def ag(x):
                return jax.lax.all_gather(x, "x", tiled=True)
            ag.out_specs = P()

            def rs(x):
                return jax.lax.psum_scatter(x, "x", tiled=True)
            rs.out_specs = P("x")

            def a2a(x):
                y = x.reshape(n, -1)
                return jax.lax.all_to_all(y, "x", 0, 0, tiled=True)
            a2a.out_specs = P("x")

            # x-values are the effective wire bytes multiplying beta in
            # LogicalDeviceMesh's cost formulas, so fitted (alpha, beta)
            # transfer across axis sizes.  Per-device block = nbytes / n.
            ring = (n - 1) / n
            block = nbytes / n
            result.record("all_reduce", ("f32", n), 2 * ring * block,
                          _time(ar, x))
            result.record("all_gather", ("f32", n), ring * nbytes,
                          _time(ag, x))
            result.record("reduce_scatter", ("f32", n), ring * block,
                          _time(rs, x))
            result.record("all_to_all", ("f32", n), ring * block,
                          _time(a2a, x))
    return result


def profile_all(cluster, filename: Optional[str] = None
                ) -> ProfilingResultDatabase:
    """Profile the whole cluster (ref profile_all:725)."""
    db = ProfilingResultDatabase()
    mesh = cluster.get_physical_mesh()
    key = (f"{mesh.num_hosts}x{mesh.num_devices_per_host}-"
           f"{mesh.flat_devices[0].platform}")
    db.update_one_mesh(key, profile_one_mesh(mesh))
    if filename:
        db.save(filename)
    return db


########################################
# static stage cost model
########################################


def estimate_stage_cost(stage_comps,
                        logical_mesh: LogicalDeviceMesh,
                        as_option,
                        sec_per_flop: Any = None,
                        use_ilp: bool = True) -> float:
    """Estimate execution time of a merged stage on a logical mesh.

    compute = total flops * sec/flop / devices; communication = the
    intra-op strategy graph's solved ILP objective.  With a calibration
    (``sec_per_flop`` callable / calibrated logical mesh) both terms are
    real seconds; otherwise abstract units with a fixed exchange rate.
    This replaces the reference's compile-and-profile workers as the
    default path (HloCostModelProfileWorker analog).

    Under ``replan_mode != off`` (ISSUE 12), a measured stage cost from
    the calibration store — keyed by the content fingerprint (flops,
    submesh size) this function computes — supersedes the whole
    analytic estimate once it clears ``calibration_min_samples``; the
    analytic value is recorded on the entry as the drift denominator.
    """
    from alpa_tpu.pipeline_parallel.computation import merge_computations
    from alpa_tpu.telemetry import calibration as _calibration

    comp = (merge_computations(stage_comps, "cost_probe")
            if len(stage_comps) > 1 else stage_comps[0])
    flops = sum(jaxpr_eqn_flops(e) for e in comp.eqns)
    n_dev = logical_mesh.num_devices

    if sec_per_flop is None:
        cal = get_global_calibration()
        if cal is not None:
            sec_per_flop = cal.sec_per_flop(flops / max(n_dev, 1))
        else:
            sec_per_flop = DEFAULT_SEC_PER_FLOP
    elif callable(sec_per_flop):
        sec_per_flop = sec_per_flop(flops / max(n_dev, 1))
    compute_cost = flops * sec_per_flop / max(n_dev, 1)

    comm_cost = 0.0
    if use_ilp and n_dev > 1:
        try:
            from alpa_tpu.shard_parallel.ilp import (solution_cost,
                                                     solve_strategy_graph)
            from alpa_tpu.shard_parallel.strategy import build_strategy_graph
            closed = comp.closed_jaxpr()
            graph = build_strategy_graph(closed,
                                         [v.aval for v in comp.invars],
                                         logical_mesh, [], as_option)
            choice = solve_strategy_graph(graph, time_limit=10)
            units = solution_cost(graph, choice)
            if logical_mesh.calibrated:
                comm_cost = units  # already seconds
            else:
                # abstract alpha-beta units: fixed exchange rate (relative
                # ranking is what matters to the DP without a calibration)
                comm_cost = units * 1e-7
        except Exception as e:  # pylint: disable=broad-except
            logger.debug("stage ILP cost estimate failed: %s", e)
    analytic = compute_cost + comm_cost
    if _calibration.replan_active():
        store = _calibration.get_calibration_store()
        sig = _calibration.stage_cost_signature(flops, n_dev)
        store.set_modeled("stage_run", sig, analytic * 1e6)
        measured = store.measured_us("stage_run", sig)
        if measured is not None:
            return measured * 1e-6
    return analytic


#: optimizer-state bytes per parameter byte (Adam-family: mu + nu)
OPT_STATE_MULT = 2.0


def estimate_stage_memory_split(stage_comps,
                                logical_mesh: LogicalDeviceMesh,
                                as_option=None,
                                objective: str = "training"
                                ) -> Tuple[float, float]:
    """(per-device param bytes, per-device per-microbatch activation
    bytes).

    Split so the stage DP can apply the position-aware schedule-dependent
    in-flight factor (ref max_n_succ_stages, stage_profiling.py:756):
    total = param + inflight(stages_from_end, B) * act.

    Activations = outvars the stage actually produces; vars that merely
    pass through (appear among the stage's invars, e.g. parameters
    forwarded across layer slices) are excluded, and duplicates across the
    stage's layer comps count once.  Both terms divide by the submesh size:
    the intra-op planner shards parameters AND activations across it.

    When ``as_option`` is given and ``objective == "training"``, the
    param term also carries the stage's optimizer state
    (:data:`OPT_STATE_MULT` x param bytes, Adam-family): replicated
    per device under ``zero_stage=0``, divided by the submesh size
    under ZeRO weight-update sharding (``zero_stage`` 2/3 — and
    ``auto``, because the memory-budgeted ILP resolves auto to sharded
    exactly when this budget matters).  That makes the ZeRO saving
    visible to the stage DP, so stage boundaries can shift.
    """
    produced = {id(v) for c in stage_comps for v in c.outvars}
    param_bytes = 0.0
    stage_inputs = set()
    for c in stage_comps:
        for v in c.invars:
            if id(v) in produced or id(v) in stage_inputs or \
                    not hasattr(v.aval, "shape"):
                continue
            stage_inputs.add(id(v))
            param_bytes += float(np.prod(v.aval.shape) or 1) * \
                v.aval.dtype.itemsize
    act_bytes = 0.0
    counted = set()
    for c in stage_comps:
        for v in c.outvars:
            if id(v) in counted or id(v) in stage_inputs or \
                    not hasattr(v.aval, "shape"):
                continue
            counted.add(id(v))
            act_bytes += float(np.prod(v.aval.shape) or 1) * \
                v.aval.dtype.itemsize
    n = max(logical_mesh.num_devices, 1)
    opt_bytes = 0.0
    if as_option is not None and objective == "training":
        from alpa_tpu.shard_parallel.auto_sharding import (
            resolved_zero_stage)
        zero = resolved_zero_stage(as_option)
        opt_bytes = OPT_STATE_MULT * param_bytes
        if zero != 0:
            opt_bytes /= n
    return param_bytes / n + opt_bytes, act_bytes / n


def estimate_stage_memory(stage_comps, logical_mesh: LogicalDeviceMesh,
                          num_in_flight: int = 1, as_option=None) -> float:
    """Rough per-device bytes: params/devices + activations in flight."""
    p, a = estimate_stage_memory_split(stage_comps, logical_mesh,
                                       as_option=as_option)
    return p + a * num_in_flight


########################################
# measured stage profiling (opt-in)
########################################


def compile_stage_candidate(stage_comps, num_devices: int, as_option):
    """Plan + compile one candidate stage on the first ``num_devices``
    available devices; returns ``(jitted, args)`` ready for timing.

    The candidate runs under the SAME intra-op planner the final compile
    uses, so the measured time includes its collectives.  Compilation is
    thread-safe (XLA compiles under the hood), so candidates compile
    concurrently; the *timing* must stay serial.
    """
    import jax
    import jax.numpy as jnp
    from jax._src.core import jaxpr_as_fun

    from alpa_tpu.pipeline_parallel.computation import merge_computations

    comp = (merge_computations(list(stage_comps), "profile_probe")
            if len(stage_comps) > 1 else stage_comps[0])
    closed = comp.closed_jaxpr()
    fun = jaxpr_as_fun(closed)
    avals = [v.aval for v in comp.invars]

    devices = jax.devices()[:num_devices]
    if len(devices) < num_devices:
        raise ValueError(
            f"cannot profile a {num_devices}-device candidate on "
            f"{len(jax.devices())} devices")

    in_shardings = None
    if num_devices > 1 and as_option is not None and \
            getattr(as_option, "enable_auto_sharding", True):
        try:
            from alpa_tpu.device_mesh import LocalPhysicalDeviceMesh
            from alpa_tpu.shard_parallel.solver import plan_auto_sharding
            pm = LocalPhysicalDeviceMesh(devices)
            _mesh, in_shardings, cfn, _ = plan_auto_sharding(
                fun, avals, [""] * len(avals), [], pm, as_option)
            if cfn is not None:
                fun = cfn
        except Exception as e:  # pylint: disable=broad-except
            logger.debug("profile candidate planning failed: %s", e)
            in_shardings = None

    def wrapped(*args):
        outs = fun(*args)
        acc = jnp.zeros((), jnp.float32)
        for o in outs:
            if hasattr(o, "astype"):
                acc = acc + o.astype(jnp.float32).sum()
        return acc

    jitted = (jax.jit(wrapped, in_shardings=tuple(in_shardings))
              if in_shardings is not None else jax.jit(wrapped))
    args = [jnp.zeros(a.shape, a.dtype) if hasattr(a, "shape") else 0
            for a in avals]
    float(jitted(*args))  # compile + one warmup execution
    return jitted, args


def time_compiled_candidate(jitted, args, niter: int = 3) -> float:
    """Serially time a compiled candidate; ends in a scalar readback
    (the only true fence on remote-attached chips)."""
    tic = time.perf_counter()
    val = None
    for _ in range(niter):
        val = jitted(*args)
    float(val)
    return (time.perf_counter() - tic) / niter


def profile_stage_cost(stage_comps, num_devices: int, as_option,
                       niter: int = 3) -> float:
    """Compile + time one candidate stage (ref ProfileWorker._profile_impl,
    stage_profiling.py:321: real submesh, dummy inputs)."""
    jitted, args = compile_stage_candidate(stage_comps, num_devices,
                                           as_option)
    return time_compiled_candidate(jitted, args, niter)


def shortlist_candidates(costs, submesh_sizes, n_avail, limit: int):
    """Pick candidates to measure, bucketed by (span length, submesh) so
    refinement touches the stage spans the DP actually considers instead
    of only the globally cheapest (= shortest-span) entries (ADVICE r2).
    Round-robins over buckets in modeled-cost order until ``limit``."""
    L, _, M = costs.shape
    buckets: Dict[Tuple[int, int], List[Tuple[float, int, int, int]]] = {}
    for i in range(L):
        for j in range(i, L):
            for m in range(M):
                if np.isfinite(costs[i, j, m]) and \
                        submesh_sizes[m] <= n_avail:
                    buckets.setdefault((j - i, m), []).append(
                        (costs[i, j, m], i, j, m))
    for b in buckets.values():
        b.sort()
    out = []
    rank = 0
    while len(out) < limit and any(len(b) > rank for b in buckets.values()):
        for key in sorted(buckets):
            b = buckets[key]
            if rank < len(b) and len(out) < limit:
                out.append(b[rank])
        rank += 1
    return out


def refine_costs_measured(costs, layer_comps, submesh_sizes, as_option,
                          limit: int = 16, compile_workers: int = 4):
    """Replace the most promising cost-model entries with measured times
    (the TPU adaptation of ref get_compute_cost's full profile sweep,
    SURVEY.md §7 hard part 2: cost model as default, real profiling as
    refinement).

    Industrial shape (ref CompileWorkerPool/ProfileWorkerPool,
    stage_profiling.py:291): candidates are shortlisted per (span,
    submesh) bucket, COMPILED concurrently on a thread pool, then TIMED
    serially (concurrent timing would corrupt the measurements).
    Failures are surfaced as warnings and the count is returned; if every
    candidate fails, raises so a broken measured mode can't silently
    masquerade as the cost model.
    """
    import concurrent.futures

    import jax

    n_avail = len(jax.devices())
    cands = shortlist_candidates(costs, submesh_sizes, n_avail, limit)
    if not cands:
        return 0
    compiled = {}
    failures = []
    with concurrent.futures.ThreadPoolExecutor(compile_workers) as pool:
        futs = {
            pool.submit(compile_stage_candidate, layer_comps[i:j + 1],
                        int(submesh_sizes[m]), as_option): (i, j, m)
            for _cost, i, j, m in cands
        }
        for fut in concurrent.futures.as_completed(futs):
            ijm = futs[fut]
            try:
                compiled[ijm] = fut.result()
            except Exception as e:  # pylint: disable=broad-except
                failures.append((ijm, repr(e)))
                logger.warning("measured profile: compiling %s failed: %s",
                               ijm, e)
    refined = 0
    for (i, j, m), (jitted, args) in sorted(compiled.items()):
        try:
            costs[i, j, m] = time_compiled_candidate(jitted, args)
            refined += 1
        except Exception as e:  # pylint: disable=broad-except
            failures.append(((i, j, m), repr(e)))
            logger.warning("measured profile: timing (%d,%d,%d) failed: %s",
                           i, j, m, e)
    if not refined and failures:
        raise RuntimeError(
            f"measured stage profiling failed for all {len(failures)} "
            f"candidates; first: {failures[0]}")
    return refined
