"""Mesh profiling database + static cost estimation.

Analog of ref ``alpa/mesh_profiling.py`` (SURVEY.md §2.8): the cost-model
side of auto stage construction.  Two paths, like the reference:

* ``ProfilingResultDatabase`` — measured dot/collective costs per mesh
  signature, picklable, filled by ``profile_all`` on real hardware
  (ref ProfilingResultDatabase:162 / profile_all:725).
* ``estimate_stage_cost`` — pure static model (ref
  ``estimate_hlo_module_cost:901`` / HloCostModelProfileWorker): analytic
  flops / collective alpha-beta over the LogicalDeviceMesh, used as the
  default on TPU where spinning up submeshes to profile is slow
  (SURVEY.md §7 hard part 2).
"""
import logging
import pickle
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from alpa_tpu.device_mesh import LogicalDeviceMesh
from alpa_tpu.util import benchmark_func, jaxpr_eqn_flops

logger = logging.getLogger(__name__)

# Rough per-chip peak for cost normalization (abstract units are fine: the
# DP only compares costs; absolute scale cancels).  Seconds per flop.
DEFAULT_SEC_PER_FLOP = 1.0 / 100e12


class MeshProfilingResult:
    """Measured costs for one mesh signature (ref MeshProfilingResult:18)."""

    def __init__(self):
        # op name -> list[(size, seconds)]
        self.dot_cost_dict: Dict[Tuple, List] = {}
        self.all_reduce_cost_dict: Dict[Tuple, List] = {}
        self.all_gather_cost_dict: Dict[Tuple, List] = {}
        self.reduce_scatter_cost_dict: Dict[Tuple, List] = {}
        self.all_to_all_cost_dict: Dict[Tuple, List] = {}

    def record(self, kind: str, key: Tuple, size: int, seconds: float):
        getattr(self, f"{kind}_cost_dict").setdefault(key, []).append(
            (size, seconds))

    def estimate(self, kind: str, key: Tuple, size: int) -> Optional[float]:
        """Linear interpolation on measured (size, time) points."""
        points = getattr(self, f"{kind}_cost_dict").get(key)
        if not points:
            return None
        points = sorted(points)
        sizes = np.array([p[0] for p in points], dtype=float)
        times = np.array([p[1] for p in points], dtype=float)
        return float(np.interp(size, sizes, times))


class ProfilingResultDatabase:
    """cluster-signature -> MeshProfilingResult (ref :162)."""

    def __init__(self, data: Optional[Dict] = None):
        self.data: Dict[str, MeshProfilingResult] = data or {}

    def query(self, cluster_key: str) -> Optional[MeshProfilingResult]:
        return self.data.get(cluster_key)

    def update_one_mesh(self, cluster_key: str,
                        result: MeshProfilingResult):
        self.data[cluster_key] = result

    def save(self, filename: str):
        with open(filename, "wb") as f:
            pickle.dump(self.data, f)

    @classmethod
    def load(cls, filename: str) -> "ProfilingResultDatabase":
        with open(filename, "rb") as f:
            return cls(pickle.load(f))


def profile_one_mesh(physical_mesh,
                     sizes=(1 << 16, 1 << 20, 1 << 24)) -> MeshProfilingResult:
    """Measure matmul + collective times on a live mesh
    (ref profile_one_hlo_op:392, simplified: jit-timed instead of
    while-loop executables)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    result = MeshProfilingResult()
    mesh = physical_mesh.get_jax_mesh(("x",),
                                      (physical_mesh.num_devices,))
    # dots
    for n in (1024, 4096):
        a = jnp.zeros((n, n), jnp.bfloat16)
        f = jax.jit(lambda a: a @ a)
        cost = benchmark_func(lambda: jax.block_until_ready(f(a)),
                              warmup=1, repeat=2, number=3).mean()
        result.record("dot", ("bf16",), 2 * n**3, cost)
    # collectives
    if physical_mesh.num_devices > 1:
        for size in sizes:
            x = jax.device_put(
                jnp.zeros((size // 4,), jnp.float32),
                NamedSharding(mesh, P("x")))

            def ag(x):
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, P()))

            f = jax.jit(ag)
            cost = benchmark_func(lambda: jax.block_until_ready(f(x)),
                                  warmup=1, repeat=2, number=3).mean()
            result.record("all_gather", ("f32",), size, cost)
    return result


def profile_all(cluster, filename: Optional[str] = None
                ) -> ProfilingResultDatabase:
    """Profile the whole cluster (ref profile_all:725)."""
    db = ProfilingResultDatabase()
    mesh = cluster.get_physical_mesh()
    key = f"{mesh.num_hosts}x{mesh.num_devices_per_host}"
    db.update_one_mesh(key, profile_one_mesh(mesh))
    if filename:
        db.save(filename)
    return db


########################################
# static stage cost model
########################################


def estimate_stage_cost(stage_comps,
                        logical_mesh: LogicalDeviceMesh,
                        as_option,
                        sec_per_flop: float = DEFAULT_SEC_PER_FLOP,
                        use_ilp: bool = True) -> float:
    """Estimate execution time of a merged stage on a logical mesh.

    compute = total flops / (devices * peak); communication = the intra-op
    strategy graph's solved ILP objective (the same alpha-beta units scaled
    into seconds).  This replaces the reference's compile-and-profile
    workers as the default path (HloCostModelProfileWorker analog).
    """
    import jax
    from jax._src.core import jaxpr_as_fun

    from alpa_tpu.pipeline_parallel.computation import merge_computations

    comp = (merge_computations(stage_comps, "cost_probe")
            if len(stage_comps) > 1 else stage_comps[0])
    flops = sum(jaxpr_eqn_flops(e) for e in comp.eqns)
    n_dev = logical_mesh.num_devices
    compute_cost = flops * sec_per_flop / max(n_dev, 1)

    comm_cost = 0.0
    if use_ilp and n_dev > 1:
        try:
            from alpa_tpu.shard_parallel.ilp import (solution_cost,
                                                     solve_strategy_graph)
            from alpa_tpu.shard_parallel.strategy import build_strategy_graph
            closed = comp.closed_jaxpr()
            graph = build_strategy_graph(closed, [v.aval for v in comp.invars],
                                         logical_mesh, [], as_option)
            choice = solve_strategy_graph(graph, time_limit=10)
            # alpha-beta units: beta=0.01 ~ 1 byte / (ICI ~100GB/s) scaled;
            # treat one cost unit as 1e-7 s (relative ranking is what
            # matters to the DP).
            comm_cost = solution_cost(graph, choice) * 1e-7
        except Exception as e:  # pylint: disable=broad-except
            logger.debug("stage ILP cost estimate failed: %s", e)
    return compute_cost + comm_cost


def estimate_stage_memory(stage_comps, logical_mesh: LogicalDeviceMesh,
                          num_in_flight: int = 1) -> float:
    """Rough per-device bytes: params/devices + activations in flight."""
    comp = stage_comps[0] if len(stage_comps) == 1 else None
    comps = stage_comps
    param_bytes = 0.0
    act_bytes = 0.0
    for c in comps:
        for v in c.invars:
            if hasattr(v.aval, "shape"):
                b = float(np.prod(v.aval.shape) or 1) * v.aval.dtype.itemsize
                param_bytes += b
        for v in c.outvars:
            if hasattr(v.aval, "shape"):
                act_bytes += float(np.prod(v.aval.shape) or 1) * \
                    v.aval.dtype.itemsize
    n = max(logical_mesh.num_devices, 1)
    return param_bytes / n + act_bytes * num_in_flight
