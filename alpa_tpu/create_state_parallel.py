"""CreateStateParallel: initialize the train state *already sharded*.

Analog of ref ``alpa/create_state_parallel.py`` (SURVEY.md §2.1): the state
initialization function is compiled with output shardings copied from an
already-compiled train step's input placement, so big models materialize
directly in their distributed layout (never unsharded on one host).
"""
import logging
from typing import Any, Callable, Optional, Sequence

import jax

from alpa_tpu.mesh_executable import NormalMeshExecutable
from alpa_tpu.parallel_method import ParallelMethod

logger = logging.getLogger(__name__)


class CreateStateParallel(ParallelMethod):
    """method=CreateStateParallel(train_step, state_example_args) for
    ``parallelize``-ing an init function (ref CreateStateParallel:336).

    ``train_step`` must be a ParallelizedFunc already compiled (or
    compilable) whose first argument is the state.
    """

    def __init__(self, train_step, train_step_args: Sequence[Any]):
        self.train_step = train_step
        self.train_step_args = train_step_args

    def compile_executable(self, fun, in_avals, in_tree, in_paths,
                           donated_invars, batch_invars):
        # Compile/fetch the target executable to read its input placement.
        executable, _ = self.train_step.get_executable(
            *self.train_step_args)

        from alpa_tpu.pipeline_parallel.pipeshard_executable import (
            PipeshardDriverExecutable)
        if isinstance(executable, PipeshardDriverExecutable):
            return _compile_create_state_pipeshard(fun, in_avals,
                                                   executable)
        # ShardParallel target: state leaves are the leading invars of the
        # train step; their shardings become our output shardings.
        n_out = len(jax.tree_util.tree_leaves(
            jax.eval_shape(fun, *in_avals)))
        out_shardings = list(executable.in_shardings[:n_out])
        jitted = jax.jit(fun, out_shardings=out_shardings)
        lowered = jitted.lower(*in_avals)
        compiled = lowered.compile()
        return NormalMeshExecutable(
            executable.physical_mesh, compiled,
            in_avals=in_avals, out_avals=None,
            in_shardings=[None] * len(in_avals),
            out_shardings=out_shardings,
            in_tree=in_tree, out_tree=None)


def _compile_create_state_pipeshard(fun, in_avals, pipeshard_exec):
    """Pipeshard target: every state leaf must materialize on the mesh its
    consuming stage lives on (ref compile_create_state_executable:73 /
    propagate_mesh_assignment:151)."""

    class _CreateStatePipeshardExecutable:

        def __init__(self):
            self.out_tree = None
            self.in_avals = in_avals

        def launch_on_driver(self, *flat_args):
            outs_host = jax.jit(fun)(*flat_args)
            # place each leaf per the pipeshard input placement
            flat_outs = list(outs_host)
            placed = []
            gin = pipeshard_exec.global_invars
            place = pipeshard_exec.input_place
            for i, x in enumerate(flat_outs):
                v = gin[i] if i < len(gin) else None
                if v is not None and v in place:
                    mesh_id, sharding = place[v][0]
                    placed.append(jax.device_put(x, sharding))
                else:
                    placed.append(x)
            return placed

    return _CreateStatePipeshardExecutable()
