"""Top-level user API: ``init``, ``shutdown``, ``@parallelize``, ``grad``.

Analog of ref ``alpa/api.py`` (SURVEY.md §2.1).  The decorator keeps the
reference's argument semantics — ``static_argnums``/``donate_argnums``
("auto" supported), ``batch_argnums`` marking data-batch args for microbatch
splitting and batch-dim sharding — and dispatches compilation to a
``ParallelMethod`` with per-(tree, avals, statics) executable caching
(ref api.py:209 ``_compile_parallel_executable`` lu.cache).
"""
import functools
import logging
import weakref
from typing import Any, Callable, Optional, Sequence, Union

import jax
import numpy as np
from jax.api_util import shaped_abstractify
from jax.tree_util import (keystr, tree_flatten, tree_flatten_with_path,
                           tree_unflatten)

from alpa_tpu.device_mesh import (init_global_cluster,
                                  shutdown_global_cluster)
from alpa_tpu.parallel_method import ParallelMethod, ShardParallel
from alpa_tpu.pipeline_parallel.primitive_def import mark_gradient

logger = logging.getLogger(__name__)

unsafe_are_we_inside_parallelize = False


def init(cluster: str = "local",
         devices: Optional[Sequence] = None,
         num_nodes: Optional[int] = None,
         num_devices_per_node: Optional[int] = None):
    """Initialize the device cluster (ref api.py:25).

    ``cluster='local'``: this process's devices (TPU chips of one host or the
    whole single-controller pod view).  ``cluster='distributed'``: call
    ``jax.distributed.initialize`` first for multi-host pods.
    """
    init_global_cluster(cluster, devices, num_nodes, num_devices_per_node)


def shutdown():
    """Release cluster state (ref api.py:59)."""
    shutdown_global_cluster()


def _is_static_arg(arg) -> bool:
    leaves, _ = tree_flatten(arg)
    if not leaves:
        return True
    return not any(
        isinstance(x, (jax.Array, np.ndarray, float, int, complex, bool)) or
        hasattr(x, "aval") for x in leaves)


def _is_state_like(arg) -> bool:
    """True for flax TrainState(-like) args — the only auto-donate targets.

    Mirrors the reference's ``auto_donate_argnums`` which donates only
    TrainState arguments; donating anything whose (shape, dtype) happens to
    match an output (e.g. params when the step returns grads) deletes
    buffers the caller still holds.
    """
    try:
        from flax.training import train_state
        if isinstance(arg, train_state.TrainState):
            return True
    except ImportError:
        pass
    # duck-typed custom TrainState variants
    return hasattr(arg, "apply_gradients") and hasattr(arg, "params")


def _abstractify(x):
    if hasattr(x, "aval"):
        a = x.aval
        return jax.ShapeDtypeStruct(a.shape, a.dtype)
    # canonicalize like jax tracing would (python int -> int32 when x64
    # is off), so cache keys from host values match device round-trips
    a = shaped_abstractify(x)
    return jax.ShapeDtypeStruct(a.shape, a.dtype)


# live ParallelizedFunc registry for clear_executable_cache (ref
# api.py clear_executable_cache); weak so decorated functions are
# collectable
_live_parallelized: "weakref.WeakSet" = weakref.WeakSet()


def clear_executable_cache():
    """Drop every compiled executable cached by @parallelize functions
    (ref alpa.clear_executable_cache): the next call recompiles."""
    for pf in list(_live_parallelized):
        pf._executable_cache.clear()
        pf._last_executable = None


class ParallelizedFunc:
    """The callable returned by ``@parallelize`` (ref api.py:106)."""

    def __init__(self,
                 fun: Callable,
                 method: Optional[ParallelMethod],
                 static_argnums: Union[str, Sequence[int]] = "auto",
                 donate_argnums: Union[str, Sequence[int]] = "auto",
                 batch_argnums: Sequence[int] = (1,)):
        functools.update_wrapper(self, fun)
        self.fun = fun
        self.method = method or ShardParallel()
        self.static_argnums = static_argnums
        self.donate_argnums = donate_argnums
        self.batch_argnums = tuple(batch_argnums)
        self._executable_cache = {}
        self._last_executable = None
        _live_parallelized.add(self)

    # ---- compilation ----
    def _decode_args(self, args):
        """Split static/dynamic args, flatten, build metadata."""
        if self.static_argnums == "auto":
            static_idx = tuple(
                i for i, a in enumerate(args) if _is_static_arg(a))
        else:
            static_idx = tuple(self.static_argnums)
        dyn_idx = tuple(i for i in range(len(args)) if i not in static_idx)
        static_vals = tuple(args[i] for i in static_idx)
        dyn_args = tuple(args[i] for i in dyn_idx)

        path_leaves, in_tree = tree_flatten_with_path(dyn_args)
        in_paths = tuple(keystr(p) for p, _ in path_leaves)
        flat_args = [x for _, x in path_leaves]
        avals = tuple(_abstractify(x) for x in flat_args)

        # flat flags: does this leaf belong to a batch / state argument?
        batch_set = set(self.batch_argnums)
        state_args = set(
            i for i, a in enumerate(dyn_args) if _is_state_like(a))
        batch_invars = []
        state_invars = []
        for (path, _x) in path_leaves:
            top = path[0].idx  # index into dyn_args tuple
            orig_idx = dyn_idx[top]
            batch_invars.append(orig_idx in batch_set)
            state_invars.append(top in state_args)

        return (static_idx, static_vals, dyn_idx, flat_args, in_tree,
                in_paths, avals, tuple(batch_invars), tuple(state_invars))

    def _infer_donation(self, flat_fun, avals, batch_invars, state_invars):
        """donate_argnums='auto': donate leaves of TrainState-like args
        whose (shape,dtype) matches an unclaimed output leaf (state flowing
        to new state).  Non-state args are never auto-donated — a step
        returning (loss, grads) shape-matches every param leaf, and donating
        params the caller still holds deletes live buffers."""
        out_shapes = jax.eval_shape(flat_fun, *avals)
        # Cache on the fun so compile paths don't re-trace (see
        # compile_shard_executable's _pin_state_out_shardings).
        flat_fun.out_shapes = out_shapes
        pool = {}
        for o in tree_flatten(out_shapes)[0]:
            pool[(tuple(o.shape), np.dtype(o.dtype))] = pool.get(
                (tuple(o.shape), np.dtype(o.dtype)), 0) + 1
        donated = []
        for aval, is_batch, is_state in zip(avals, batch_invars,
                                            state_invars):
            key = (tuple(aval.shape), np.dtype(aval.dtype))
            if is_state and not is_batch and pool.get(key, 0) > 0:
                pool[key] -= 1
                donated.append(True)
            else:
                donated.append(False)
        if any(donated):
            logger.debug("auto-donated %d/%d input leaves (TrainState args)",
                         sum(donated), len(donated))
        return tuple(donated)

    def get_executable(self, *args):
        (static_idx, static_vals, dyn_idx, flat_args, in_tree, in_paths,
         avals, batch_invars, state_invars) = self._decode_args(args)
        key = (in_tree, avals, static_idx, static_vals, batch_invars)
        try:
            cached = self._executable_cache.get(key)
        except TypeError:  # unhashable static arg
            key = None
            cached = None
        if cached is not None:
            self._last_executable = cached
            return cached, flat_args

        out_tree_store = [None]
        fun = self.fun
        arg_count = len(args)

        def flat_fun(*flat):
            dyn = tree_unflatten(in_tree, list(flat))
            full = []
            di = iter(dyn)
            si = iter(static_vals)
            for i in range(arg_count):
                full.append(next(si) if i in static_idx else next(di))
            out = fun(*full)
            flat_out, out_tree = tree_flatten(out)
            out_tree_store[0] = out_tree
            return flat_out

        if self.donate_argnums == "auto":
            donated_invars = self._infer_donation(flat_fun, avals,
                                                  batch_invars, state_invars)
        else:
            donate_set = set(self.donate_argnums)
            donated_invars = tuple(
                dyn_idx[p[0].idx] in donate_set
                for p, _ in tree_flatten_with_path(
                    tree_unflatten(in_tree, list(avals)))[0])

        executable = self.method.compile_executable(flat_fun, avals, in_tree,
                                                    in_paths, donated_invars,
                                                    batch_invars)
        self._save_parallel_plan(executable, avals, in_paths, batch_invars,
                                 donated_invars)
        if out_tree_store[0] is None:
            # method didn't trace eagerly; force one abstract eval
            jax.eval_shape(flat_fun, *avals)
        executable.out_tree = out_tree_store[0]
        if key is not None:
            self._executable_cache[key] = executable
        self._last_executable = executable
        return executable, flat_args

    def _save_parallel_plan(self, executable, avals, in_paths, batch_invars,
                            donated_invars):
        """Persist the replayable ParallelPlan artifact of this compile in
        the ``parallel_plan`` cache namespace (ISSUE 2): a warm restart can
        rebuild the ParallelMethod from the plan (``plan_to_method``)
        without re-running stage construction or the ILP, and
        ``scripts/cache_tool.py`` can inspect what was compiled.  Purely
        archival — failures never break compilation."""
        from alpa_tpu.compile_cache import cache_enabled, get_compile_cache
        if not cache_enabled():
            return
        try:
            from alpa_tpu.parallel_plan import executable_to_plan
            plan = executable_to_plan(
                executable,
                num_micro_batches=getattr(self.method, "num_micro_batches",
                                          None))
            cache = get_compile_cache()
            method_desc = "{}({})".format(
                type(self.method).__name__,
                ",".join(f"{k}={v!r}" for k, v in
                         sorted(vars(self.method).items())))
            key = cache.make_key("parallel_plan", [
                "parallelize",
                getattr(self.fun, "__module__", "?"),
                getattr(self.fun, "__qualname__", repr(self.fun)),
                repr([str(a) for a in avals]),
                repr(tuple(in_paths)),
                repr(tuple(batch_invars)),
                repr(tuple(donated_invars)),
                method_desc,
            ])
            cache.put("parallel_plan", key, plan)
        except Exception:  # pylint: disable=broad-except
            logger.debug("parallel_plan artifact save failed", exc_info=True)

    def __call__(self, *args):
        executable, flat_args = self.get_executable(*args)
        flat_out = executable.launch_on_driver(*flat_args)
        return tree_unflatten(executable.out_tree, list(flat_out))

    def get_last_executable(self):
        return self._last_executable


def parallelize(fun: Optional[Callable] = None,
                *,
                method: Optional[ParallelMethod] = None,
                static_argnums: Union[str, Sequence[int]] = "auto",
                donate_argnums: Union[str, Sequence[int]] = "auto",
                batch_argnums: Sequence[int] = (1,)):
    """Parallelize a single-device jax function (ref api.py:71)."""

    def decorate(f):
        return ParallelizedFunc(f, method, static_argnums, donate_argnums,
                                batch_argnums)

    if fun is None:
        return decorate
    return decorate(fun)


def _maybe_layer_transform(fun):
    """Apply the active pipeline layer transform to a loss function.

    The pipeline compile driver installs a LayerOption context while
    tracing (ref: the reference applies manual/automatic_layer_construction
    decorators to the loss fn); here ``alpa_tpu.grad`` picks it up so users
    don't decorate the loss function themselves.
    """
    from alpa_tpu.pipeline_parallel.layer_construction import (
        current_layer_option, layer_level_transform)
    opt = current_layer_option()
    if opt is None:
        return fun
    return layer_level_transform(fun, opt)


def grad(fun, *args, **kwargs):
    """``jax.grad`` + gradient boundary marker (ref api.py:241).

    Use this instead of ``jax.grad`` inside parallelized functions so that
    gradient accumulation and pipeline compilation can split compute_grad
    from apply_grad at the marker.
    """

    @functools.wraps(fun)
    def wrapped(*call_args, **call_kwargs):
        jax_grad = jax.grad(_maybe_layer_transform(fun), *args, **kwargs)
        return mark_gradient(jax_grad(*call_args, **call_kwargs))

    return wrapped


def value_and_grad(fun, *args, **kwargs):
    """``jax.value_and_grad`` + gradient marker (ref api.py:265)."""

    @functools.wraps(fun)
    def wrapped(*call_args, **call_kwargs):
        jax_vg = jax.value_and_grad(_maybe_layer_transform(fun), *args,
                                    **kwargs)
        val, grads = jax_vg(*call_args, **call_kwargs)
        return mark_gradient((val, grads))

    return wrapped
