"""Process-level backend pinning helpers.

The container attaches one real TPU chip through the axon PJRT plugin
(registered by a sitecustomize on PYTHONPATH), which pins
``jax_platforms``.  Tests and the multi-chip dryrun instead need an
n-device virtual CPU backend; this helper is the single place that
knows how to force it (used by ``tests/conftest.py`` and
``__graft_entry__.dryrun_multichip``).
"""
import os


def set_cpu_device_count(n_devices: int) -> None:
    """Request ``n_devices`` virtual CPU devices WITHOUT touching the
    backend (multi-process workers must still run
    ``jax.distributed.initialize`` afterwards, which a backend probe
    would break).  Must run before the first jax backend use."""
    import jax

    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        # Older jax has no jax_num_cpu_devices config option; the XLA
        # flag is the portable spelling and is read at first backend
        # initialization, which hasn't happened yet on this path.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{n_devices}").strip()
    except RuntimeError as e:
        raise RuntimeError(
            "CPU pin ineffective — a jax backend was already initialized "
            "in this process; call pin_cpu_platform() before any jax "
            "operation, or run in a fresh process") from e


def pin_cpu_platform(n_devices: int) -> None:
    """Pin this process to an ``n_devices``-device virtual CPU backend.

    Must run before the first jax backend use.  Mutates process-global
    jax config and initializes the backend to verify the pin took: any
    later work in the same process sees the CPU backend — run TPU work
    in a separate process.
    """
    import jax

    set_cpu_device_count(n_devices)
    devices = jax.devices()
    assert devices[0].platform == "cpu" and len(devices) == n_devices, (
        f"expected {n_devices} cpu devices, got {devices}")
