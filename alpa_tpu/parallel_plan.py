"""Serializable record of a full parallelization decision.

Analog of ref ``alpa/parallel_plan.py`` (SURVEY.md §2.1): captures enough
of the solved plan (cluster shape, logical mesh, stage partition, chosen
input shardings) to rebuild a ParallelMethod that replays it without
searching (``plan_to_method``, ref :57).
"""
import dataclasses
import pickle
from typing import Any, Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class PlacementSpec:
    """Where one tensor lives (ref :14)."""
    aval_shape: Tuple[int, ...]
    mesh_ids: List[int]
    partition_specs: List[Any]  # PartitionSpec per mesh


@dataclasses.dataclass
class StagePlan:
    """Intra-op decisions of one stage (ref :22)."""
    logical_mesh_shape: Tuple[int, ...]
    input_partition_specs: Optional[List[Any]] = None
    auto_sharding_solution: Optional[List[int]] = None


@dataclasses.dataclass
class PipelinePlan:
    """Inter-op decisions (ref :34)."""
    pipeline_schedule: str
    layer_option: Any
    forward_stage_layer_ids: List[List[int]]
    submesh_physical_shapes: List[Tuple[int, int]]
    submesh_logical_shapes: List[Optional[Tuple[int, int]]]


@dataclasses.dataclass
class ClusterInfo:
    num_hosts: int
    num_devices_per_host: int


@dataclasses.dataclass
class ParallelPlan:
    """The whole decision (ref :48)."""
    cluster_info: ClusterInfo
    num_micro_batches: Optional[int]
    pipeline_plan: Optional[PipelinePlan] = None
    stage_plans: Optional[List[StagePlan]] = None
    input_placement_specs: Optional[List[PlacementSpec]] = None

    def save(self, filename: str):
        with open(filename, "wb") as f:
            pickle.dump(self, f)

    def fingerprint(self) -> str:
        """Stable content hash of the plan — checkpoint manifests record
        it (``checkpoint.CheckpointManager.save(plan_fingerprint=...)``)
        so resume can refuse weights saved under a different
        parallelization.  Dataclass reprs are value-based, so two equal
        plans hash identically across processes."""
        import hashlib
        return hashlib.sha256(repr(self).encode()).hexdigest()

    @classmethod
    def load(cls, filename: str) -> "ParallelPlan":
        with open(filename, "rb") as f:
            return pickle.load(f)


def plan_to_method(plan: ParallelPlan):
    """Rebuild a ParallelMethod replaying a saved plan (ref :57)."""
    from alpa_tpu.parallel_method import PipeshardParallel, ShardParallel
    from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption

    if plan.pipeline_plan is None:
        shape = (plan.stage_plans[0].logical_mesh_shape
                 if plan.stage_plans else None)
        return ShardParallel(
            num_micro_batches=plan.num_micro_batches,
            auto_sharding_option=AutoShardingOption(
                logical_mesh_shape=shape))
    from alpa_tpu.pipeline_parallel.stage_construction import (
        ManualStageOption)
    pp = plan.pipeline_plan
    return PipeshardParallel(
        num_micro_batches=plan.num_micro_batches or 1,
        pipeline_schedule=pp.pipeline_schedule,
        layer_option=pp.layer_option,
        stage_option=ManualStageOption(
            forward_stage_layer_ids=pp.forward_stage_layer_ids,
            submesh_physical_shapes=[list(s) for s in
                                     pp.submesh_physical_shapes],
            submesh_logical_shapes=list(pp.submesh_logical_shapes),
            submesh_autosharding_option_dicts=[{} for _ in
                                               pp.forward_stage_layer_ids]))


def executable_to_plan(executable, num_micro_batches=None) -> ParallelPlan:
    """Extract a replayable plan from a compiled executable."""
    from alpa_tpu.pipeline_parallel.pipeshard_executable import (
        PipeshardDriverExecutable)

    if isinstance(executable, PipeshardDriverExecutable):
        meshes = executable.mesh_group
        pp = PipelinePlan(
            pipeline_schedule=executable.schedule_name,
            layer_option=None,
            forward_stage_layer_ids=[[i] for i in range(
                executable.num_fwd_stages)],
            submesh_physical_shapes=[tuple(m.shape) for m in meshes],
            submesh_logical_shapes=[None] * len(meshes),
        )
        cluster = ClusterInfo(
            sum(m.num_hosts for m in meshes),
            meshes[0].num_devices_per_host if len(meshes) else 1)
        return ParallelPlan(cluster_info=cluster,
                            num_micro_batches=executable.num_micro_batches,
                            pipeline_plan=pp)
    mesh = executable.physical_mesh
    sp = StagePlan(logical_mesh_shape=tuple(mesh.shape),
                   input_partition_specs=[s.spec for s in
                                          executable.in_shardings])
    return ParallelPlan(
        cluster_info=ClusterInfo(mesh.num_hosts,
                                 mesh.num_devices_per_host),
        num_micro_batches=num_micro_batches,
        stage_plans=[sp])
