"""Shared utilities for alpa_tpu.

TPU-native analog of the reference's ``alpa/util.py``.  The Ray placement
group, NCCL and pickled-HLO helpers disappear; the jaxpr manipulation, HLO
text analysis, and flops-accounting helpers survive in jax-idiomatic form.
"""
import functools
import itertools
import re
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.tree_util import tree_flatten, tree_unflatten
from jax.extend.core import ClosedJaxpr, Jaxpr, Var, Literal

########################################
# Data structures
########################################


class OrderedSet:
    """Insertion-ordered set (ref: alpa/util.py:159)."""

    def __init__(self, iterable=()):
        self._dict = dict.fromkeys(iterable)

    def add(self, item):
        self._dict[item] = None

    def update(self, iterable):
        for x in iterable:
            self._dict[x] = None

    def discard(self, item):
        self._dict.pop(item, None)

    def remove(self, item):
        del self._dict[item]

    def union(self, *others):
        out = OrderedSet(self)
        for o in others:
            out.update(o)
        return out

    def intersection(self, *others):
        out = OrderedSet()
        for x in self._dict:
            if all(x in o for o in others):
                out.add(x)
        return out

    def difference(self, *others):
        out = OrderedSet()
        for x in self._dict:
            if not any(x in o for o in others):
                out.add(x)
        return out

    def intersection_update(self, *others):
        self._dict = self.intersection(*others)._dict

    def difference_update(self, *others):
        self._dict = self.difference(*others)._dict

    def pop(self):
        key = next(iter(self._dict))
        del self._dict[key]
        return key

    def __or__(self, other):
        return self.union(other)

    def __and__(self, other):
        return self.intersection(other)

    def __sub__(self, other):
        return self.difference(other)

    def __contains__(self, item):
        return item in self._dict

    def __iter__(self):
        return iter(self._dict)

    def __len__(self):
        return len(self._dict)

    def __bool__(self):
        return bool(self._dict)

    def __repr__(self):
        return f"OrderedSet({list(self._dict)})"

    def __eq__(self, other):
        if isinstance(other, (OrderedSet, set, frozenset)):
            return set(self._dict) == set(other)
        return NotImplemented


########################################
# jaxpr helpers
########################################


def clone_jaxpr(closed_jaxpr: ClosedJaxpr,
                invars=None,
                outvars=None,
                eqns=None,
                constvars=None,
                consts=None) -> ClosedJaxpr:
    """Build a new ClosedJaxpr overriding selected fields."""
    jaxpr = closed_jaxpr.jaxpr
    kwargs = dict(
        invars=list(invars) if invars is not None else jaxpr.invars,
        outvars=list(outvars) if outvars is not None else jaxpr.outvars,
        eqns=list(eqns) if eqns is not None else jaxpr.eqns,
        constvars=list(constvars) if constvars is not None else jaxpr.constvars,
    )
    dbg = getattr(jaxpr, "debug_info", None)
    if dbg is not None and (
            len(getattr(dbg, "arg_names", ())) != len(kwargs["invars"]) or
            len(getattr(dbg, "result_paths", ())) !=
            len(kwargs["outvars"])):
        # the traced-for debug names no longer line up with the cloned
        # signature; newer jax asserts on the mismatch at construction
        kwargs["debug_info"] = None
    new_jaxpr = jaxpr.replace(**kwargs)
    new_consts = list(consts) if consts is not None else closed_jaxpr.consts
    return ClosedJaxpr(new_jaxpr, new_consts)


def new_jaxpr_eqn(invars, outvars, primitive, params, effects=None,
                  source_info=None):
    """Create a JaxprEqn across jax versions."""
    from jax._src import core as src_core
    return src_core.new_jaxpr_eqn(invars, outvars, primitive, params,
                                  effects or src_core.no_effects, source_info)


_var_count = itertools.count()


def gensym_var(aval, suffix: str = "") -> Var:
    """Create a fresh Var with the given abstract value."""
    from jax._src import core as src_core
    try:
        return src_core.Var(aval)
    except TypeError:
        return src_core.Var(suffix, aval)


def eqn_invars_nonlit(eqn) -> List[Var]:
    return [v for v in eqn.invars if isinstance(v, Var)]


def jaxpr_free_vars(jaxpr: Jaxpr) -> OrderedSet:
    """Variables read before being defined (excluding invars/constvars)."""
    defined = OrderedSet(jaxpr.constvars)
    defined.update(jaxpr.invars)
    free = OrderedSet()
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if isinstance(v, Var) and v not in defined:
                free.add(v)
        defined.update(eqn.outvars)
    for v in jaxpr.outvars:
        if isinstance(v, Var) and v not in defined:
            free.add(v)
    return free


def abstractify_with_aval(x):
    if hasattr(x, "aval"):
        return x.aval
    return jax.api_util.shaped_abstractify(x)


def trace_to_closed_jaxpr(fun: Callable, *avals) -> Tuple[ClosedJaxpr, Any]:
    """Trace ``fun`` on abstract values; returns (closed_jaxpr, out_tree)."""
    jaxpr, out_shapes = jax.make_jaxpr(fun, return_shape=True)(*avals)
    out_tree = jax.tree_util.tree_structure(out_shapes)
    return jaxpr, out_tree


########################################
# HLO text analysis
########################################

# Matches the opcode position in an HLO instruction line:
#   %name = f32[128]{0} all-reduce(...)
#   %name = (f32[4]{0}, f32[4]{0}) all-reduce-start(...)
# Group 1 captures the opcode; operand references never match because they
# appear inside the parens, after the opcode.
# The type prefix may be a scalar/array type or a tuple; tuples can contain
# parens one level deep (TPU tiled layouts like {1,0:T(8,128)}).
_HLO_OP_RE = re.compile(
    r"=\s*(?:\((?:[^()]|\([^()]*\))*\)|[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?)\s*"
    r"([a-z0-9-]+)(?:\.\d+)?\(")

_COLLECTIVE_OPS = {
    "all-reduce": ("all-reduce", "all-reduce-start"),
    "all-gather": ("all-gather", "all-gather-start"),
    "reduce-scatter": ("reduce-scatter",),
    "all-to-all": ("all-to-all",),
    "collective-permute": ("collective-permute", "collective-permute-start"),
}
_OP_TO_KIND = {op: kind for kind, ops in _COLLECTIVE_OPS.items() for op in ops}


def count_communication_primitives(hlo_text: str,
                                   ignore_scalar_all_reduce: bool = False):
    """Count collectives in optimized HLO text.

    TPU analog of ref ``alpa/util.py:400``: returns
    (total, all_reduce, all_gather, reduce_scatter, all_to_all).
    Only counts op definitions (opcode position), not operand references.
    """
    counts = {k: 0 for k in _COLLECTIVE_OPS}
    for line in hlo_text.splitlines():
        m = _HLO_OP_RE.search(line)
        if not m:
            continue
        kind = _OP_TO_KIND.get(m.group(1))
        if kind is None:
            continue
        if (ignore_scalar_all_reduce and kind == "all-reduce" and
                re.search(r"=\s*[a-z0-9]+\[\]", line)):
            continue
        counts[kind] += 1
    total = sum(counts.values())
    return (total, counts["all-reduce"], counts["all-gather"],
            counts["reduce-scatter"], counts["all-to-all"])


def get_compiled_hlo_text(fn, *args, **jit_kwargs) -> str:
    """Compile a function and return post-optimization HLO text."""
    return jax.jit(fn, **jit_kwargs).lower(*args).compile().as_text()


########################################
# Benchmark / flops accounting
########################################


def compute_gpt_parameter_count(num_layers, hidden_size, vocab_size):
    """Analytic GPT param count (ref: alpa/util.py 'compute_gpt_parameter_count')."""
    return (num_layers * (
        # self attention
        hidden_size * (3 * hidden_size + 1) + hidden_size * (hidden_size + 1) +
        # mlp
        hidden_size * (4 * hidden_size + 1) + hidden_size * 4 * (hidden_size + 1) +
        # layer norm
        hidden_size * 4) + vocab_size * (hidden_size + 1))


def compute_gpt_tflops(batch_size,
                       seq_len,
                       num_layers,
                       hidden_size,
                       vocab_size,
                       num_devices,
                       latency,
                       backward=True,
                       checkpoint_activations=False):
    """Analytic GPT TFLOPS (ref: alpa/util.py:1658-1692)."""
    factor = 24
    if backward:
        factor += 48
        if checkpoint_activations:
            factor += 24
    total_flop = (factor * batch_size * seq_len * (hidden_size**2) * num_layers *
                  (1 + seq_len / (6 * hidden_size)) +
                  (6 if backward else 2) * batch_size * seq_len * hidden_size * vocab_size)
    tflops = total_flop / latency / num_devices / 1e12
    return tflops


def compute_moe_tflops(batch_size, seq_len, num_layers, hidden_size,
                       group_size, vocab_size, num_experts, num_devices,
                       latency, backward=True, checkpoint_activations=False,
                       mlp_factor=8):
    """Analytic MoE transformer TFLOPS (ref: alpa/util.py compute_moe_tflops)."""
    factor = 24 if not backward else 72
    if checkpoint_activations:
        factor += 24
    pure_transformer = (batch_size * seq_len * (hidden_size**2) * num_layers / 2 *
                        (factor / 24) * 24 * (1 + seq_len / (6 * hidden_size)))
    moe_transformer = (batch_size * seq_len * (hidden_size**2) * num_layers / 2 *
                       (factor / 24) * (4 * mlp_factor + 8))
    embedding = ((6 if backward else 2) * batch_size * seq_len * hidden_size *
                 vocab_size)
    total_flop = pure_transformer + moe_transformer + embedding
    return total_flop / latency / num_devices / 1e12


def write_tsv(heads: Sequence[str],
              values: Sequence[Any],
              filename: str,
              print_line: bool = True):
    """Append one TSV record (ref: alpa/util.py:1276)."""
    assert len(heads) == len(values)
    with open(filename, mode="a", encoding="utf-8") as fout:
        fout.write("\t".join(str(x) for x in values) + "\n")
    if print_line:
        print(" | ".join(f"{h}: {v}" for h, v in zip(heads, values)))


def benchmark_func(run_func,
                   sync_func=None,
                   warmup=1,
                   repeat=3,
                   number=5) -> np.ndarray:
    """Time run_func; returns per-repeat average seconds (ref util.benchmark_func)."""
    for _ in range(warmup):
        run_func()
    if sync_func:
        sync_func()
    costs = []
    for _ in range(repeat):
        if sync_func:
            sync_func()
        tic = time.perf_counter()
        for _ in range(number):
            run_func()
        if sync_func:
            sync_func()
        costs.append((time.perf_counter() - tic) / number)
    return np.array(costs)


########################################
# Tree/arg helpers
########################################


def tree_leaf_count(tree) -> int:
    return len(tree_flatten(tree)[0])


def split_list(lst, sizes):
    """Split a flat list into chunks of the given sizes."""
    out, start = [], 0
    for s in sizes:
        out.append(lst[start:start + s])
        start += s
    assert start == len(lst)
    return out


def to_int_tuple(x) -> Tuple[int, ...]:
    return tuple(int(v) for v in x)


def divide_evenly(total: int, parts: int) -> List[int]:
    base, rem = divmod(total, parts)
    return [base + (1 if i < rem else 0) for i in range(parts)]


def aval_bytes(aval) -> int:
    return int(np.prod(aval.shape)) * aval.dtype.itemsize if aval.shape else aval.dtype.itemsize


def jaxpr_eqn_flops(eqn) -> float:
    """Cheap analytic flop count for one jaxpr eqn.

    Mirrors ref ``alpa/pipeline_parallel/layer_stats.py:eqn_flops`` in spirit:
    dots and convs dominate; elementwise ops count size; control-flow counts
    its body.
    """
    prim = eqn.primitive.name
    if prim == "dot_general":
        d = eqn.params["dimension_numbers"]
        (lhs_contract, _), (lhs_batch, _) = d
        lhs = eqn.invars[0].aval
        rhs = eqn.invars[1].aval
        out = eqn.outvars[0].aval
        contract_size = int(np.prod([lhs.shape[i] for i in lhs_contract])) or 1
        return 2.0 * float(np.prod(out.shape)) * contract_size
    if prim in ("conv_general_dilated",):
        out = eqn.outvars[0].aval
        rhs = eqn.invars[1].aval
        return 2.0 * float(np.prod(out.shape)) * float(np.prod(rhs.shape[:-1]))
    if prim in ("custom_jvp_call", "custom_vjp_call", "pjit", "jit",
                "closed_call", "remat", "checkpoint",
                "custom_vjp_call_jaxpr"):
        sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if sub is None:
            return 0.0
        sub_jaxpr = sub.jaxpr if isinstance(sub, ClosedJaxpr) else sub
        return sum(jaxpr_eqn_flops(e) for e in sub_jaxpr.eqns)
    if prim in ("scan", "while"):
        sub = eqn.params.get("jaxpr") or eqn.params.get("body_jaxpr")
        if sub is None:
            return 0.0
        sub_jaxpr = sub.jaxpr if isinstance(sub, ClosedJaxpr) else sub
        n = eqn.params.get("length", 1)
        return n * sum(jaxpr_eqn_flops(e) for e in sub_jaxpr.eqns)
    if eqn.outvars and hasattr(eqn.outvars[0], "aval") and eqn.outvars[0].aval.shape:
        return float(np.prod(eqn.outvars[0].aval.shape))
    return 0.0


def clusters_to_str(clusters) -> str:
    return " | ".join(",".join(str(x) for x in c) for c in clusters)
