"""HTTP serving controller.

Analog of ref ``alpa/serve/controller.py:96`` (Controller Ray actor with
uvicorn/starlette ingress + model registry + replica dispatch) — rebuilt on
the standard library: a ``ThreadingHTTPServer`` front end, a registry of
named models, round-robin replica dispatch, and per-model locks (device
execution is serialized per replica; concurrent requests to different
models overlap through jax's async dispatch).

Endpoints:
  GET  /models                          -> registered model names
  POST /completions                     -> {"model", "prompt_ids",
        "max_new_tokens"?, "temperature"?, "top_k"?, "do_sample"?}
        => {"output_ids": [[...]]}
  GET  /health                          -> liveness
"""
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

import numpy as np

from alpa_tpu.serve.generation import GenerationConfig, Generator

logger = logging.getLogger(__name__)


class _Replica:

    def __init__(self, generator: Generator):
        self.generator = generator
        self.lock = threading.Lock()


class Controller:
    """Model registry + dispatch (ref controller.py:96)."""

    def __init__(self):
        self._models: Dict[str, List[_Replica]] = {}
        self._rr: Dict[str, int] = {}
        self._lock = threading.Lock()

    def register_model(self, name: str, generator: Generator):
        with self._lock:
            self._models.setdefault(name, []).append(_Replica(generator))
            self._rr.setdefault(name, 0)
        logger.info("registered model %s (%d replicas)", name,
                    len(self._models[name]))

    def list_models(self) -> List[str]:
        return sorted(self._models)

    def _pick_replica(self, name: str) -> _Replica:
        with self._lock:
            replicas = self._models[name]
            i = self._rr[name] % len(replicas)
            self._rr[name] += 1
        return replicas[i]

    def completions(self, request: Dict[str, Any]) -> Dict[str, Any]:
        name = request["model"]
        if name not in self._models:
            raise KeyError(f"unknown model {name!r}; "
                           f"registered: {self.list_models()}")
        prompt_ids = np.asarray(request["prompt_ids"], np.int32)
        if prompt_ids.ndim == 1:
            prompt_ids = prompt_ids[None]
        cfg = GenerationConfig(
            max_new_tokens=int(request.get("max_new_tokens", 32)),
            temperature=float(request.get("temperature", 1.0)),
            top_k=int(request.get("top_k", 0)),
            do_sample=bool(request.get("do_sample", False)),
            eos_token_id=request.get("eos_token_id"))
        replica = self._pick_replica(name)
        with replica.lock:
            out = replica.generator.generate(prompt_ids, cfg)
        return {"output_ids": out.tolist()}


class _Handler(BaseHTTPRequestHandler):
    controller: Controller = None  # set by run_controller

    def log_message(self, fmt, *args):  # quiet
        logger.debug(fmt, *args)

    def _send(self, code: int, payload: Dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/health":
            self._send(200, {"status": "ok"})
        elif self.path == "/models":
            self._send(200, {"models": self.controller.list_models()})
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        if self.path != "/completions":
            self._send(404, {"error": f"unknown path {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            request = json.loads(self.rfile.read(length) or b"{}")
            result = self.controller.completions(request)
            self._send(200, result)
        except KeyError as e:
            self._send(404, {"error": str(e)})
        except (json.JSONDecodeError, ValueError, AssertionError,
                TypeError) as e:
            self._send(400, {"error": f"bad request: {e}"})
        except Exception as e:  # pylint: disable=broad-except
            logger.exception("completions failed")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})


class ControllerServer:
    """The running HTTP server (ref run_controller:280)."""

    def __init__(self, controller: Controller, host: str, port: int):
        handler = type("BoundHandler", (_Handler,),
                       {"controller": controller})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.controller = controller
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self):
        self.thread.start()

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def run_controller(host: str = "127.0.0.1",
                   port: int = 8000,
                   start: bool = True) -> ControllerServer:
    """Create (and start) a controller server (ref run_controller:280)."""
    server = ControllerServer(Controller(), host, port)
    if start:
        server.start()
    return server
