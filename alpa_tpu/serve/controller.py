"""HTTP serving controller.

Analog of ref ``alpa/serve/controller.py:96`` (Controller Ray actor with
uvicorn/starlette ingress + model registry + replica dispatch) — rebuilt on
the standard library: a ``ThreadingHTTPServer`` front end, a registry of
named models, round-robin replica dispatch, and a per-replica
``RequestBatcher`` that coalesces concurrent requests into one
mixed-length batched generate call (device execution is serialized per
replica by the batcher's single worker thread).

Endpoints:
  GET  /models                          -> registered model names
  POST /completions                     -> {"model", "prompt_ids",
        "max_new_tokens"?, "temperature"?, "top_k"?, "do_sample"?}
        => {"output_ids": [[...]]}
  POST /disagg/prefill                  -> same body as /completions;
        => KV handoff artifact wire JSON (serve.disagg)
  POST /disagg/ingest                   -> artifact wire JSON; => SSE
        token stream joining the decode batch
  POST /disagg/fetch | /disagg/ack      -> {"request_id"}: retained-
        artifact re-ingest source / release
  GET  /health                        -> {"status": "ok" | "degraded"
        | "shedding"} (503 when shedding; see docs/fault_tolerance.md)
  GET  /healthz                         -> recovery-state liveness probe
        (200 healthy/suspect/recovering, 503 degraded;
        docs/observability.md)
  GET  /metrics                         -> Prometheus text exposition of
        the process metrics registry (docs/observability.md)
"""
import dataclasses
import json
import logging
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

import numpy as np

from alpa_tpu import fault
from alpa_tpu.global_env import global_config
from alpa_tpu.serve.generation import GenerationConfig, Generator
from alpa_tpu.telemetry import metrics as _tmetrics
from alpa_tpu.telemetry import trace as _ttrace

logger = logging.getLogger(__name__)

_REG = _tmetrics.get_registry()
_QUEUE_DEPTH = _REG.gauge(
    "alpa_serving_queue_depth", "Requests waiting in the batcher queue")
_BATCH_SIZE = _REG.histogram(
    "alpa_serving_batch_size", "Prompts per batched generate call",
    buckets=(1, 2, 4, 8, 16, 32, 64))
_BATCHES = _REG.counter(
    "alpa_serving_batches_total", "Batched generate calls executed")
_REQUESTS = _REG.counter(
    "alpa_serving_requests_total", "Completion requests by outcome",
    labelnames=("outcome",))
_REQ_LATENCY = _REG.histogram(
    "alpa_serving_request_seconds",
    "End-to-end /completions latency (batched path)")


class RequestBatcher:
    """Groups concurrent completion requests into ONE mixed-length
    batched ``Generator.generate`` call (iteration-level batching; the
    analog of ref ``wrapper_1d.py``'s 1-D batching).  Requests arriving
    while the device is busy queue up and ride the next batch instead of
    serializing one generate per request.  Only requests with identical
    sampling settings share a batch; ``max_new_tokens`` may differ (the
    batch runs to the max, each request is truncated to its own)."""

    def __init__(self, generator: Generator, max_batch: int = 8,
                 max_wait_ms: float = 2.0, prefix=None, scheduler=None):
        self.generator = generator
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1e3
        # shared system-prompt handle: prompts are suffixes over it, in
        # BOTH the batched and streaming paths (same request semantics)
        self.prefix = prefix
        if scheduler is None:
            from alpa_tpu.serve.scheduler import FIFOQueue
            scheduler = FIFOQueue()
        for method in ("append", "take", "drain", "__len__"):
            if not hasattr(scheduler, method):
                # fail at REGISTRATION, loudly: a protocol gap surfacing
                # inside the worker thread would kill it and hang every
                # submit() forever
                raise TypeError(
                    f"scheduler {type(scheduler).__name__} lacks "
                    f"{method}(); see serve.scheduler's queue protocol")
        self._queue = scheduler
        self._cv = threading.Condition()
        # drain barrier for hot weight swaps: the worker holds this for
        # the whole device-execution section of each batch, so whoever
        # else acquires it (checkpoint.hot_swap via _Replica.swap_
        # weights) is guaranteed no batch is mid-flight — queued
        # requests simply wait and ride the next batch on new weights
        self._gen_lock = threading.Lock()
        self.batches_run = 0          # introspection for tests
        # degraded mode: a broken custom scheduler demotes this batcher
        # to a fresh FIFO queue instead of failing queued requests
        self.degraded = False
        self.degraded_reason: Optional[str] = None
        self.on_degraded = None       # callback(exc), set by _Replica
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def submit(self, prompts: List[np.ndarray],
               cfg: GenerationConfig,
               queue: Optional[str] = None) -> List[np.ndarray]:
        item = {"prompts": prompts, "cfg": cfg,
                "done": threading.Event(), "result": None, "error": None,
                "queue": queue or "default"}
        with self._cv:
            self._queue.append(item)
            self._cv.notify()
        item["done"].wait()
        if item["error"] is not None:
            raise item["error"]
        return item["result"]

    @staticmethod
    def _group_key(cfg: GenerationConfig):
        return (cfg.do_sample, cfg.temperature, cfg.top_k,
                cfg.eos_token_id)

    def _run(self):
        import time
        while True:
            with self._cv:
                while len(self._queue) == 0:
                    self._cv.wait()
                # small window lets concurrent arrivals coalesce
                deadline = time.monotonic() + self.max_wait_s
            while time.monotonic() < deadline:
                time.sleep(self.max_wait_s / 4)
            with self._cv:
                if len(self._queue) == 0:
                    continue
                # selective take in POLICY order (FIFO default): the
                # head item picks the sampling-settings group,
                # compatible items join; skipped items stay in the
                # scheduler with their original priority (fairness
                # neither freezes nor re-tags — scheduler.take's
                # contract)
                state = {"key": None, "n": 0}

                def selector(item, state=state):
                    if state["key"] is None:
                        state["key"] = self._group_key(item["cfg"])
                    fits = state["n"] + len(item["prompts"]) <= \
                        self.max_batch
                    # an oversized request runs alone rather than
                    # starving (its batch is just bigger)
                    if (self._group_key(item["cfg"]) == state["key"]
                            and (fits or state["n"] == 0)):
                        state["n"] += len(item["prompts"])
                        return "take"
                    if state["n"] >= self.max_batch:
                        # batch full: nothing later can join — stop
                        # scanning the backlog
                        return "stop"
                    return "skip"

                try:
                    fault.fire("scheduler_take",
                               backlog=len(self._queue))
                    batch = self._queue.take(selector)
                except Exception as e:  # pylint: disable=broad-except
                    # a faulty custom scheduler must not take queued
                    # requests down with it: demote to a fresh FIFO,
                    # carry every drained item over, and keep serving
                    # (degraded — policy lost, liveness kept).  Failing
                    # the whole backlog here would turn one policy bug
                    # into N client-visible errors.
                    logger.exception(
                        "scheduler.take failed; degrading to FIFO")
                    from alpa_tpu.serve.scheduler import FIFOQueue
                    fresh = FIFOQueue()
                    try:
                        for item in self._queue.drain():
                            fresh.append(item)
                    except Exception:  # pylint: disable=broad-except
                        # drain is the last resort; if even that raises,
                        # whatever it yielded so far is preserved
                        logger.exception("scheduler.drain also failed")
                    self._queue = fresh
                    if not self.degraded:
                        self.degraded = True
                        self.degraded_reason = \
                            f"{type(e).__name__}: {e}"
                        if self.on_degraded is not None:
                            try:
                                self.on_degraded(e)
                            except Exception:  # pylint: disable=broad-except
                                logger.exception(
                                    "on_degraded callback failed")
                    continue
                if not batch:
                    continue
                _QUEUE_DEPTH.set(len(self._queue))
            try:
                with self._gen_lock:
                    prompts = [p for it in batch for p in it["prompts"]]
                    run_cfg = dataclasses.replace(
                        batch[0]["cfg"],
                        max_new_tokens=max(it["cfg"].max_new_tokens
                                           for it in batch))
                    _BATCH_SIZE.observe(len(prompts))
                    with _ttrace.span(
                            "batcher.generate", "serving",
                            {"prompts": len(prompts),
                             "max_new_tokens": run_cfg.max_new_tokens}
                            if _ttrace.enabled() else None,
                            "serve-batcher"):
                        outs = self.generator.generate(prompts, run_cfg,
                                                       prefix=self.prefix)
                self.batches_run += 1
                _BATCHES.inc()
                i = 0
                for it in batch:
                    k = len(it["prompts"])
                    rows = []
                    for j, p in enumerate(it["prompts"]):
                        row = outs[i + j]
                        limit = len(p) + it["cfg"].max_new_tokens
                        rows.append(row[:limit])
                    it["result"] = rows
                    it["done"].set()
                    i += k
            except Exception as e:  # pylint: disable=broad-except
                for it in batch:
                    it["error"] = e
                    it["done"].set()


class _Replica:

    def __init__(self, generator: Generator, prefix=None,
                 scheduler_factory=None, on_degraded=None,
                 warm_prefix_ids=None):
        self.generator = generator
        self.batcher = RequestBatcher(
            generator, prefix=prefix,
            scheduler=scheduler_factory() if scheduler_factory else None)
        self.batcher.on_degraded = on_degraded
        self.prefix = prefix
        self.scheduler_factory = scheduler_factory
        #: system prompt to pre-warm into the paged prefix index when
        #: the streaming engine is built (kv_paged + kv_prefix_reuse)
        self.warm_prefix_ids = warm_prefix_ids
        self._engine = None
        self._prefill_engine = None
        self._lock = threading.Lock()

    @property
    def degraded(self) -> bool:
        return self.batcher.degraded

    def swap_weights(self, new_params, prefix_ids=None,
                     drain_timeout: float = 30.0) -> None:
        """Swap this replica onto already-staged weights under a drain
        barrier (the swap phase of checkpoint.hot_swap; the staged
        params must share the current params' shapes/dtypes, so every
        compiled executable is reused — the swap is a pointer flip).

        Guarantees: the in-flight batch finishes on the OLD weights;
        queued requests are never dropped and run on the NEW weights;
        a shared-prefix model gets its prefix KV recomputed under the
        barrier so no request ever mixes old prefix with new params.
        The streaming engine is drained (bounded by ``drain_timeout``)
        and lazily rebuilt; an undrained straggler stream finishes its
        remaining tokens on the new weights rather than erroring.
        """
        from alpa_tpu.checkpoint.hot_swap import drain_engine

        # Hold the replica lock first: new streaming requests acquire it
        # in the `engine` property, so none can board the old engine
        # while we retire it.
        with self._lock:
            old_engine = self._engine
            drained = (old_engine is None or
                       drain_engine(old_engine, timeout=drain_timeout))
            # the drain barrier proper: wait out the in-flight batch
            with self.batcher._gen_lock:
                self.generator.params = new_params
                new_prefix = None
                if prefix_ids is not None:
                    new_prefix = self.generator.cache_prefix(prefix_ids)
                self.prefix = new_prefix
                self.batcher.prefix = new_prefix
            if old_engine is not None:
                if drained:
                    old_engine.shutdown()
                else:
                    logger.warning(
                        "engine streams outlived the %.0fs drain window;"
                        " leaving the old engine to finish them on the "
                        "new weights", drain_timeout)
                self._engine = None  # next stream builds a fresh engine
            if self._prefill_engine is not None:
                # prefill-pool KV (and retained handoff artifacts) are
                # only valid for the params that produced them
                self._prefill_engine.shutdown()
                self._prefill_engine = None

    @property
    def engine(self):
        """Lazy continuous-batching engine for streaming requests (so
        non-streaming deployments never spin its decode thread).  When
        the model was registered with a prefix, every streamed request's
        prompt_ids are a SUFFIX over that shared system prompt — unless
        ``kv_paged`` + ``kv_prefix_reuse`` are on, in which case the
        prefix is pre-warmed into the engine's paged block pool and
        requests send FULL prompts (any shared token prefix hits).  A
        hot weight swap rebuilds engine AND pool together: cached KV is
        only valid for the params that produced it."""
        with self._lock:
            if self._engine is None:
                from alpa_tpu.serve.engine import ContinuousBatchingEngine
                sched = (self.scheduler_factory()
                         if self.scheduler_factory else None)
                pool = None
                if global_config.kv_paged:
                    if self.prefix is not None:
                        # kv_prefix_reuse=off kept the PrefixHandle
                        # suffix semantics; those are incompatible with
                        # block tables, so this replica stays unpaged
                        logger.warning(
                            "kv_paged with a static PrefixHandle "
                            "(kv_prefix_reuse=off): replica keeps the "
                            "unpaged suffix engine")
                    else:
                        from alpa_tpu.serve.kv_cache import KVBlockPool
                        pool = KVBlockPool.for_generator(self.generator)
                self._engine = ContinuousBatchingEngine(
                    self.generator,
                    prompt_bucket=self.generator.prompt_buckets[-1],
                    prefix=None if pool is not None else self.prefix,
                    scheduler=sched, kv_pool=pool)
                if pool is not None and self.warm_prefix_ids is not None:
                    pool.warm_prefix(self.generator, self.warm_prefix_ids)
            return self._engine

    @property
    def prefill_engine(self):
        """Lazy prefill-only engine for the disaggregated prefill pool
        (serve.disagg).  Mirrors the decode engine's admission exactly
        (same prompt bucket, same prefix-hit path), so the handoff
        artifact carries bit-identical KV to what the monolithic engine
        would have computed in place.  A static-PrefixHandle replica
        cannot serve the prefill pool (block tables need kv_paged +
        kv_prefix_reuse semantics)."""
        with self._lock:
            if self._prefill_engine is None:
                if self.prefix is not None:
                    raise fault.ServiceDegradedError(
                        "replica runs a static PrefixHandle; the "
                        "disaggregated prefill pool needs paged KV "
                        "(kv_paged + kv_prefix_reuse)")
                from alpa_tpu.serve.disagg import PrefillEngine
                sched = (self.scheduler_factory()
                         if self.scheduler_factory else None)
                self._prefill_engine = PrefillEngine(
                    self.generator,
                    prompt_bucket=self.generator.prompt_buckets[-1],
                    scheduler=sched)
                if self.warm_prefix_ids is not None:
                    self._prefill_engine.pool.warm_prefix(
                        self.generator, self.warm_prefix_ids)
            return self._prefill_engine


class Controller:
    """Model registry + dispatch (ref controller.py:96)."""

    def __init__(self):
        self._models: Dict[str, List[_Replica]] = {}
        self._rr: Dict[str, int] = {}
        self._prefix_ids: Dict[str, Any] = {}
        self._lock = threading.Lock()
        # health: "ok" -> full service; "degraded" -> serving, but some
        # replica lost its admission policy (FIFO fallback); "shedding"
        # -> recovery declared the backend dead, new work is rejected
        # with ServiceDegradedError (HTTP 503) until recovery clears it
        self._health = "ok"
        self._health_reason: Optional[str] = None
        #: bound RecoveryManager (attach_recovery) — drives /healthz
        self._recovery = None
        #: completed hot swaps, newest last (introspection + /admin)
        self.reloads: List[Dict[str, Any]] = []
        #: recent request latencies (seconds) feeding load_report's p99
        #: — the router's load-aware placement signal (serve.router)
        self._latencies = deque(maxlen=512)

    # -- health / graceful degradation --------------------------------

    def set_health(self, state: str, reason: Optional[str] = None):
        if state not in ("ok", "degraded", "shedding"):
            raise ValueError(f"unknown health state {state!r}")
        with self._lock:
            self._health = state
            self._health_reason = reason
        logger.warning("controller health -> %s (%s)", state, reason)

    def health_report(self) -> Dict[str, Any]:
        with self._lock:
            state, reason = self._health, self._health_reason
            degraded = sorted(name for name, reps in self._models.items()
                              if any(r.degraded for r in reps))
        if state == "ok" and degraded:
            state = "degraded"
            reason = f"replica scheduler fallback: {degraded}"
        report = {"status": state}
        if reason:
            report["reason"] = reason
        if degraded:
            report["degraded_models"] = degraded
        return report

    def load_report(self) -> Dict[str, Any]:
        """Load signals for the multi-replica router (serve.router) and
        ``/healthz``: total queued requests (batcher + engine queues),
        tokens held by in-flight streams, and a request-latency p99 over
        the recent window (ms; ``None`` before any traffic)."""
        depth = 0
        tokens_in_flight = 0
        with self._lock:
            replicas = [r for reps in self._models.values() for r in reps]
        for rep in replicas:
            depth += len(rep.batcher._queue)
            pe = rep._prefill_engine
            if pe is not None:
                depth += pe.queue_depth()
            eng = rep._engine
            if eng is None:
                continue
            with eng._cv:
                depth += len(eng._queue)
                for it in eng._rows:
                    if it is not None:
                        tokens_in_flight += (len(it["prompt"]) +
                                             len(it["tokens"]))
        lat = sorted(self._latencies)
        p99 = lat[int(0.99 * (len(lat) - 1))] * 1e3 if lat else None
        return {"queue_depth": depth,
                "tokens_in_flight": tokens_in_flight,
                "ttft_p99_ms": p99}

    def attach_recovery(self, recovery) -> None:
        """Bind a :class:`alpa_tpu.fault.RecoveryManager`: entering
        DEGRADED sheds load here (503s), recovering restores service."""
        self._recovery = recovery
        recovery.on_degrade = (
            lambda reason=None: self.set_health(
                "shedding", reason or "mesh recovery failed"))
        recovery.on_recover = (
            lambda: self.set_health("ok", "recovered"))

    def _check_shedding(self):
        with self._lock:
            state, reason = self._health, self._health_reason
        if state == "shedding":
            raise fault.ServiceDegradedError(
                f"service unavailable: {reason or 'backend recovering'}")

    def register_model(self, name: str, generator: Generator,
                       prefix_ids=None, scheduler_factory=None):
        """``prefix_ids``: optional shared system prompt.

        Default (``kv_paged`` off, or ``kv_prefix_reuse`` off): its KV
        is precomputed once (Generator.cache_prefix; requires the
        generator's chunked-prefill mode) and every request to this
        model (batched or streamed) sends only its suffix.  All
        replicas of one model must register the SAME prefix: round-robin
        dispatch must not change what prompt_ids mean.

        With ``kv_paged`` + ``kv_prefix_reuse`` (ISSUE 11) that
        limitation is SUPERSEDED on the streaming path: the prefix is
        pre-warmed into the replica's paged prefix index instead
        (``serve.kv_cache.KVBlockPool.warm_prefix``), requests send
        FULL prompts, any shared token prefix — warmed or organic —
        hits the block cache, and different replicas may warm different
        prefixes (no consistency error).

        ``scheduler_factory``: builds this replica's admission policy
        (``serve.scheduler``, e.g.
        ``lambda: WeightedFairQueue({"paid": 4})``) — one instance for
        the batcher and one for the streaming engine; requests carry a
        ``"queue"`` field to pick their named queue on either path."""
        prefix_ids = (None if prefix_ids is None
                      else np.asarray(prefix_ids, np.int32).reshape(-1))
        if global_config.kv_paged and global_config.kv_prefix_reuse:
            # paged prefix reuse: no shared PrefixHandle, no
            # one-prefix-per-model constraint — prompt_ids are always
            # full prompts, so dispatch cannot change their meaning
            with self._lock:
                self._models.setdefault(name, []).append(
                    _Replica(generator,
                             scheduler_factory=scheduler_factory,
                             warm_prefix_ids=prefix_ids,
                             on_degraded=lambda e, n=name: logger.warning(
                                 "model %s replica degraded to FIFO: %s",
                                 n, e)))
                self._rr.setdefault(name, 0)
            logger.info(
                "registered model %s (%d replicas, paged KV%s)", name,
                len(self._models[name]),
                f", warm prefix {len(prefix_ids)} tokens"
                if prefix_ids is not None else "")
            return

        def check_consistent():
            prev = self._prefix_ids[name]
            same = ((prev is None and prefix_ids is None) or
                    (prev is not None and prefix_ids is not None and
                     np.array_equal(prev, prefix_ids)))
            if not same:
                raise ValueError(
                    f"model {name!r} replicas must share one "
                    "prefix: an inconsistent replica would make "
                    "identical requests mean different prompts")

        # Validate first (no commit), run the possibly-slow/failing
        # cache_prefix OUTSIDE the lock, then commit _prefix_ids and the
        # replica append together — a cache_prefix failure must not pin
        # the name to a prefix with zero replicas, and two concurrent
        # registrations of the same name must both be checked against
        # whatever actually got committed.
        with self._lock:
            if name in self._prefix_ids:
                check_consistent()
        prefix = None
        if prefix_ids is not None:
            prefix = generator.cache_prefix(prefix_ids)
        with self._lock:
            if name in self._prefix_ids:
                check_consistent()
            else:
                self._prefix_ids[name] = prefix_ids
            self._models.setdefault(name, []).append(
                _Replica(generator, prefix=prefix,
                         scheduler_factory=scheduler_factory,
                         on_degraded=lambda e, n=name: logger.warning(
                             "model %s replica degraded to FIFO: %s",
                             n, e)))
            self._rr.setdefault(name, 0)
        logger.info("registered model %s (%d replicas%s)", name,
                    len(self._models[name]),
                    f", prefix {prefix.length} tokens" if prefix else "")

    def list_models(self) -> List[str]:
        return sorted(self._models)

    def reload_model(self, name: str, checkpoint_source,
                     step: Optional[int] = None) -> Dict[str, Any]:
        """Zero-downtime weight reload (``POST /admin/reload``).

        Phase 1 (background, per replica): stage the checkpoint step
        onto the replica's exact device placement, hash-verifying every
        chunk — requests keep flowing on the old weights the whole time,
        and a corrupt checkpoint fails here without touching serving.
        Phase 2: swap each replica under its drain barrier
        (:meth:`_Replica.swap_weights`) — in-flight requests finish on
        the old weights, queued ones ride the new; nothing is dropped.
        """
        from alpa_tpu.checkpoint.hot_swap import (
            stage_weights_from_checkpoint)
        with self._lock:
            if name not in self._models:
                raise KeyError(f"unknown model {name!r}; "
                               f"registered: {sorted(self._models)}")
            replicas = list(self._models[name])
            prefix_ids = self._prefix_ids.get(name)
        loaded_step = None
        for replica in replicas:
            new_params, loaded_step = stage_weights_from_checkpoint(
                checkpoint_source, replica.generator.params, step=step)
            replica.swap_weights(new_params, prefix_ids=prefix_ids)
        result = {"model": name, "step": loaded_step,
                  "replicas_swapped": len(replicas)}
        with self._lock:
            self.reloads.append(result)
        logger.info("hot-swapped model %s to checkpoint step %s "
                    "(%d replicas)", name, loaded_step, len(replicas))
        return result

    def _pick_replica(self, name: str) -> _Replica:
        with self._lock:
            replicas = self._models[name]
            i = self._rr[name] % len(replicas)
            self._rr[name] += 1
        return replicas[i]

    def _parse_request(self, request: Dict[str, Any]):
        """Shared request validation: (replica, prompt_ids, cfg) — one
        parser so streaming and non-streaming cannot diverge.  Checks
        load shedding FIRST: in shedding mode every new request is
        rejected up front (503) — cheap refusal beats queueing work the
        backend cannot run."""
        self._check_shedding()
        name = request["model"]
        if name not in self._models:
            raise KeyError(f"unknown model {name!r}; "
                           f"registered: {self.list_models()}")
        prompt_ids = np.asarray(request["prompt_ids"], np.int32)
        queue = request.get("queue")
        if queue is not None and (not isinstance(queue, str) or
                                  len(queue) > 64):
            # untrusted input headed for scheduler dict keys: reject
            # non-strings (unhashable lists would 500) and cap length
            raise ValueError("queue must be a string of <= 64 chars")
        cfg = GenerationConfig(
            max_new_tokens=int(request.get("max_new_tokens", 32)),
            temperature=float(request.get("temperature", 1.0)),
            top_k=int(request.get("top_k", 0)),
            do_sample=bool(request.get("do_sample", False)),
            eos_token_id=request.get("eos_token_id"))
        return self._pick_replica(name), prompt_ids, cfg, queue

    def completions(self, request: Dict[str, Any]) -> Dict[str, Any]:
        tic = time.monotonic()
        try:
            with _ttrace.span("serve.request", "serving",
                              {"model": str(request.get("model"))}
                              if _ttrace.enabled() else None,
                              "serve-driver"):
                replica, prompt_ids, cfg, queue = \
                    self._parse_request(request)
                if prompt_ids.ndim == 1:
                    prompt_ids = prompt_ids[None]
                outs = replica.batcher.submit(list(prompt_ids), cfg,
                                              queue=queue)
        except fault.ServiceDegradedError:
            _REQUESTS.labels("shed").inc()
            raise
        except Exception:
            _REQUESTS.labels("error").inc()
            raise
        _REQUESTS.labels("ok").inc()
        elapsed = time.monotonic() - tic
        _REQ_LATENCY.observe(elapsed)
        self._latencies.append(elapsed)
        return {"output_ids": [o.tolist() for o in outs]}

    def completions_stream(self, request: Dict[str, Any]):
        """Token iterator for a single-prompt streaming request (rides
        the replica's continuous-batching engine, so concurrent streams
        share decode ticks).  Yields ints; the full row is
        prompt + yielded tokens."""
        replica, prompt_ids, cfg, queue = self._parse_request(request)
        if prompt_ids.ndim > 1 and prompt_ids.shape[0] != 1:
            raise ValueError(
                "streaming accepts exactly one prompt per request; got "
                f"{prompt_ids.shape[0]} rows")
        return replica.engine.submit_stream(prompt_ids.reshape(-1), cfg,
                                            queue=queue)

    # -- disaggregated prefill/decode (serve.disagg) -------------------

    def disagg_prefill(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Prefill-phase half of a disaggregated request: admit + run
        the prompt's prefill on this replica's prefill pool and return
        the (retained) handoff artifact's wire form."""
        tic = time.monotonic()
        replica, prompt_ids, cfg, queue = self._parse_request(request)
        if prompt_ids.ndim > 1 and prompt_ids.shape[0] != 1:
            raise ValueError(
                "disaggregated prefill takes exactly one prompt per "
                f"request; got {prompt_ids.shape[0]} rows")
        pe = replica.prefill_engine
        pe.model = request["model"]
        art = pe.prefill(prompt_ids.reshape(-1), cfg, queue=queue)
        self._latencies.append(time.monotonic() - tic)
        return art.to_wire()

    def disagg_ingest(self, wire: Dict[str, Any]):
        """Decode-phase half: verify the artifact (any hash mismatch
        raises ArtifactCorruptError — corrupt KV is never decoded),
        land it on a replica's decode engine, and return the token
        stream that joins the continuous decode batch mid-tick."""
        from alpa_tpu.serve import disagg
        self._check_shedding()
        art = disagg.KVHandoffArtifact.from_wire(wire)  # verifies
        name = art.model
        if name not in self._models:
            raise KeyError(f"unknown model {name!r}; "
                           f"registered: {self.list_models()}")
        replica = self._pick_replica(name)
        return disagg.ingest_stream(replica.engine, art)

    def disagg_fetch(self, request_id: str) -> Dict[str, Any]:
        """The retained artifact for ``request_id`` — the router's
        re-ingest source after a decode-side failure."""
        with self._lock:
            replicas = [r for reps in self._models.values()
                        for r in reps]
        for rep in replicas:
            pe = rep._prefill_engine
            if pe is not None:
                art = pe.fetch(request_id)
                if art is not None:
                    return art.to_wire()
        raise KeyError(f"no retained artifact {request_id!r}")

    def disagg_ack(self, request_id: str) -> bool:
        """Drop the retained artifact: its decode stream finished."""
        with self._lock:
            replicas = [r for reps in self._models.values()
                        for r in reps]
        return any(rep._prefill_engine is not None and
                   rep._prefill_engine.ack(request_id)
                   for rep in replicas)


class _Handler(BaseHTTPRequestHandler):
    controller: Controller = None  # set by run_controller

    def log_message(self, fmt, *args):  # quiet
        logger.debug(fmt, *args)

    def _send(self, code: int, payload: Dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str,
                   content_type: str = "text/plain; version=0.0.4"):
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _metrics(self):
        """Prometheus text exposition of the whole process registry.
        Importing monitoring first guarantees every module-level family
        (watchdog gauges, compile-cache collector, ...) is registered
        even when the controller is the only thing this process ran."""
        import alpa_tpu.monitoring  # noqa: F401  pylint: disable=unused-import
        # the serving-fleet families (alpa_kv_*, alpa_router_*) register
        # at module import; pull them in so a controller that never built
        # a pool or router still exposes the series
        import alpa_tpu.serve.kv_cache  # noqa: F401  pylint: disable=unused-import
        import alpa_tpu.serve.router  # noqa: F401  pylint: disable=unused-import
        self._send_text(200, _tmetrics.get_registry().to_prometheus_text())

    def _healthz(self):
        """Liveness wired to the recovery state machine: 200 while
        HEALTHY/SUSPECT/RECOVERING (body carries the state), 503 once
        DEGRADED.  Falls back to the controller health report when no
        RecoveryManager is attached.  The body also carries
        ``last_flight_dump`` — the path of the most recent flight
        recorder post-mortem (ISSUE 6), so an operator seeing a SUSPECT
        or DEGRADED state knows where the instruction timeline landed
        (null when nothing has been dumped), and ``elastic`` — the
        ElasticSupervisor's episode report when this process runs one
        (docs/fault_tolerance.md#elastic-training; null otherwise)."""
        from alpa_tpu import elastic as _elastic
        from alpa_tpu.telemetry import flight as _flight
        recovery = self.controller._recovery
        if recovery is not None:
            state = recovery.state.value
            code = 503 if state == "degraded" else 200
            self._send(code, {"status": state,
                              "last_flight_dump": _flight.last_dump_path(),
                              "elastic": _elastic.status_report(),
                              "load": self.controller.load_report()})
            return
        report = self.controller.health_report()
        report["last_flight_dump"] = _flight.last_dump_path()
        report["elastic"] = _elastic.status_report()
        report["load"] = self.controller.load_report()
        code = 503 if report["status"] == "shedding" else 200
        self._send(code, report)

    def do_GET(self):
        if self.path == "/health":
            report = self.controller.health_report()
            code = 503 if report["status"] == "shedding" else 200
            self._send(code, report)
        elif self.path == "/healthz":
            self._healthz()
        elif self.path == "/metrics":
            self._metrics()
        elif self.path == "/models":
            self._send(200, {"models": self.controller.list_models()})
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        if self.path == "/admin/reload":
            self._admin_reload()
            return
        if self.path.startswith("/disagg/"):
            self._disagg()
            return
        if self.path != "/completions":
            self._send(404, {"error": f"unknown path {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            request = json.loads(self.rfile.read(length) or b"{}")
            if request.get("stream"):
                self._stream(request)
                return
            result = self.controller.completions(request)
            self._send(200, result)
        except fault.ServiceDegradedError as e:
            self._send(503, {"error": str(e)})
        except KeyError as e:
            self._send(404, {"error": str(e)})
        except (json.JSONDecodeError, ValueError, AssertionError,
                TypeError) as e:
            self._send(400, {"error": f"bad request: {e}"})
        except Exception as e:  # pylint: disable=broad-except
            logger.exception("completions failed")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def _admin_reload(self):
        """``POST /admin/reload`` {"model", "ckpt_dir", "step"?}: stage
        + hash-verify the checkpoint in the background, then swap every
        replica of the model under a drain barrier.  Requests in flight
        during the call are served without interruption."""
        try:
            length = int(self.headers.get("Content-Length", 0))
            request = json.loads(self.rfile.read(length) or b"{}")
            name = request.get("model")
            ckpt_dir = request.get("ckpt_dir")
            if not name or not ckpt_dir:
                raise ValueError(
                    "reload needs 'model' and 'ckpt_dir' fields")
            step = request.get("step")
            result = self.controller.reload_model(
                name, ckpt_dir, step=None if step is None else int(step))
            self._send(200, result)
        except KeyError as e:
            self._send(404, {"error": str(e)})
        except (json.JSONDecodeError, ValueError, TypeError,
                FileNotFoundError) as e:
            self._send(400, {"error": f"bad reload request: {e}"})
        except Exception as e:  # pylint: disable=broad-except
            logger.exception("hot reload failed")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def _stream(self, request):
        """Server-sent events: one ``data: {"token": t}`` per generated
        token, then ``data: {"done": true}``.  Close-delimited (no
        Content-Length; Connection: close) so stdlib clients can read
        incrementally.

        Validation happens BEFORE headers go out (bad requests still get
        a JSON error status via do_POST); once streaming has started, any
        failure is reported as a final ``data: {"error": ...}`` event —
        never a second status line into the open SSE body.
        """
        it = self.controller.completions_stream(request)  # validates
        self._stream_body(it)

    def _disagg(self):
        """Disaggregation endpoints (serve.disagg / serve.router):
        ``/disagg/prefill`` -> handoff artifact wire JSON;
        ``/disagg/ingest`` -> SSE token stream joining the decode
        batch; ``/disagg/fetch`` + ``/disagg/ack`` manage the prefill
        side's retained artifacts.  A corrupt artifact maps to 422 so
        the router re-fetches the retained copy instead of failing the
        request."""
        from alpa_tpu.serve.disagg import ArtifactCorruptError
        try:
            length = int(self.headers.get("Content-Length", 0))
            request = json.loads(self.rfile.read(length) or b"{}")
            if self.path == "/disagg/prefill":
                self._send(200,
                           self.controller.disagg_prefill(request))
            elif self.path == "/disagg/ingest":
                it = self.controller.disagg_ingest(request)  # validates
                self._stream_body(it)
            elif self.path == "/disagg/fetch":
                self._send(200, self.controller.disagg_fetch(
                    str(request.get("request_id"))))
            elif self.path == "/disagg/ack":
                self._send(200, {"acked": self.controller.disagg_ack(
                    str(request.get("request_id")))})
            else:
                self._send(404, {"error": f"unknown path {self.path}"})
        except fault.ServiceDegradedError as e:
            self._send(503, {"error": str(e)})
        except ArtifactCorruptError as e:
            self._send(422, {"error": str(e)})
        except KeyError as e:
            self._send(404, {"error": str(e)})
        except (json.JSONDecodeError, ValueError, AssertionError,
                TypeError) as e:
            self._send(400, {"error": f"bad request: {e}"})
        except Exception as e:  # pylint: disable=broad-except
            logger.exception("disagg request failed")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def _stream_body(self, it):
        """Write an already-validated token iterator as SSE."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            try:
                for t in it:
                    self.wfile.write(
                        f"data: {json.dumps({'token': t})}\n\n".encode())
                    self.wfile.flush()
                final = {"done": True}
            except (BrokenPipeError, ConnectionResetError):
                logger.info("stream client disconnected")
                it.close()  # flags the engine row cancelled
                return
            except Exception as e:  # pylint: disable=broad-except
                logger.exception("stream failed mid-generation")
                final = {"error": f"{type(e).__name__}: {e}"}
            self.wfile.write(f"data: {json.dumps(final)}\n\n".encode())
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            logger.info("stream client disconnected at finish")
            it.close()
        finally:
            self.close_connection = True


class ControllerServer:
    """The running HTTP server (ref run_controller:280)."""

    def __init__(self, controller: Controller, host: str, port: int):
        handler = type("BoundHandler", (_Handler,),
                       {"controller": controller})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.controller = controller
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self):
        self.thread.start()

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def run_controller(host: str = "127.0.0.1",
                   port: int = 8000,
                   start: bool = True) -> ControllerServer:
    """Create (and start) a controller server (ref run_controller:280)."""
    server = ControllerServer(Controller(), host, port)
    if start:
        server.start()
    return server
