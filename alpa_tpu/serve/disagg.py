"""Disaggregated prefill/decode serving (ISSUE 18 tentpole).

DistServe-style phase splitting over the PR 11 serving stack: long
prefills convoy a monolithic :class:`~alpa_tpu.serve.engine.
ContinuousBatchingEngine` — every chunked prefill runs between decode
ticks, so one 2k-token prompt inflates inter-token latency for every
decoding request behind it.  This module splits the two phases onto
separate replica pools:

* A **prefill replica** runs admission + prefill ONLY
  (:class:`PrefillEngine`): it reserves a block table in its own
  :class:`~alpa_tpu.serve.kv_cache.KVBlockPool` (cross-request prefix
  reuse applies — a cached prefix skips recomputation exactly like the
  monolithic engine's hit path), prefills the prompt, and packages the
  request's block-table slice as a :class:`KVHandoffArtifact`:
  per-block K/V payload, content-hashed per block (sha256 over the wire
  bytes, so corruption anywhere between the pools is detected before a
  single token is decoded), plus the last-token logits that seed decode.
* The artifact crosses replicas over the cross-mesh transfer layer:
  payload arrays land on the decode replica's cache sharding through
  :func:`~alpa_tpu.pipeline_parallel.cross_mesh_resharding.
  make_ingest_transfer` (the arrival half of a DirectTransfer whose
  source lives in another process), and the PR 7 activation codec can
  quantize the payload blockwise (``disagg_codec=int8|fp8`` — lossy
  within ``reshard_codec.ERROR_BOUND``, OFF by default so the handoff
  ships verbatim bits).
* A **decode replica** ingests (:func:`ingest_stream`): hashes are
  verified, the dense row state is reconstructed and the request joins
  the continuous decode batch mid-tick via
  ``ContinuousBatchingEngine.submit_prefilled_stream`` — the engine
  scatters the blocks into ITS pool and registers the prefix chain, so
  cross-request reuse keeps working on the decode side too.

Bit-exactness: the prefill replica computes the SAME prefill function
(same code path: bucketed ``_prefill`` on a miss, gather + chunked
suffix prefill on a prefix hit) the monolithic engine would run, the
verbatim payload moves bits unchanged, and the decode engine's
admission/tick path is shared — so the disaggregated decode stream is
``np.array_equal`` with the monolithic engine on miss, full-hit, and
shared-prefix paths (pinned in tests/serve/test_disagg.py).

Failure handling (no handoff is ever dropped): every produced artifact
is RETAINED by the prefill engine until the router acks the finished
stream.  A decode replica dying mid-handoff (or mid-stream, greedy
decode) makes the router re-fetch the retained artifact and re-ingest
on a survivor; a corrupt artifact (any flipped block hash) is rejected
with :class:`ArtifactCorruptError` and re-fetched — never silently
decoded.  Phase-aware routing, SLOs, and backpressure live in
``serve.router``; knobs in ``global_env`` (``disagg_*``);
docs/serving.md#disaggregated-prefilldecode.
"""
import base64
import dataclasses
import logging
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from alpa_tpu.global_env import global_config
from alpa_tpu.telemetry import metrics as _tmetrics

logger = logging.getLogger(__name__)

_REG = _tmetrics.get_registry()
_HANDOFF_BYTES = _REG.counter(
    "alpa_disagg_handoff_bytes_total",
    "KV handoff payload bytes shipped prefill -> decode")
_HANDOFF_SECONDS = _REG.histogram(
    "alpa_disagg_handoff_seconds",
    "Handoff latency: artifact produced -> decode replica admitted it")
_HANDOFFS_IN_FLIGHT = _REG.gauge(
    "alpa_disagg_handoffs_in_flight",
    "Handoff artifacts produced and not yet acked by the router")
_TTFT_H = _REG.histogram(
    "alpa_disagg_ttft_seconds",
    "Time to first token through the disaggregated path, by pool",
    labelnames=("pool",))
_ITL_H = _REG.histogram(
    "alpa_disagg_itl_seconds",
    "Inter-token gap through the disaggregated path, by pool",
    labelnames=("pool",))
_REINGESTS = _REG.counter(
    "alpa_disagg_reingests_total",
    "Handoffs re-ingested from the retained artifact, by reason",
    labelnames=("reason",))
_BACKPRESSURE_SHEDS = _REG.counter(
    "alpa_disagg_backpressure_sheds_total",
    "Prefill admissions shed by decode-pool backpressure")
_PREFILLS = _REG.counter(
    "alpa_disagg_prefills_total",
    "Prefill-phase requests completed into handoff artifacts")


class ArtifactCorruptError(RuntimeError):
    """A handoff artifact failed per-block content verification.  The
    router re-fetches the retained pristine copy from the prefill side
    instead of ever decoding corrupt KV."""


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registered by jax; covers fp8/bfloat16 names
        return np.dtype(getattr(ml_dtypes, name))


def _arr_to_wire(a: np.ndarray) -> Dict[str, Any]:
    a = np.ascontiguousarray(a)
    return {"shape": list(a.shape), "dtype": str(a.dtype),
            "data": base64.b64encode(a.tobytes()).decode("ascii")}


def _arr_from_wire(d: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(d["data"])
    return np.frombuffer(raw, dtype=_np_dtype(d["dtype"])).reshape(
        tuple(d["shape"])).copy()


def _codec_ok(mode: str, dtype: np.dtype) -> bool:
    """Whether the reshard codec can carry this KV dtype under ``mode``
    (mirrors ``reshard_codec.eligible`` minus the size floor — handoff
    payloads opt in explicitly)."""
    if mode == "off":
        return True
    from alpa_tpu.pipeline_parallel import reshard_codec
    if mode not in reshard_codec.ERROR_BOUND:
        return False
    if str(dtype) not in reshard_codec._ELIGIBLE_DTYPES:
        return False
    if mode == "fp8" and not reshard_codec.have_fp8():
        return False
    return True


def _encode_blocks(blocks: np.ndarray, mode: str
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Quantize one layer's ``(num_blocks, block_size, ...)`` K or V
    payload per KV block through the reshard codec (per-block so the
    per-block content hashes stay meaningful over the wire payload)."""
    import jax.numpy as jnp

    from alpa_tpu.pipeline_parallel import reshard_codec
    qs, ss = [], []
    for i in range(blocks.shape[0]):
        q, s = reshard_codec.encode(jnp.asarray(blocks[i]), mode)
        qs.append(np.asarray(q))
        ss.append(np.asarray(s))
    return np.stack(qs), np.stack(ss)


def _decode_blocks(q: np.ndarray, s: np.ndarray, block_shape, dtype,
                   mode: str) -> np.ndarray:
    import jax.numpy as jnp

    from alpa_tpu.pipeline_parallel import reshard_codec
    outs = [np.asarray(reshard_codec.decode(
        jnp.asarray(q[i]), jnp.asarray(s[i]), block_shape, dtype, mode))
        for i in range(q.shape[0])]
    return np.stack(outs)


@dataclasses.dataclass
class KVHandoffArtifact:
    """One request's prefilled KV state, packaged for the wire.

    ``layers[l]`` is ``{"k": arr, "v": arr}`` (codec off, arrays shaped
    ``(num_blocks, block_size, ...)`` in the model's KV dtype) or
    ``{"k_q", "k_s", "v_q", "v_s"}`` (codec on: per-block quantized
    payload + scales).  ``block_hashes[i]`` is sha256 over block ``i``'s
    wire bytes across every layer; ``logits_hash`` covers the seed
    logits + prompt.  Hashes are computed over what actually crosses
    the wire, so verification catches transport corruption exactly and
    a re-fetched artifact re-ingests bitwise identically (quantized or
    not)."""

    request_id: str
    model: str
    prompt: np.ndarray
    cfg: Dict[str, Any]
    queue: Optional[str]
    weights_tag: str
    block_size: int
    num_blocks: int
    codec: str
    kv_dtype: str
    layers: List[Dict[str, np.ndarray]]
    last_logits: np.ndarray
    block_hashes: List[str]
    logits_hash: str

    # ---- construction -----------------------------------------------

    @classmethod
    def build(cls, request_id: str, model: str, prompt: np.ndarray,
              cfg: Dict[str, Any], queue: Optional[str],
              weights_tag: str, block_size: int,
              layer_blocks: List[Tuple[np.ndarray, np.ndarray]],
              last_logits: np.ndarray,
              codec: str = "off") -> "KVHandoffArtifact":
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        kv_dtype = str(layer_blocks[0][0].dtype)
        num_blocks = int(layer_blocks[0][0].shape[0])
        layers: List[Dict[str, np.ndarray]] = []
        for (kb, vb) in layer_blocks:
            if codec == "off":
                layers.append({"k": np.ascontiguousarray(kb),
                               "v": np.ascontiguousarray(vb)})
            else:
                kq, ks = _encode_blocks(kb, codec)
                vq, vs = _encode_blocks(vb, codec)
                layers.append({"k_q": kq, "k_s": ks,
                               "v_q": vq, "v_s": vs})
        art = cls(request_id=request_id, model=model, prompt=prompt,
                  cfg=dict(cfg), queue=queue, weights_tag=weights_tag,
                  block_size=int(block_size), num_blocks=num_blocks,
                  codec=codec, kv_dtype=kv_dtype, layers=layers,
                  last_logits=np.ascontiguousarray(
                      np.asarray(last_logits)),
                  block_hashes=[], logits_hash="")
        art.block_hashes = art._hash_blocks()
        art.logits_hash = art._hash_logits()
        return art

    # ---- hashing ----------------------------------------------------

    def _block_bytes(self, i: int):
        import hashlib
        h = hashlib.sha256()
        for lay in self.layers:
            for key in sorted(lay):
                h.update(np.ascontiguousarray(lay[key][i]).tobytes())
        return h.hexdigest()

    def _hash_blocks(self) -> List[str]:
        return [self._block_bytes(i) for i in range(self.num_blocks)]

    def _hash_logits(self) -> str:
        import hashlib
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(self.prompt).tobytes())
        h.update(np.ascontiguousarray(self.last_logits).tobytes())
        return h.hexdigest()

    def verify(self) -> None:
        """Recompute every per-block content hash against the carried
        ones; any mismatch rejects the whole artifact (the decode side
        must never scatter corrupt KV into its pool)."""
        if len(self.block_hashes) != self.num_blocks:
            raise ArtifactCorruptError(
                f"artifact {self.request_id}: {len(self.block_hashes)} "
                f"hashes for {self.num_blocks} blocks")
        for i in range(self.num_blocks):
            if self._block_bytes(i) != self.block_hashes[i]:
                raise ArtifactCorruptError(
                    f"artifact {self.request_id}: block {i} content "
                    f"hash mismatch (corrupt handoff)")
        if self._hash_logits() != self.logits_hash:
            raise ArtifactCorruptError(
                f"artifact {self.request_id}: seed logits/prompt hash "
                f"mismatch (corrupt handoff)")

    # ---- payload accounting -----------------------------------------

    @property
    def payload_nbytes(self) -> int:
        return sum(int(a.nbytes) for lay in self.layers
                   for a in lay.values())

    # ---- wire form --------------------------------------------------

    def to_wire(self) -> Dict[str, Any]:
        return {
            "request_id": self.request_id, "model": self.model,
            "prompt": self.prompt.tolist(), "cfg": dict(self.cfg),
            "queue": self.queue, "weights_tag": self.weights_tag,
            "block_size": self.block_size,
            "num_blocks": self.num_blocks, "codec": self.codec,
            "kv_dtype": self.kv_dtype,
            "layers": [{k: _arr_to_wire(v) for k, v in lay.items()}
                       for lay in self.layers],
            "last_logits": _arr_to_wire(self.last_logits),
            "block_hashes": list(self.block_hashes),
            "logits_hash": self.logits_hash,
            "payload_nbytes": self.payload_nbytes,
        }

    @classmethod
    def from_wire(cls, wire: Dict[str, Any],
                  verify: bool = True) -> "KVHandoffArtifact":
        try:
            art = cls(
                request_id=str(wire["request_id"]),
                model=str(wire["model"]),
                prompt=np.asarray(wire["prompt"], np.int32).reshape(-1),
                cfg=dict(wire["cfg"]), queue=wire.get("queue"),
                weights_tag=str(wire.get("weights_tag", "")),
                block_size=int(wire["block_size"]),
                num_blocks=int(wire["num_blocks"]),
                codec=str(wire["codec"]),
                kv_dtype=str(wire["kv_dtype"]),
                layers=[{k: _arr_from_wire(v) for k, v in lay.items()}
                        for lay in wire["layers"]],
                last_logits=_arr_from_wire(wire["last_logits"]),
                block_hashes=[str(h) for h in wire["block_hashes"]],
                logits_hash=str(wire.get("logits_hash", "")))
        except (KeyError, TypeError, ValueError) as e:
            raise ArtifactCorruptError(
                f"malformed handoff artifact: {e}") from e
        if verify:
            art.verify()
        return art

    # ---- decode-side reconstruction ---------------------------------

    def dense_rows(self, layer: int, tail: Tuple[int, ...]
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """Like :meth:`materialize` but given the destination cache's
        per-token tail shape (needed to invert the codec's flattening)."""
        lay = self.layers[layer]
        dtype = _np_dtype(self.kv_dtype)
        block_shape = (self.block_size,) + tuple(tail)
        if self.codec == "off":
            kb, vb = lay["k"], lay["v"]
        else:
            kb = _decode_blocks(lay["k_q"], lay["k_s"], block_shape,
                                dtype, self.codec)
            vb = _decode_blocks(lay["v_q"], lay["v_s"], block_shape,
                                dtype, self.codec)
        n = self.num_blocks * self.block_size
        return (np.ascontiguousarray(kb).reshape((n,) + tuple(tail))
                .astype(dtype, copy=False),
                np.ascontiguousarray(vb).reshape((n,) + tuple(tail))
                .astype(dtype, copy=False))


class PrefillEngine:
    """Admission + prefill ONLY: the prefill-pool half of a
    disaggregated deployment.  One worker thread drains a scheduler
    queue (the same ``serve.scheduler`` protocol the batcher and the
    decode engine speak, so per-tenant weighted fairness holds on this
    pool too), runs each prompt's prefill against this replica's
    :class:`KVBlockPool` (prefix reuse included), and packages the
    block-table slice into a :class:`KVHandoffArtifact`.

    Every artifact is retained (LRU, ``disagg_retain_artifacts`` deep)
    until :meth:`ack` — the router's re-ingest path
    (:meth:`fetch`) rides this, so a decode-replica death or a corrupt
    wire copy never loses a handoff."""

    def __init__(self, generator, kv_pool=None, scheduler=None,
                 prompt_bucket: Optional[int] = None, model: str = "",
                 weights_tag: str = "", codec: Optional[str] = None,
                 max_retained: Optional[int] = None):
        from alpa_tpu.serve.kv_cache import KVBlockPool
        self.gen = generator
        self.model = model
        self.weights_tag = weights_tag
        self.bucket = prompt_bucket or generator.prompt_buckets[-1]
        self.pool = kv_pool or KVBlockPool.for_generator(generator)
        if self.pool.seq_len != generator.config.seq_len:
            raise ValueError(
                f"kv_pool seq_len {self.pool.seq_len} != generator "
                f"seq_len {generator.config.seq_len}")
        self._reuse = (self.pool.prefix_reuse and
                       bool(generator.prefill_chunk))
        codec = (global_config.disagg_codec if codec is None else codec)
        if codec != "off" and not _codec_ok(
                codec, self.pool._kp[0].dtype):
            logger.warning(
                "disagg_codec=%s unsupported for KV dtype %s; handoff "
                "ships verbatim", codec, self.pool._kp[0].dtype)
            codec = "off"
        self.codec = codec
        if scheduler is None:
            from alpa_tpu.serve.scheduler import FIFOQueue
            scheduler = FIFOQueue()
        self._queue = scheduler
        self._cv = threading.Condition()
        self._retained: "OrderedDict[str, KVHandoffArtifact]" = \
            OrderedDict()
        self._retain_cap = (global_config.disagg_retain_artifacts
                            if max_retained is None else max_retained)
        self.prefills = 0
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # ---- public API -------------------------------------------------

    def prefill(self, prompt: np.ndarray, cfg=None,
                queue: Optional[str] = None,
                request_id: Optional[str] = None) -> KVHandoffArtifact:
        """Blocking: admit ``prompt``, prefill it, return (and retain)
        the handoff artifact."""
        from alpa_tpu.serve.generation import GenerationConfig
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        cfg = cfg or GenerationConfig()
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) > self.bucket:
            raise ValueError(
                f"prompt {len(prompt)} exceeds prefill bucket "
                f"{self.bucket}")
        seq_len = self.gen.config.seq_len
        if len(prompt) + cfg.max_new_tokens > seq_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens "
                f"{cfg.max_new_tokens} exceeds seq_len {seq_len}")
        if not self.pool.fits(len(prompt)):
            raise ValueError(
                f"prompt {len(prompt)} needs more KV blocks than the "
                f"prefill pool holds")
        item = {"prompt": prompt, "cfg": cfg,
                "queue": queue or "default",
                "request_id": request_id or uuid.uuid4().hex,
                "done": threading.Event(), "artifact": None,
                "error": None}
        with self._cv:
            if self._stop:
                raise RuntimeError("prefill engine shut down")
            self._queue.append(item)
            self._cv.notify()
        item["done"].wait()
        if item["error"] is not None:
            raise item["error"]
        return item["artifact"]

    def fetch(self, request_id: str) -> Optional[KVHandoffArtifact]:
        """The retained artifact for ``request_id`` (None when already
        acked or evicted) — the router's re-ingest source."""
        with self._cv:
            return self._retained.get(request_id)

    def ack(self, request_id: str) -> bool:
        """Drop the retained artifact: its stream finished cleanly."""
        with self._cv:
            art = self._retained.pop(request_id, None)
        if art is not None:
            _HANDOFFS_IN_FLIGHT.dec()
        return art is not None

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def shutdown(self):
        with self._cv:
            self._stop = True
            self._cv.notify()

    # ---- worker -----------------------------------------------------

    def _run(self):
        while True:
            with self._cv:
                while not self._stop and len(self._queue) == 0:
                    self._cv.wait()
                if self._stop:
                    err = RuntimeError("prefill engine shut down")
                    for item in self._queue.drain():
                        item["error"] = err
                        item["done"].set()
                    return
                item = self._queue.popleft()
            try:
                item["artifact"] = self._prefill_one(item)
            except Exception as e:  # pylint: disable=broad-except
                logger.exception("prefill failed")
                item["error"] = e
            item["done"].set()

    def _prefill_one(self, item) -> KVHandoffArtifact:
        import dataclasses as _dc

        import jax.numpy as jnp

        from alpa_tpu.model.gpt_model import init_kv_caches
        p = item["prompt"]
        # max_new_tokens=0: this pool never decodes — it only needs the
        # prompt's blocks, and releases them (into the prefix index)
        # right after the artifact is gathered
        seq = self.pool.begin_sequence(p, 0)
        if seq is None:
            raise RuntimeError(
                "prefill pool cannot free enough blocks (all held by "
                "the prefix index under concurrent prefills)")
        clean = False
        try:
            m = seq.matched_tokens
            total = jnp.asarray([len(p)], jnp.int32)
            if m:
                # prefix hit: identical to the monolithic engine's hit
                # path (gather + chunked suffix prefill from the match
                # offset) — bit-exactness rides the same ops
                gathered = self.pool.gather_dense(seq)
                logits1, caches1 = self.gen._run_chunked_prefill(
                    [p[m:]], total, 1, caches=gathered, start=m)
            else:
                ids = np.zeros((1, self.bucket), np.int32)
                ids[0, :len(p)] = p
                caches1 = init_kv_caches(self.gen.config, 1)
                logits1, caches1 = self.gen._prefill(
                    self.gen.params, jnp.asarray(ids), caches1, total)
            self.pool.scatter_prompt(seq, caches1)
            if self._reuse:
                self.pool.register_prompt(seq, p)
            nb = -(-len(p) // self.pool.block_size)
            layer_blocks = self.pool.gather_blocks(seq, nb)
            art = KVHandoffArtifact.build(
                request_id=item["request_id"], model=self.model,
                prompt=p, cfg=_dc.asdict(item["cfg"]),
                queue=item["queue"], weights_tag=self.weights_tag,
                block_size=self.pool.block_size,
                layer_blocks=layer_blocks,
                last_logits=np.asarray(logits1), codec=self.codec)
            clean = True
        finally:
            self.pool.release(seq, tokens=p if clean else None,
                              register=clean)
        self.prefills += 1
        _PREFILLS.inc()
        _HANDOFF_BYTES.inc(art.payload_nbytes)
        with self._cv:
            self._retained[art.request_id] = art
            _HANDOFFS_IN_FLIGHT.inc()
            while len(self._retained) > max(1, self._retain_cap):
                evicted, _ = self._retained.popitem(last=False)
                _HANDOFFS_IN_FLIGHT.dec()
                logger.warning(
                    "retained-artifact cap reached; dropped %s (raise "
                    "disagg_retain_artifacts if re-ingest matters "
                    "more than memory)", evicted)
        return art


# ---- decode-side ingest ---------------------------------------------


def land_artifact(engine, artifact: KVHandoffArtifact):
    """Verify + reconstruct: the artifact's payload becomes the dense
    single-row caches + seed logits the decode engine's prefilled
    admission expects, landed on the engine's resident-cache sharding
    through the cross-mesh transfer layer."""
    import jax
    import jax.numpy as jnp

    from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
        make_ingest_transfer)
    artifact.verify()
    cfgm = engine.gen.config
    L = cfgm.seq_len
    if len(artifact.layers) != len(engine._caches):
        raise ValueError(
            f"artifact has {len(artifact.layers)} layers; decode "
            f"engine has {len(engine._caches)}")
    if artifact.num_blocks * artifact.block_size > L:
        raise ValueError(
            f"artifact carries {artifact.num_blocks * artifact.block_size} "
            f"token positions; decode seq_len is {L}")
    span = artifact.num_blocks * artifact.block_size
    idx = jnp.asarray([len(artifact.prompt)], jnp.int32)
    dense = []
    for l, (k_res, v_res, _i) in enumerate(engine._caches):
        tail = tuple(k_res.shape[2:])
        kb, vb = artifact.dense_rows(l, tail)
        if kb.shape[1:] != tail or str(kb.dtype) != str(k_res.dtype):
            raise ValueError(
                f"layer {l}: artifact KV {kb.shape[1:]}/{kb.dtype} "
                f"does not match decode caches {tail}/{k_res.dtype}")
        dk = np.zeros((1, L) + tail, kb.dtype)
        dv = np.zeros((1, L) + tail, vb.dtype)
        dk[0, :span] = kb
        dv[0, :span] = vb
        tr = make_ingest_transfer(
            jax.ShapeDtypeStruct(dk.shape, dk.dtype), k_res.sharding)
        dense.append((tr(dk), tr(dv), idx))
    logits1 = jnp.asarray(artifact.last_logits)
    return dense, logits1


def _ingest_cfg(artifact: KVHandoffArtifact):
    from alpa_tpu.serve.generation import GenerationConfig
    known = {f.name for f in dataclasses.fields(GenerationConfig)}
    return GenerationConfig(**{k: v for k, v in artifact.cfg.items()
                               if k in known})


def ingest_stream(engine, artifact: KVHandoffArtifact,
                  queue: Optional[str] = None):
    """Decode-side half of the handoff: verify, land, and join the
    request into ``engine``'s continuous decode batch mid-tick.
    Returns the engine token stream.  The engine scatters the prompt
    blocks into its OWN pool and registers the prefix chain, so
    cross-request reuse keeps working on the decode pool."""
    caches1, logits1 = land_artifact(engine, artifact)
    cfg = _ingest_cfg(artifact)
    return engine.submit_prefilled_stream(
        artifact.prompt, cfg, caches1, logits1,
        queue=queue or artifact.queue)


def ingest(engine, artifact: KVHandoffArtifact,
           queue: Optional[str] = None) -> np.ndarray:
    """Blocking variant of :func:`ingest_stream` (tests + batch path)."""
    caches1, logits1 = land_artifact(engine, artifact)
    cfg = _ingest_cfg(artifact)
    return engine.submit_prefilled(
        artifact.prompt, cfg, caches1, logits1,
        queue=queue or artifact.queue)


# ---- telemetry hooks shared with the router --------------------------


def observe_handoff(seconds: float) -> None:
    _HANDOFF_SECONDS.observe(seconds)


def observe_ttft(pool: str, seconds: float) -> None:
    _TTFT_H.labels(pool).observe(seconds)


def observe_itl(pool: str, seconds: float) -> None:
    _ITL_H.labels(pool).observe(seconds)


def count_reingest(reason: str) -> None:
    _REINGESTS.labels(reason).inc()


def count_backpressure_shed() -> None:
    _BACKPRESSURE_SHEDS.inc()
