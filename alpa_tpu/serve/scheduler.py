"""Request scheduling policies for the serving engine.

Analog of ref ``examples/llm_serving/service/scheduler.py`` (270 LoC:
WeightedRoundRobin via an "hourglass" event list, NestedScheduler,
FrontQueueScheduler).  Redesigned around virtual-time fair queueing —
the textbook SFQ formulation gives the same service proportions as the
reference's hourglass construction with far less machinery: each item
is tagged ``max(V, last_tag(queue)) + 1/weight`` at arrival and pops in
tag order, so backlogged queues share throughput in weight ratio and an
idle queue neither starves others nor banks credit.

All schedulers speak the engine's queue protocol — ``append(item)``,
``popleft()``, ``peek()``, ``pushback(items)``, ``drain()``,
``__len__`` — so ``ContinuousBatchingEngine(scheduler=...)`` swaps
policies without touching admission logic.  Items are the engine's
request dicts; the policy key is ``item.get("queue", "default")``.
"""
import heapq
from collections import deque
from typing import Dict, Iterable, List, Optional

__all__ = ["FIFOQueue", "WeightedFairQueue", "NestedScheduler"]

# a WeightedFairQueue's per-queue tag dict is pruned when it outgrows
# this (entries at or below virtual time are semantically dead weight)
_TAG_PRUNE_THRESHOLD = 1024


def _queue_name(item) -> str:
    return item.get("queue", "default") if isinstance(item, dict) \
        else "default"


class _FrontedQueue:
    """Shared protocol shell: a front deque for pushed-back items (the
    packed-admission path pops a prefix speculatively and may return
    it) ahead of whatever ordering the policy implements via
    ``_pop_policy`` / ``_peek_policy`` / ``_drain_policy`` /
    ``_len_policy``."""

    def __init__(self):
        self._front = deque()

    def pushback(self, items: Iterable):
        """Return borrowed items to the FRONT, preserving their order,
        ahead of all policy-ordered work."""
        for item in reversed(list(items)):
            self._front.appendleft(item)

    def popleft(self):
        if self._front:
            return self._front.popleft()
        return self._pop_policy()

    def peek(self):
        if self._front:
            return self._front[0]
        return self._peek_policy()

    def drain(self) -> List:
        out = list(self._front)
        self._front.clear()
        out.extend(self._drain_policy())
        return out

    def __len__(self):
        return len(self._front) + self._len_policy()


class FIFOQueue(_FrontedQueue):
    """The engine's default policy: one global arrival-order queue."""

    def __init__(self):
        super().__init__()
        self._q = deque()

    def append(self, item):
        self._q.append(item)

    def _pop_policy(self):
        return self._q.popleft()

    def _peek_policy(self):
        return self._q[0] if self._q else None

    def _drain_policy(self) -> List:
        out = list(self._q)
        self._q.clear()
        return out

    def _len_policy(self):
        return len(self._q)


class WeightedFairQueue(_FrontedQueue):
    """Start-time fair queueing across named queues.

    ``weights``: queue name -> positive weight; unknown queues get
    ``default_weight``.  Under backlog, queue throughput converges to
    the weight ratio; within a queue, FIFO order is preserved.
    """

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0):
        super().__init__()
        self.weights = dict(weights or {})
        self.default_weight = float(default_weight)
        if any(w <= 0 for w in self.weights.values()) or \
                default_weight <= 0:
            raise ValueError("weights must be positive")
        self._heap: List = []         # (tag, seq, item)
        self._seq = 0                 # FIFO tie-break + within-queue order
        self._vtime = 0.0             # virtual time = tag of last pop
        self._last_tag: Dict[str, float] = {}

    def append(self, item):
        name = _queue_name(item)
        start = max(self._vtime, self._last_tag.get(name, 0.0))
        tag = start + 1.0 / self.weights.get(name, self.default_weight)
        self._last_tag[name] = tag
        heapq.heappush(self._heap, (tag, self._seq, item))
        self._seq += 1

    def _pop_policy(self):
        tag, _seq, item = heapq.heappop(self._heap)
        self._vtime = tag
        if len(self._last_tag) > _TAG_PRUNE_THRESHOLD:
            # entries at/below vtime cannot affect any future tag
            # (start = max(vtime, last_tag)); pruning them bounds
            # memory against clients inventing unique queue names
            self._last_tag = {k: v for k, v in self._last_tag.items()
                              if v > self._vtime}
        return item

    def _peek_policy(self):
        return self._heap[0][2] if self._heap else None

    def _drain_policy(self) -> List:
        out = [it for _, _, it in sorted(self._heap)]
        self._heap.clear()
        return out

    def _len_policy(self):
        return len(self._heap)


class NestedScheduler(_FrontedQueue):
    """Two-level policy (ref NestedScheduler): an outer scheduler picks
    the GROUP, a per-group inner scheduler picks within it.

    The group key is ``item["group"]`` when present, else the prefix of
    the queue name before "/" — so the engine/controller API (which
    only carries ``queue``) drives both levels with composite names
    like ``"paid/alice"``: outer fairness across ``paid`` vs ``free``,
    inner policy (default FIFO) across the full names within a group.
    """

    def __init__(self, outer: Optional[WeightedFairQueue] = None,
                 inner_factory=FIFOQueue):
        super().__init__()
        self._outer = outer or WeightedFairQueue()
        self._inner: Dict[str, object] = {}
        self._inner_factory = inner_factory

    @staticmethod
    def _group(item) -> str:
        if isinstance(item, dict) and "group" in item:
            return item["group"]
        return _queue_name(item).split("/", 1)[0]

    def append(self, item):
        g = self._group(item)
        if g not in self._inner:
            self._inner[g] = self._inner_factory()
        self._inner[g].append(item)
        # the outer queue holds one token per queued item, tagged with
        # the group name so fair service applies across groups
        self._outer.append({"queue": g})

    def _pop_from_group(self, g: str):
        item = self._inner[g].popleft()
        if len(self._inner[g]) == 0:
            # drop drained inner queues: group names come from
            # untrusted queue fields, and an entry per ever-seen name
            # would grow forever (same threat WeightedFairQueue prunes
            # _last_tag against)
            del self._inner[g]
        return item

    def _pop_policy(self):
        token = self._outer.popleft()
        return self._pop_from_group(token["queue"])

    def _peek_policy(self):
        token = self._outer.peek()
        if token is None:
            return None
        return self._inner[token["queue"]].peek()

    def _drain_policy(self) -> List:
        out = []
        while len(self._outer):
            token = self._outer.popleft()
            out.append(self._pop_from_group(token["queue"]))
        return out

    def _len_policy(self):
        return len(self._outer)
