"""Request scheduling policies for the serving engine.

Analog of ref ``examples/llm_serving/service/scheduler.py`` (270 LoC:
WeightedRoundRobin via an "hourglass" event list, NestedScheduler,
FrontQueueScheduler).  Redesigned around virtual-time fair queueing —
the textbook SFQ formulation gives the same service proportions as the
reference's hourglass construction with far less machinery: each item
is tagged ``max(V, last_tag(queue)) + 1/weight`` at arrival and pops in
tag order, so backlogged queues share throughput in weight ratio and an
idle queue neither starves others nor banks credit.

All schedulers speak the engine's queue protocol:

* ``append(item)`` — enqueue (policy key: ``item.get("queue")``).
* ``popleft()`` / ``peek()`` — serve / inspect the policy head.
* ``take(selector)`` — SELECTIVE service in policy order:
  ``selector(item)`` returns ``"take"`` (remove + return), ``"skip"``
  (leave in place, priority untouched), or ``"stop"``.  This is how
  the request batcher forms sampling-compatible batches without
  destroying the policy state: skipped items keep their original
  virtual-time tags, and only actually-taken items advance service.
* ``pushback(items)`` — return items popped moments ago to the FRONT
  (the engine's speculative packed-admission path; the hold lasts one
  engine tick, so front-of-queue semantics are exact enough there).
* ``drain()`` — destructive empty-out in policy order (shutdown).
* ``__len__``.
"""
import heapq
from collections import deque
from typing import Dict, Iterable, List, Optional

__all__ = ["FIFOQueue", "WeightedFairQueue", "NestedScheduler"]

# a WeightedFairQueue's per-queue tag dict is pruned when it outgrows
# this (entries at or below virtual time are semantically dead weight)
_TAG_PRUNE_THRESHOLD = 1024


def _queue_name(item) -> str:
    return item.get("queue", "default") if isinstance(item, dict) \
        else "default"


class _FrontedQueue:
    """Shared protocol shell: a front deque for pushed-back items ahead
    of whatever ordering the policy implements via ``_pop_policy`` /
    ``_peek_policy`` / ``_take_policy`` / ``_drain_policy`` /
    ``_len_policy``."""

    def __init__(self):
        self._front = deque()

    def pushback(self, items: Iterable):
        """Return borrowed items to the FRONT, preserving their order,
        ahead of all policy-ordered work."""
        for item in reversed(list(items)):
            self._front.appendleft(item)

    def popleft(self):
        if self._front:
            return self._front.popleft()
        return self._pop_policy()

    def peek(self):
        if self._front:
            return self._front[0]
        return self._peek_policy()

    @staticmethod
    def _take_from_deque(q: deque, selector, taken: List) -> deque:
        """Run the selector loop over a deque; returns the kept deque
        (original order) and appends taken items.  Shared by the front
        pass and FIFO's policy pass so stop/skip semantics cannot
        drift.  A selector exception keeps the in-flight item in the
        kept deque (nothing is lost)."""
        kept = deque()
        while q:
            item = q.popleft()
            try:
                decision = selector(item)
            except Exception:
                # restore IN PLACE: the caller's reference to q (whose
                # reassignment never happens on a raise) must still
                # hold every non-taken item
                kept.append(item)
                kept.extend(q)
                q.clear()
                q.extend(kept)
                raise
            if decision == "take":
                taken.append(item)
            elif decision == "skip":
                kept.append(item)
            else:
                kept.append(item)
                break
        kept.extend(q)
        return kept

    def take(self, selector) -> List:
        """Pop items in policy order under ``selector`` decisions (see
        module docstring).  Front items are offered first.

        Exception safety: if the selector raises, items taken so far
        return to the FRONT and no item is lost — a faulty policy
        callback must never strand a request outside the queue."""
        taken = []
        stopped = [False]

        def wrapped(item):
            decision = selector(item)
            if decision == "stop":
                stopped[0] = True
            return decision

        try:
            self._front = self._take_from_deque(self._front, wrapped,
                                                taken)
            if not stopped[0]:
                # _take_policy appends into the SHARED list so a raise
                # mid-policy still leaves every taken item reachable
                # for the pushback below
                self._take_policy(wrapped, taken)
        except Exception:
            self.pushback(taken)
            raise
        return taken

    def drain(self) -> List:
        out = list(self._front)
        self._front.clear()
        out.extend(self._drain_policy())
        return out

    def __len__(self):
        return len(self._front) + self._len_policy()


class FIFOQueue(_FrontedQueue):
    """The engine's default policy: one global arrival-order queue."""

    def __init__(self):
        super().__init__()
        self._q = deque()

    def append(self, item):
        self._q.append(item)

    def _pop_policy(self):
        return self._q.popleft()

    def _peek_policy(self):
        return self._q[0] if self._q else None

    def _take_policy(self, selector, taken: List):
        self._q = self._take_from_deque(self._q, selector, taken)

    def _drain_policy(self) -> List:
        out = list(self._q)
        self._q.clear()
        return out

    def _len_policy(self):
        return len(self._q)


class WeightedFairQueue(_FrontedQueue):
    """Start-time fair queueing across named queues.

    ``weights``: queue name -> positive weight; unknown queues get
    ``default_weight``.  Under backlog, queue throughput converges to
    the weight ratio; within a queue, FIFO order is preserved.
    """

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0):
        super().__init__()
        self.weights = dict(weights or {})
        self.default_weight = float(default_weight)
        if any(w <= 0 for w in self.weights.values()) or \
                default_weight <= 0:
            raise ValueError("weights must be positive")
        self._heap: List = []         # (tag, seq, item)
        self._seq = 0                 # FIFO tie-break + within-queue order
        self._vtime = 0.0             # virtual time of last SERVICE
        self._last_tag: Dict[str, float] = {}

    def append(self, item):
        name = _queue_name(item)
        start = max(self._vtime, self._last_tag.get(name, 0.0))
        tag = start + 1.0 / self.weights.get(name, self.default_weight)
        self._last_tag[name] = tag
        heapq.heappush(self._heap, (tag, self._seq, item))
        self._seq += 1

    def _advance(self, tag):
        self._vtime = max(self._vtime, tag)
        if len(self._last_tag) > _TAG_PRUNE_THRESHOLD:
            # entries at/below vtime cannot affect any future tag
            # (start = max(vtime, last_tag)); pruning them bounds
            # memory against clients inventing unique queue names
            self._last_tag = {k: v for k, v in self._last_tag.items()
                              if v > self._vtime}

    def _pop_policy(self):
        tag, _seq, item = heapq.heappop(self._heap)
        self._advance(tag)
        return item

    def _peek_policy(self):
        return self._heap[0][2] if self._heap else None

    def _take_policy(self, selector, taken: List):
        # Lazy pops: peek the heap head, decide, then pop — entries are
        # visited in tag order straight off the heap, so the common
        # take (head batch, then "stop" once full) costs O(k log n)
        # against an n-item backlog instead of the full O(n log n)
        # sort-and-rebuild.  The unvisited tail is never touched.
        skipped = []
        try:
            while self._heap:
                entry = self._heap[0]
                tag, _seq, item = entry
                decision = selector(item)
                if decision == "take":
                    heapq.heappop(self._heap)
                    taken.append(item)
                    self._advance(tag)
                elif decision == "skip":
                    heapq.heappop(self._heap)
                    skipped.append(entry)
                else:
                    break
        finally:
            # also the raise path: the in-flight entry (peeked, never
            # popped) and the unvisited tail stay put; skipped entries
            # return with their original tags; entries taken so far are
            # off the heap (take() pushes the taken ITEMS back to the
            # front)
            for entry in skipped:
                heapq.heappush(self._heap, entry)
        return taken

    def _drain_policy(self) -> List:
        out = [it for _, _, it in sorted(self._heap)]
        self._heap.clear()
        return out

    def _len_policy(self):
        return len(self._heap)


class NestedScheduler(_FrontedQueue):
    """Two-level policy (ref NestedScheduler): an outer scheduler picks
    the GROUP, a per-group inner scheduler picks within it.

    The group key is ``item["group"]`` when present, else the prefix of
    the queue name before "/" — so the engine/controller API (which
    only carries ``queue``) drives both levels with composite names
    like ``"paid/alice"``: outer fairness across ``paid`` vs ``free``,
    inner policy (default FIFO) across the full names within a group.
    """

    def __init__(self, outer: Optional[WeightedFairQueue] = None,
                 inner_factory=FIFOQueue):
        super().__init__()
        self._outer = outer or WeightedFairQueue()
        self._inner: Dict[str, object] = {}
        self._inner_factory = inner_factory

    @staticmethod
    def _group(item) -> str:
        if isinstance(item, dict) and "group" in item:
            return item["group"]
        return _queue_name(item).split("/", 1)[0]

    def append(self, item):
        g = self._group(item)
        if g not in self._inner:
            self._inner[g] = self._inner_factory()
        self._inner[g].append(item)
        # the outer queue holds one token per queued item, tagged with
        # the group name so fair service applies across groups
        self._outer.append({"queue": g})

    def _pop_from_group(self, g: str):
        item = self._inner[g].popleft()
        if len(self._inner[g]) == 0:
            # drop drained inner queues: group names come from
            # untrusted queue fields, and an entry per ever-seen name
            # would grow forever (same threat WeightedFairQueue prunes
            # _last_tag against)
            del self._inner[g]
        return item

    def _pop_policy(self):
        token = self._outer.popleft()
        return self._pop_from_group(token["queue"])

    def _peek_policy(self):
        token = self._outer.peek()
        if token is None:
            return None
        return self._inner[token["queue"]].peek()

    def _take_policy(self, selector, taken: List):
        """Offer each group's inner HEAD in outer policy order.  A
        'skip' on a group's head skips the whole group for this take
        (deeper inner items are unreachable without consuming the
        head); taken heads consume their outer token (real service),
        skipped groups' tokens stay untouched.

        A selector exception is captured so the outer take completes
        cleanly (tokens for already-taken items are consumed, matching
        the inner pops), the popped items return to the front, and the
        error re-raises — nothing is lost."""
        skip_groups = set()
        stop = [False]
        err: List = []

        def outer_selector(token):
            if err or stop[0]:
                return "stop"
            g = token["queue"]
            if g in skip_groups:
                return "skip"
            head = self._inner[g].peek()
            try:
                decision = selector(head)
            except Exception as e:  # pylint: disable=broad-except
                err.append(e)
                return "stop"
            if decision == "take":
                taken.append(self._pop_from_group(g))
                return "take"
            if decision == "skip":
                skip_groups.add(g)
                return "skip"
            stop[0] = True
            return "stop"

        self._outer.take(outer_selector)
        if err:
            # taken items are in the SHARED list; the caller's except
            # path pushes them back — just surface the error
            raise err[0]

    def _drain_policy(self) -> List:
        out = []
        for token in self._outer.drain():
            out.append(self._pop_from_group(token["queue"]))
        return out

    def _len_policy(self):
        return len(self._outer)
