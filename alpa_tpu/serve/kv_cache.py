"""Paged KV cache with cross-request prefix reuse (ISSUE 11 tentpole).

vLLM-style paged attention memory, adapted to this repo's cache-as-invars
convention: the serving engine keeps decoding on its DENSE resident caches
(``(B, seq_len, heads, head_dim)`` per layer — the compute view the
compiled decode step was built for), while this pool is the STORAGE tier
behind it: KV lives in fixed-size token blocks, each sequence owns a
block table, blocks are refcounted with copy-on-write, and a hash-chain
index over full-block contents lets any request whose prompt shares a
token prefix with a live or recently finished request skip recomputing
those blocks entirely (they are gathered back into the dense row and
prefill resumes at the match offset via the chunked-prefill path).

Design points that keep everything fixed-shape (one jit compile per
engine lifetime, like the rest of the serving stack):

* Block id 0 is a reserved scratch block.  Gather/scatter calls take
  block-id vectors padded to the per-sequence maximum with id 0 plus a
  mask; masked-out lanes read as zeros and write into scratch, which is
  never read — so every pool op runs at one fixed shape regardless of
  how many blocks a sequence actually holds.
* Eviction only ever touches blocks whose sole reference is the prefix
  index itself (refcount == 1, leaf entries, not pinned), so a cached
  prefix being dropped under pressure can never corrupt a live
  sequence's KV.
* Gather and scatter move bits unchanged, and the no-hit admission path
  is operation-identical to the unpaged engine — paged decode is
  bit-exact vs unpaged (pinned in tests/serve/test_kv_cache.py).

The pool is NOT thread-safe by design intent (the engine loop is its
single writer), but all bookkeeping is taken under an internal lock so
stats/readers from other threads (``/healthz``, the router) stay
consistent.
"""
import hashlib
import logging
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu.global_env import global_config
from alpa_tpu.model.gpt_model import init_kv_caches
from alpa_tpu.telemetry import metrics as _tmetrics

logger = logging.getLogger(__name__)

_REG = _tmetrics.get_registry()
_BLOCKS_IN_USE = _REG.gauge(
    "alpa_kv_blocks_in_use",
    "KV pool blocks held by live sequences or the prefix index")
_PREFIX_HITS = _REG.counter(
    "alpa_kv_prefix_hits_total",
    "Admissions that reused at least one cached prefix block")
_BYTES_SAVED = _REG.counter(
    "alpa_kv_bytes_saved_total",
    "KV bytes served from the prefix index instead of recomputed")
_EVICTIONS = _REG.counter(
    "alpa_kv_evictions_total",
    "Prefix-index blocks evicted under pool pressure")

_ROOT = b"alpa-kv-root"


class KVPoolExhaustedError(RuntimeError):
    """A single request needs more blocks than the pool can ever free."""


class PagedSequence:
    """One sequence's block table: ``ids[i]`` backs token positions
    ``[i*block_size, (i+1)*block_size)``.  Capacity is reserved up front
    (prompt + max_new_tokens) so admission is the only backpressure
    point — a decoding sequence can never run out of blocks."""

    __slots__ = ("ids", "block_size", "prompt_len", "matched_tokens",
                 "capacity_tokens")

    def __init__(self, ids: List[int], block_size: int, prompt_len: int,
                 matched_tokens: int, capacity_tokens: int):
        self.ids = ids
        self.block_size = block_size
        self.prompt_len = prompt_len
        self.matched_tokens = matched_tokens
        self.capacity_tokens = capacity_tokens

    def block_of(self, pos: int) -> int:
        return self.ids[pos // self.block_size]


class _Entry:
    """One cached full block in the prefix index.  ``key`` is the chain
    hash H(parent_key, block_tokens): equal keys mean equal token
    PATHS from the sequence start, so a key match guarantees the cached
    KV is exactly what recomputation would produce."""

    __slots__ = ("key", "parent", "block", "pinned")

    def __init__(self, key: bytes, parent: bytes, block: int,
                 pinned: bool):
        self.key = key
        self.parent = parent
        self.block = block
        self.pinned = pinned


def _chain_key(parent: bytes, tokens: np.ndarray) -> bytes:
    h = hashlib.sha256(parent)
    h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
    return h.digest()


class KVBlockPool:
    """Refcounted block pool + prefix index for one engine/generator.

    A pool is bound to one set of params (cached KV is only valid for
    the weights that produced it); hot weight swaps therefore rebuild
    the engine AND its pool together (``controller._Replica``).
    """

    def __init__(self, config, num_blocks: Optional[int] = None,
                 block_size: Optional[int] = None,
                 prefix_reuse: Optional[bool] = None):
        bs = block_size or global_config.kv_block_size
        if bs <= 0:
            raise ValueError(f"kv_block_size must be positive, got {bs}")
        if config.seq_len % bs:
            raise ValueError(
                f"kv_block_size {bs} must divide seq_len "
                f"{config.seq_len} (block tables tile the cache exactly)")
        n = num_blocks if num_blocks is not None else \
            global_config.kv_cache_blocks
        self.blocks_per_seq = config.seq_len // bs
        if not n:
            # auto-size: room for a full engine batch worth of sequences
            # is the caller's job (for_generator); standalone default is
            # two sequences' worth
            n = 2 * self.blocks_per_seq
        self.block_size = bs
        self.num_blocks = int(n)
        self.seq_len = config.seq_len
        self.prefix_reuse = (global_config.kv_prefix_reuse
                             if prefix_reuse is None else prefix_reuse)
        self.config = config

        # per-layer pool arrays mirror the engine cache convention via
        # the same init used for the dense caches (works for any family
        # honoring the (k, v, index) contract)
        template = init_kv_caches(config, 1)
        self._kp, self._vp = [], []
        self.token_bytes = 0
        for (k, v, _i) in template:
            blk_shape = (self.num_blocks + 1, bs) + k.shape[2:]
            self._kp.append(jnp.zeros(blk_shape, k.dtype))
            self._vp.append(jnp.zeros(blk_shape, v.dtype))
            per_tok = int(np.prod(k.shape[2:]))
            self.token_bytes += 2 * per_tok * k.dtype.itemsize
        self.block_bytes = self.token_bytes * bs

        self._lock = threading.RLock()
        self._rc = np.zeros(self.num_blocks + 1, np.int64)
        self._rc[0] = 1  # scratch: permanently reserved
        self._free = list(range(self.num_blocks, 0, -1))
        self._entries: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._children: Dict[bytes, set] = {}
        self.prefix_hits = 0
        self.bytes_saved = 0
        self.evictions = 0

        nmax, L = self.blocks_per_seq, config.seq_len

        def gather(kp, vp, ids, mask):
            outs = []
            m4 = mask[:, None, None, None]
            for k, v in zip(kp, vp):
                dk = jnp.where(m4, k[ids], 0).reshape((1, L) + k.shape[2:])
                dv = jnp.where(m4, v[ids], 0).reshape((1, L) + v.shape[2:])
                outs.append((dk, dv))
            return outs

        def scatter_blocks(kp, vp, dk, dv, ids, mask):
            # masked-out lanes are redirected into scratch block 0
            sel = jnp.where(mask, ids, 0)
            nk, nv = [], []
            for k, v, d_k, d_v in zip(kp, vp, dk, dv):
                bk = d_k.reshape((nmax, bs) + k.shape[2:])
                bv = d_v.reshape((nmax, bs) + v.shape[2:])
                nk.append(k.at[sel].set(bk))
                nv.append(v.at[sel].set(bv))
            return nk, nv

        def scatter_token(kp, vp, ck, cv, pos, blocks, offs):
            rows = jnp.arange(pos.shape[0])
            nk, nv = [], []
            for k, v, c_k, c_v in zip(kp, vp, ck, cv):
                nk.append(k.at[blocks, offs].set(c_k[rows, pos]))
                nv.append(v.at[blocks, offs].set(c_v[rows, pos]))
            return nk, nv

        def copy_block(kp, vp, src, dst):
            nk, nv = [], []
            for k, v in zip(kp, vp):
                nk.append(k.at[dst].set(k[src]))
                nv.append(v.at[dst].set(v[src]))
            return nk, nv

        self._gather_jit = jax.jit(gather)
        # the pool buffers are donated: every mutator returns the new
        # arrays and the (lock-held) caller immediately rebinds
        # self._kp/_vp, so XLA updates the pool in place instead of
        # copying the whole block store per scatter
        self._scatter_blocks_jit = jax.jit(scatter_blocks,
                                           donate_argnums=(0, 1))
        self._scatter_token_jit = jax.jit(scatter_token,
                                          donate_argnums=(0, 1))
        self._copy_block_jit = jax.jit(copy_block,
                                       donate_argnums=(0, 1))

    @classmethod
    def for_generator(cls, generator, max_batch: int = 4,
                      **kwargs) -> "KVBlockPool":
        """Pool sized for an engine: knob ``kv_cache_blocks`` when set,
        else one full batch of sequences plus one batch's worth of
        headroom for cached prefixes."""
        cfg = generator.config
        bs = kwargs.get("block_size") or global_config.kv_block_size
        n = global_config.kv_cache_blocks or \
            (2 * max_batch * (cfg.seq_len // max(1, bs)))
        kwargs.setdefault("num_blocks", n)
        return cls(cfg, **kwargs)

    # ---- capacity ---------------------------------------------------

    def _pinned_blocks(self) -> int:
        return sum(1 for e in self._entries.values() if e.pinned)

    def fits(self, total_tokens: int) -> bool:
        """Whether a request of ``total_tokens`` (prompt + max new) can
        EVER be admitted — pinned prefix blocks are unreclaimable."""
        need = -(-total_tokens // self.block_size)
        with self._lock:
            return need <= self.num_blocks - self._pinned_blocks()

    def blocks_in_use(self) -> int:
        with self._lock:
            return self.num_blocks - len(self._free)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "blocks_in_use": self.num_blocks - len(self._free),
                "cached_entries": len(self._entries),
                "pinned_entries": self._pinned_blocks(),
                "prefix_hits": self.prefix_hits,
                "bytes_saved": self.bytes_saved,
                "evictions": self.evictions,
            }

    def _update_gauge(self):
        _BLOCKS_IN_USE.set(self.num_blocks - len(self._free))

    # ---- refcounting ------------------------------------------------

    def _decref(self, block: int):
        self._rc[block] -= 1
        if self._rc[block] < 0:
            raise AssertionError(f"block {block} refcount underflow")
        if self._rc[block] == 0:
            self._free.append(block)

    def _evict_one(self) -> bool:
        """Drop the least-recently-used evictable index entry (leaf, not
        pinned, no other holders).  Parents are always touched at least
        as recently as their children on a match walk, so LRU order
        visits children first — eviction peels chains from the tail."""
        for key in list(self._entries):
            e = self._entries[key]
            if e.pinned or self._children.get(key):
                continue
            if self._rc[e.block] != 1:
                continue  # a live sequence still shares this block
            del self._entries[key]
            sibs = self._children.get(e.parent)
            if sibs is not None:
                sibs.discard(key)
                if not sibs:
                    del self._children[e.parent]
            self._decref(e.block)
            self.evictions += 1
            _EVICTIONS.inc()
            return True
        return False

    def _allocate(self, n: int) -> Optional[List[int]]:
        while len(self._free) < n:
            if not self._evict_one():
                return None
        got = [self._free.pop() for _ in range(n)]
        for b in got:
            self._rc[b] = 1
        return got

    # ---- prefix index -----------------------------------------------

    def _match_and_ref(self, tokens: np.ndarray) -> List[int]:
        """Walk the hash chain over full prompt blocks, taking a
        reference on every hit.  Capped so at least the final prompt
        token is always recomputed — its logits seed decode."""
        bs = self.block_size
        cap = (len(tokens) - 1) // bs
        matched, parent = [], _ROOT
        for i in range(cap):
            key = _chain_key(parent, tokens[i * bs:(i + 1) * bs])
            e = self._entries.get(key)
            if e is None:
                break
            self._rc[e.block] += 1
            self._entries.move_to_end(key)
            matched.append(e.block)
            parent = key
        return matched

    def _register_chain(self, tokens: np.ndarray, ids: List[int],
                        pinned: bool = False) -> int:
        """Insert every full block of ``tokens`` into the index (the
        index holds its own reference).  Existing entries win — content
        keys are path-unique, so a duplicate block is simply not
        indexed twice."""
        bs = self.block_size
        parent, added = _ROOT, 0
        for i in range(len(tokens) // bs):
            key = _chain_key(parent, tokens[i * bs:(i + 1) * bs])
            e = self._entries.get(key)
            if e is None:
                e = _Entry(key, parent, ids[i], pinned)
                self._entries[key] = e
                self._children.setdefault(parent, set()).add(key)
                self._rc[ids[i]] += 1
                added += 1
            elif pinned:
                e.pinned = True
            self._entries.move_to_end(key)
            parent = key
        return added

    # ---- sequence lifecycle -----------------------------------------

    def begin_sequence(self, tokens, max_new_tokens: int
                       ) -> Optional[PagedSequence]:
        """Reserve a block table for prompt + max_new_tokens, reusing
        cached prefix blocks when the index matches.  Returns ``None``
        when the pool cannot free enough blocks RIGHT NOW (live
        sequences hold them — the caller backpressures and retries
        after a decode tick); raises :class:`KVPoolExhaustedError` when
        the request can never fit."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        total = len(tokens) + int(max_new_tokens)
        need = -(-total // self.block_size)
        with self._lock:
            if need > self.num_blocks - self._pinned_blocks():
                raise KVPoolExhaustedError(
                    f"request needs {need} blocks; pool has "
                    f"{self.num_blocks} ({self._pinned_blocks()} pinned)")
            matched: List[int] = []
            if self.prefix_reuse:
                matched = self._match_and_ref(tokens)
            got = self._allocate(need - len(matched))
            if got is None:
                for b in matched:
                    self._decref(b)
                return None
            seq = PagedSequence(
                ids=matched + got, block_size=self.block_size,
                prompt_len=len(tokens),
                matched_tokens=len(matched) * self.block_size,
                capacity_tokens=need * self.block_size)
            if matched:
                self.prefix_hits += 1
                _PREFIX_HITS.inc()
                saved = len(matched) * self.block_bytes
                self.bytes_saved += saved
                _BYTES_SAVED.inc(saved)
            self._update_gauge()
            return seq

    def release(self, seq: PagedSequence, tokens=None,
                register: bool = True):
        """Return a sequence's blocks.  With ``register`` (and reuse
        on), every FULL block of ``tokens`` (prompt + generated) is
        first published to the prefix index so follow-up and multi-turn
        requests can hit it; the index reference keeps those blocks
        alive past the sequence."""
        with self._lock:
            if register and self.prefix_reuse and tokens is not None:
                tokens = np.asarray(tokens, np.int32).reshape(-1)
                self._register_chain(tokens, seq.ids)
            for b in seq.ids:
                self._decref(b)
            seq.ids = []
            self._update_gauge()

    def register_prompt(self, seq: PagedSequence, tokens):
        """Publish a live sequence's full PROMPT blocks immediately
        after admission, so concurrent requests sharing the prefix hit
        while this one is still decoding."""
        if not self.prefix_reuse:
            return
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        nfull = (len(tokens) // self.block_size) * self.block_size
        with self._lock:
            self._register_chain(tokens[:nfull], seq.ids)
            self._update_gauge()

    # ---- copy-on-write ----------------------------------------------

    def fork(self, seq: PagedSequence) -> PagedSequence:
        """Second table over the same blocks (shared until written)."""
        with self._lock:
            for b in seq.ids:
                self._rc[b] += 1
            self._update_gauge()
            return PagedSequence(
                ids=list(seq.ids), block_size=seq.block_size,
                prompt_len=seq.prompt_len,
                matched_tokens=seq.matched_tokens,
                capacity_tokens=seq.capacity_tokens)

    def ensure_writable(self, seq: PagedSequence, block_idx: int) -> int:
        """Copy-on-write: before writing into ``seq.ids[block_idx]``,
        give the sequence a private copy if the block is shared (other
        tables or the prefix index hold it)."""
        with self._lock:
            b = seq.ids[block_idx]
            if self._rc[b] <= 1:
                return b
            got = self._allocate(1)
            if got is None:
                raise KVPoolExhaustedError(
                    "no free block for copy-on-write")
            dst = got[0]
            self._kp, self._vp = self._copy_block_jit(
                self._kp, self._vp, b, dst)
            self._decref(b)
            seq.ids[block_idx] = dst
            self._update_gauge()
            return dst

    # ---- device data movement ---------------------------------------

    def _padded_ids(self, ids: List[int], lo: int, hi: int):
        arr = np.zeros((self.blocks_per_seq,), np.int32)
        mask = np.zeros((self.blocks_per_seq,), bool)
        arr[lo:hi] = ids[lo:hi]
        mask[lo:hi] = True
        return jnp.asarray(arr), jnp.asarray(mask)

    def gather_dense(self, seq: PagedSequence):
        """Materialize the matched prefix region of ``seq`` as dense
        per-layer caches ``[(k, v, index_vec)]`` positioned at the match
        offset — exactly the shape ``Generator._run_chunked_prefill``
        resumes from (the reuse-hit admission path)."""
        m = seq.matched_tokens // self.block_size
        ids, mask = self._padded_ids(seq.ids, 0, m)
        with self._lock:
            outs = self._gather_jit(self._kp, self._vp, ids, mask)
        idx = jnp.asarray([seq.matched_tokens], jnp.int32)
        return [(k, v, idx) for (k, v) in outs]

    def gather_blocks(self, seq: PagedSequence, num_blocks: int):
        """Materialize the first ``num_blocks`` blocks of ``seq`` as
        host block arrays ``[(k, v)]`` per layer, each shaped
        ``(num_blocks, block_size, ...)`` — the block-table slice a
        disaggregated prefill replica ships to a decode replica
        (serve.disagg).  Gather moves bits unchanged, so the handoff
        payload is exactly what the pool holds."""
        ids, mask = self._padded_ids(seq.ids, 0, num_blocks)
        with self._lock:
            outs = self._gather_jit(self._kp, self._vp, ids, mask)
        bs = self.block_size
        res = []
        for (k, v) in outs:
            kk = np.asarray(k)[0, :num_blocks * bs]
            vv = np.asarray(v)[0, :num_blocks * bs]
            res.append((kk.reshape((num_blocks, bs) + kk.shape[1:]),
                        vv.reshape((num_blocks, bs) + vv.shape[1:])))
        return res

    def scatter_prompt(self, seq: PagedSequence, dense_caches):
        """Store the freshly prefilled prompt region (dense single-row
        caches) into the sequence's NEW blocks — matched blocks already
        hold identical bits and are skipped."""
        m = seq.matched_tokens // self.block_size
        nprompt = -(-seq.prompt_len // self.block_size)
        if nprompt <= m:
            return
        ids, mask = self._padded_ids(seq.ids, m, nprompt)
        dk = [c[0] for c in dense_caches]
        dv = [c[1] for c in dense_caches]
        with self._lock:
            self._kp, self._vp = self._scatter_blocks_jit(
                self._kp, self._vp, dk, dv, ids, mask)

    def write_tokens(self, batch_caches,
                     tables: List[Optional[PagedSequence]],
                     positions: np.ndarray):
        """Per decode tick: copy each active row's just-written K/V
        position from the dense batch caches into its table's block.
        Rows without a table write into scratch (fixed shape — one
        compile for the engine's whole life)."""
        B = len(tables)
        blocks = np.zeros((B,), np.int32)
        offs = np.zeros((B,), np.int32)
        pos = np.zeros((B,), np.int32)
        for r, t in enumerate(tables):
            if t is None:
                continue
            p = int(positions[r])
            if p >= t.capacity_tokens:
                raise AssertionError(
                    f"row {r} wrote past its reserved blocks "
                    f"({p} >= {t.capacity_tokens})")
            blocks[r] = t.block_of(p)
            offs[r] = p % self.block_size
            pos[r] = p
        ck = [c[0] for c in batch_caches]
        cv = [c[1] for c in batch_caches]
        with self._lock:
            self._kp, self._vp = self._scatter_token_jit(
                self._kp, self._vp, ck, cv, jnp.asarray(pos),
                jnp.asarray(blocks), jnp.asarray(offs))

    # ---- warmed (registered) prefixes -------------------------------

    def warm_prefix(self, generator, prefix_ids) -> int:
        """Precompute a system prompt's KV into PINNED index entries
        (supersedes the one-static-``PrefixHandle`` mode for paged
        serving: requests send FULL prompts and match against any number
        of warmed prefixes).  Returns the number of tokens warmed."""
        ids = np.asarray(prefix_ids, np.int32).reshape(-1)
        nfull = len(ids) // self.block_size
        if nfull == 0 or not self.prefix_reuse:
            return 0
        span = nfull * self.block_size
        lengths = jnp.asarray([span], jnp.int32)
        if generator.prefill_chunk:
            _, caches = generator._run_chunked_prefill(
                [ids[:span]], lengths, 1)
        else:
            _, caches = generator._run_bucketed_prefill(
                [ids[:span]], lengths, 1)
        with self._lock:
            got = self._allocate(nfull)
            if got is None:
                raise KVPoolExhaustedError(
                    f"cannot pin {nfull} blocks for a warmed prefix")
        seq = PagedSequence(ids=got, block_size=self.block_size,
                            prompt_len=span, matched_tokens=0,
                            capacity_tokens=span)
        self.scatter_prompt(seq, caches)
        with self._lock:
            self._register_chain(ids[:span], got, pinned=True)
            # drop the bootstrap references; the pinned entries keep
            # the blocks alive forever
            for b in got:
                self._decref(b)
            self._update_gauge()
        logger.info("warmed %d prefix tokens (%d pinned blocks)",
                    span, nfull)
        return span
