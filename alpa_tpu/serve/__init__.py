"""Model serving: generation engine + HTTP controller.

Analog of ref ``alpa/serve/`` + ``examples/llm_serving`` (SURVEY.md §2.8,
§3.5): a controller with a model registry dispatching to replicas, and an
autoregressive generation engine with resident KV caches compiled per
(batch, length-bucket).
"""
from alpa_tpu.serve.generation import (GenerationConfig, Generator,
                                       PrefixHandle, get_model)
from alpa_tpu.serve.controller import (Controller, ControllerServer,
                                       RequestBatcher, run_controller)
from alpa_tpu.serve.engine import ContinuousBatchingEngine
from alpa_tpu.serve.hf_wrapper import WrappedInferenceModel, get_hf_model
from alpa_tpu.serve.kv_cache import (KVBlockPool, KVPoolExhaustedError,
                                     PagedSequence)
from alpa_tpu.serve.packed import PackedPrefill, pack_prompts
from alpa_tpu.serve.router import (HTTPReplicaHandle, LocalReplicaHandle,
                                   Router, RouterServer)
from alpa_tpu.serve.scheduler import (FIFOQueue, NestedScheduler,
                                      WeightedFairQueue)
