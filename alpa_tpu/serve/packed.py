"""Packed 1-D prefill: many prompts in ONE model forward.

TPU-native analog of the reference's 1-D packed batching
(ref ``examples/llm_serving/model/opt_model_1d.py`` + ``wrapper_1d.py``):
the reference flattens all prompts into one token stream and relies on a
custom fused-MHA CUDA kernel with an external cache manager; here the
same packing rides a block-diagonal SEGMENT mask inside stock XLA
attention (static shapes, no custom kernel), and the packed KV is
re-gathered into per-row caches with one XLA gather — so the row-level
continuous-batching engine decodes from it unchanged.

Why packing: N single-prompt prefills waste (bucket - len) padding FLOPs
per prompt and N dispatches; one packed prefill pays one dispatch and
pads only to the shared total bucket.

Scope: models whose positions enter via ``position_ids`` (GPT/OPT
learned embeddings).  Rotary/ALiBi models bake positions into attention
at their GLOBAL offset, so relocating packed KV to row-local offsets
would corrupt them — they take the per-row prefill path instead.
"""
import logging
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu.model.gpt_model import GPTConfig, init_kv_caches

logger = logging.getLogger(__name__)


def pack_prompts(prompts: Sequence[np.ndarray], total_bucket: int,
                 max_rows: int) -> Tuple[np.ndarray, ...]:
    """Pack prompts into one (1, total_bucket) row.

    Returns (ids, segment_ids, position_ids, starts, lens); all prompt
    slots beyond ``len(prompts)`` get a 1-token dummy segment sharing
    position 0 of the padding region (masked out by segment id -1 where
    unused).
    """
    assert len(prompts) <= max_rows
    ids = np.zeros((1, total_bucket), np.int32)
    seg = np.full((1, total_bucket), -1, np.int32)
    pos = np.zeros((1, total_bucket), np.int32)
    starts = np.zeros((max_rows,), np.int32)
    lens = np.ones((max_rows,), np.int32)
    off = 0
    for r, p in enumerate(prompts):
        p = np.asarray(p, np.int32).reshape(-1)
        n = len(p)
        assert off + n <= total_bucket, (
            f"packed length {off + n} exceeds bucket {total_bucket}")
        ids[0, off:off + n] = p
        seg[0, off:off + n] = r
        pos[0, off:off + n] = np.arange(n)
        starts[r] = off
        lens[r] = n
        off += n
    return ids, seg, pos, starts, lens


class PackedPrefill:
    """One compiled executable: packed forward + KV re-gather to rows.

    ``__call__`` takes up to ``max_rows`` prompts whose total length fits
    ``total_bucket`` and returns (last_logits (max_rows, V), row_caches)
    where row_caches are (max_rows, seq_len, H, D) caches with per-row
    write indices — exactly the continuous-batching engine's resident
    layout.  Rows beyond the submitted prompt count carry a 1-token dummy
    and must be ignored by the caller.
    """

    def __init__(self, model, params, config: GPTConfig,
                 total_bucket: int, max_rows: int, prefix=None):
        """``prefix``: an optional ``generation.PrefixHandle`` (shared
        system prompt).  The packed chunk is then written at cache
        offset ``prefix.length``: every segment attends to the prefix
        K/V plus its own span, positions continue from the prefix, and
        the per-row re-gather lays each row out as [prefix | suffix]."""
        self.model = model
        self.params = params
        self.config = config
        self.prefix = prefix
        plen = int(prefix.length) if prefix is not None else 0
        self.prefix_len = plen
        self.total_bucket = int(total_bucket)
        self.max_rows = int(max_rows)
        assert plen + self.total_bucket <= config.seq_len, (
            f"prefix {plen} + packed bucket {total_bucket} exceeds "
            f"KV-cache capacity (seq_len {config.seq_len})")
        if prefix is not None and getattr(prefix, "params", None) \
                is not params:
            raise ValueError("PrefixHandle was built for different params")
        self.traces = 0
        row_cap = config.seq_len
        cap = plen + self.total_bucket

        def prefill(params, ids, seg, pos, starts, lens, caches):
            self.traces += 1
            # packed caches sized to prefix + bucket, not full seq_len
            caches = [(k[:, :cap], v[:, :cap], i)
                      for (k, v, i) in caches]
            logits, caches = model.apply(params, ids, pos, caches,
                                         segment_ids=seg)
            # one gather per layer relocates each prompt's KV span to
            # its row-local origin, after the shared prefix region
            # (copied verbatim to every row); positions past len are
            # clamped repeats, masked at decode by the per-row index
            t = jnp.arange(row_cap)[None, :]                 # (1, cap)
            sfx = plen + starts[:, None] + jnp.minimum(
                jnp.maximum(t - plen, 0), lens[:, None] - 1)
            idx = jnp.minimum(jnp.where(t < plen, t, sfx), cap - 1)
            row_caches = []
            for (k, v, _i) in caches:
                rk = k[0][idx]                               # (R, cap, H, D)
                rv = v[0][idx]
                row_caches.append((rk, rv, plen + lens))
            last = logits[0, starts + lens - 1]              # (R, V)
            return last, row_caches

        self._prefill = jax.jit(prefill)

    def __call__(self, prompts: Sequence[np.ndarray]):
        ids, seg, pos, starts, lens = pack_prompts(
            prompts, self.total_bucket, self.max_rows)
        if self.prefix is not None:
            caches = self.prefix.caches
            pos = pos + self.prefix_len  # global positions after prefix
        else:
            caches = init_kv_caches(self.config, 1)
        return self._prefill(self.params, jnp.asarray(ids),
                             jnp.asarray(seg), jnp.asarray(pos),
                             jnp.asarray(starts), jnp.asarray(lens),
                             caches)
