"""Autoregressive generation with resident KV caches.

Analog of ref ``examples/llm_serving/model/wrapper.py:501`` (``get_model``,
the HF-GenerationMixin-compatible wrapper): prefill + decode executables
compiled once, KV caches living on device between steps (ref
``init_cache_dis_array`` opt_model.py:1044 — here plain sharded jax.Arrays
threaded through the jitted step, ref cache-as-invars design).

Supports greedy / temperature / top-k sampling, batched requests, and a
pluggable parallel method (ShardParallel on one mesh today; the pipeshard
inference schedule slots in via the same executable interface).
"""
import dataclasses
import logging
from functools import partial
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu.model.gpt_model import GPTConfig, GPTModel, init_kv_caches

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class PrefixHandle:
    """Precomputed KV for a shared prompt prefix (system prompt caching):
    B=1 caches holding ``length`` tokens at scalar write index
    ``length``.  Created by ``Generator.cache_prefix``; consumed by
    ``generate(..., prefix=handle)``, which broadcasts the K/V across
    the batch and prefills only each request's suffix.  ``last_logits``
    are the prefix's final-token logits, so empty suffixes generate
    straight from the cached prompt.  ``params`` is a strong reference
    used for identity guarding (a raw id() could collide after GC)."""
    caches: Any
    length: int
    last_logits: Any
    params: Any = dataclasses.field(repr=False, default=None)


@dataclasses.dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    temperature: float = 1.0
    top_k: int = 0           # 0 = no top-k filtering
    do_sample: bool = False
    eos_token_id: Optional[int] = None


# The ONE top-k mask value, shared by the device sampler and the
# host-side prob warper.  It must be -inf: a finite sentinel like -1e9
# leaves masked tokens with tiny-but-nonzero device probability while
# the host assigns them exactly zero, and speculative sampling's
# acceptance ratio p/q is only exact when both agree on the support.
TOP_K_MASK = float("-inf")


def _sample_logits(logits, rng, cfg: GenerationConfig):
    logits = logits.astype(jnp.float32)
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1)
    if cfg.temperature != 1.0:
        logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        top = jax.lax.top_k(logits, cfg.top_k)[0][..., -1:]
        logits = jnp.where(logits < top, TOP_K_MASK, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def _warp_probs_np(logits, cfg: GenerationConfig) -> np.ndarray:
    """Host-side probabilities under the cfg's warping (temperature +
    top-k), matching ``_sample_logits``'s semantics (ties at the k-th
    value survive).  float64 for exact rejection-sampling ratios."""
    x = np.asarray(logits, np.float64)
    if cfg.temperature != 1.0:
        x = x / max(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        kth = np.partition(x, -cfg.top_k, axis=-1)[..., -cfg.top_k, None]
        x = np.where(x < kth, TOP_K_MASK, x)
    x = x - x.max(axis=-1, keepdims=True)
    p = np.exp(x)
    return p / p.sum(axis=-1, keepdims=True)


def _sample_from_probs(p: np.ndarray, u: float) -> int:
    """Inverse-CDF draw from a probability vector with one uniform."""
    c = np.cumsum(p)
    return int(np.clip(np.searchsorted(c, u * c[-1], side="right"),
                       0, len(p) - 1))


def speculative_accept(props, q_probs, p_probs, us, u_extra):
    """Rejection-sampling acceptance (Leviathan et al. speculative
    sampling): token i drawn from q_i is accepted with probability
    min(1, p_i(x)/q_i(x)); the first rejection emits from the residual
    norm(max(p_i - q_i, 0)); a fully-accepted round emits a bonus token
    from p_k.  Returns (num_accepted, extra_token).  The marginal
    distribution of every emitted token is EXACTLY p — see
    tests/serve/test_speculative_sampling.py for the statistical proof
    harness.

    ``props``: k proposed tokens; ``q_probs``: (k, V) draft probs;
    ``p_probs``: (k+1, V) target probs; ``us``: k uniforms;
    ``u_extra``: one uniform for the residual/bonus draw.
    """
    k = len(props)
    for i in range(k):
        x = int(props[i])
        ratio = p_probs[i][x] / max(q_probs[i][x], 1e-300)
        if us[i] < min(1.0, ratio):
            continue
        residual = np.maximum(p_probs[i] - q_probs[i], 0.0)
        s = residual.sum()
        if s <= 0.0:
            # p == q exactly: the residual is empty and acceptance was
            # certain up to float rounding — fall back to p itself
            residual, s = p_probs[i], p_probs[i].sum()
        return i, _sample_from_probs(residual / s, u_extra)
    return k, _sample_from_probs(p_probs[k], u_extra)


def default_prompt_buckets(seq_len: int) -> List[int]:
    """Power-of-two prompt-length buckets up to seq_len."""
    buckets, b = [], 32
    while b < seq_len:
        buckets.append(b)
        b *= 2
    buckets.append(seq_len)
    return buckets


class Generator:
    """Compiled prefill + decode loop over a GPT-family model.

    Shape bucketing (ref wrapper_1d.py intent): prompts are right-padded
    to a fixed bucket ladder, so serving traffic with arbitrary prompt
    lengths compiles exactly one prefill per (batch, bucket) pair and one
    decode per batch — not one pair per request shape.  Right padding is
    safe because the causal mask bounds attention to positions < the
    per-row write index, and each decode step overwrites the padded
    garbage at its position before that position ever becomes attendable.
    Mixed prompt lengths share one batch via per-row KV-cache indices.
    ``prefill_traces`` / ``decode_traces`` count actual retraces so tests
    can hold the bucketing to its promise.
    """

    def __init__(self, model: GPTModel, params, config: GPTConfig,
                 batch_size: int = 1,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 parallel_method: Optional[Any] = None,
                 prefill_chunk: Optional[int] = None):
        """``parallel_method``: optional alpa_tpu ParallelMethod for the
        prefill/decode executables — e.g. ``PipeshardParallel(
        pipeline_schedule="inference")`` with a layer-marked model config
        gives pipelined inference with per-stage-resident KV caches (ref
        get_pipeshard_executable, opt_model.py:770); cache outputs keep
        their stage placement so the next decode's device_put is a no-op.

        ``prefill_chunk``: CHUNKED prefill — prompts stream through the
        cached decode-style path in fixed-size chunks, so ONE compiled
        step serves every prompt length (no bucket ladder, no per-bucket
        compiles; the long-context serving mode).  Positions enter via
        the cache write index, so it applies to every decoder family.
        """
        self.model = model
        self.params = params
        self.config = config
        self.batch_size = batch_size
        self.prompt_buckets = sorted(prompt_buckets or
                                     default_prompt_buckets(config.seq_len))
        self.prefill_traces = 0
        self.decode_traces = 0
        # MoE capacity hazard: bucket pads enter routing and can steal
        # expert capacity from real tokens below the no-drop regime
        # (see MoELMModel docstring)
        cap = getattr(config, "capacity_factor", None)
        n_exp = getattr(config, "num_experts", None)
        if cap is not None and n_exp is not None and cap < n_exp:
            logger.warning(
                "serving an MoE config with capacity_factor (%s) < "
                "num_experts (%s): padded prefill tokens can steal "
                "expert capacity and change real tokens' logits — use "
                "capacity_factor >= num_experts for exact serving", cap,
                n_exp)

        def prefill(params, input_ids, caches, lengths):
            self.prefill_traces += 1
            b, s = input_ids.shape
            pos = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
            logits, caches = model.apply(params, input_ids, pos, caches)
            last = logits[jnp.arange(b), lengths - 1]
            # per-row cache indices: each row continues at its own length
            caches = [(kc, vc, lengths) for (kc, vc, _i) in caches]
            return last, caches

        def decode(params, token, index, caches):
            self.decode_traces += 1
            pos = index[:, None]
            logits, caches = model.apply(params, token, pos, caches)
            return logits[:, 0, :], caches

        self.prefill_chunk = prefill_chunk
        self._parallel_method = parallel_method

        def chunk_prefill(params, ids_chunk, lengths, caches, last):
            """One fixed-shape chunk through the cached path.  The
            chunk's absolute start position rides the caches' scalar
            write index; ``last`` accumulates each row's final-token
            logits from whichever chunk contains position length-1."""
            self.prefill_traces += 1
            b, c = ids_chunk.shape
            start = caches[0][2]                     # scalar chunk start
            pos = start + jax.lax.broadcasted_iota(jnp.int32, (b, c), 1)
            logits, caches = model.apply(params, ids_chunk, pos, caches)
            off = lengths - 1 - start                # (B,)
            hit = (off >= 0) & (off < c)
            sel = logits[jnp.arange(b), jnp.clip(off, 0, c - 1)]
            last = jnp.where(hit[:, None], sel, last)
            return last, caches

        if parallel_method is not None:
            import alpa_tpu
            self._prefill = alpa_tpu.parallelize(
                prefill, method=parallel_method, donate_argnums=())
            self._decode = alpa_tpu.parallelize(
                decode, method=parallel_method, donate_argnums=())
            self._chunk_prefill = alpa_tpu.parallelize(
                chunk_prefill, method=parallel_method, donate_argnums=())
        else:
            self._prefill = jax.jit(prefill)
            self._decode = jax.jit(decode)
            self._chunk_prefill = jax.jit(chunk_prefill)
        # beam-search KV-cache gather, compiled once (per cache shapes)
        self._reorder = jax.jit(
            lambda caches, idx: jax.tree_util.tree_map(
                lambda x: jnp.take(x, idx, axis=0)
                if hasattr(x, "ndim") and x.ndim > 0 else x, caches))

    def _run_bucketed_prefill(self, prompts, lengths_j, b):
        """Classic bucketed prefill: right-pad to the bucket ladder (one
        compile per bucket).  The single shared implementation for
        generate and speculative decoding."""
        bucket = self._bucket_len(int(max(len(p) for p in prompts)))
        ids = np.zeros((b, bucket), np.int32)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = p
        caches = init_kv_caches(self.config, b)
        return self._prefill(self.params, jnp.asarray(ids), caches,
                             lengths_j)

    def _run_chunked_prefill(self, prompts, lengths_j, b, caches=None,
                             start=0, init_last=None):
        """Stream the prompts through the fixed-shape chunk step: one
        compile covers every prompt length.

        ``caches``/``start``: continue from precomputed K/V (prefix
        caching) — ``prompts`` are then suffixes written from position
        ``start``, and ``lengths_j`` are TOTAL lengths (prefix+suffix).
        ``init_last`` seeds the final-logit accumulator (the prefix's
        last-token logits, so empty suffixes keep them).
        """
        c = self.prefill_chunk
        s_max = int(max(len(p) for p in prompts))
        if s_max == 0 and caches is not None:
            # all suffixes empty: nothing to prefill — the prefix's
            # last_logits (init_last) already seed decode
            caches = [(kc, vc, lengths_j) for (kc, vc, _i) in caches]
            return init_last, caches
        n_chunks = max(1, -(-s_max // c))
        if start + n_chunks * c > self.config.seq_len:
            # hard error (not assert): under -O a clamped cache write
            # would silently corrupt earlier tokens' K/V
            raise ValueError(
                f"chunked prefill of {s_max} tokens at offset {start} "
                f"pads to {start + n_chunks * c}, exceeding the KV "
                f"capacity (seq_len {self.config.seq_len}); use a chunk "
                f"size dividing seq_len or a shorter prompt")
        ids = np.zeros((b, n_chunks * c), np.int32)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = p
        if caches is None:
            caches = init_kv_caches(self.config, b)   # scalar index 0
        if init_last is None:
            init_last = jnp.zeros((b, self.config.vocab_size),
                                  self.config.dtype)
        last = init_last
        for ci in range(n_chunks):
            chunk = jnp.asarray(ids[:, ci * c:(ci + 1) * c])
            last, caches = self._chunk_prefill(self.params, chunk,
                                               lengths_j, caches, last)
        # per-row decode positions take over from the scalar chunk index
        caches = [(kc, vc, lengths_j) for (kc, vc, _i) in caches]
        return last, caches

    def cache_prefix(self, prefix_ids) -> "PrefixHandle":
        """Precompute KV for a shared prefix (system prompt caching).
        Chunked mode only — the chunk step is what lets suffixes resume
        at an arbitrary cache offset with one compile."""
        if not self.prefill_chunk:
            raise ValueError(
                "cache_prefix requires Generator(prefill_chunk=...)")
        p = np.asarray(prefix_ids, np.int32).reshape(-1)
        lengths = jnp.asarray([len(p)], jnp.int32)
        last, caches = self._run_chunked_prefill([p], lengths, 1)
        # restore the SCALAR index (suffix chunks continue from here)
        caches = [(kc, vc, jnp.int32(len(p))) for (kc, vc, _i) in caches]
        return PrefixHandle(caches=caches, length=len(p),
                            last_logits=last, params=self.params)

    def _bucket_len(self, n: int) -> int:
        for b in self.prompt_buckets:
            if b >= n:
                return b
        raise ValueError(f"prompt length {n} exceeds the largest bucket "
                         f"{self.prompt_buckets[-1]}")

    def generate(self,
                 input_ids,
                 generation_config: Optional[GenerationConfig] = None,
                 rng: Optional[jax.Array] = None,
                 prefix: Optional["PrefixHandle"] = None
                 ) -> List[np.ndarray]:
        """Generate for a batch of (possibly mixed-length) prompts.

        ``input_ids``: (B, S) array, or a list of 1-D prompts of varying
        lengths.  Uniform-length batches return a (B, S + T) array with
        finished rows eos-padded; mixed-length batches return a list of B
        1-D arrays (prompt + generation, truncated at eos).

        ``prefix``: a ``cache_prefix`` handle — the prefix's KV is
        broadcast across the batch and only each request's SUFFIX
        (``input_ids``) is prefilled; returned rows contain suffix +
        generation (the caller already has the prefix tokens).
        """
        cfg = generation_config or GenerationConfig()
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        if isinstance(input_ids, (list, tuple)):
            prompts = [np.asarray(p, np.int32).reshape(-1)
                       for p in input_ids]
        else:
            arr = np.asarray(input_ids, np.int32)
            if arr.ndim == 1:
                arr = arr[None]
            prompts = list(arr)
        b = len(prompts)
        plen = 0
        if prefix is not None:
            if not self.prefill_chunk:
                raise ValueError("prefix caching requires "
                                 "Generator(prefill_chunk=...)")
            if prefix.params is not self.params:
                raise ValueError("PrefixHandle was built for different "
                                 "params")
            plen = prefix.length
        lengths = np.array([plen + len(p) for p in prompts], np.int32)
        s_max = int(lengths.max())
        if s_max + cfg.max_new_tokens > self.config.seq_len:
            # hard error: under -O a stripped assert would let decode
            # write past the cache and silently corrupt the last entry
            raise ValueError(
                f"prompt {s_max} + max_new_tokens {cfg.max_new_tokens} "
                f"exceeds seq_len {self.config.seq_len}")
        lengths_j = jnp.asarray(lengths)
        if self.prefill_chunk:
            # no bucket ladder in chunked mode: any length up to the KV
            # capacity streams through the one compiled chunk step
            init = None
            init_last = None
            if prefix is not None:
                # broadcast the prefix K/V across the batch; the scalar
                # write index (== plen) rides along, and the prefix's
                # last logits seed rows whose suffix is empty
                init = [(jnp.repeat(kc, b, axis=0),
                         jnp.repeat(vc, b, axis=0), idx)
                        for (kc, vc, idx) in prefix.caches]
                init_last = jnp.repeat(prefix.last_logits, b, axis=0)
            logits, caches = self._run_chunked_prefill(
                prompts, lengths_j, b, caches=init, start=plen,
                init_last=init_last)
        else:
            logits, caches = self._run_bucketed_prefill(prompts, lengths_j,
                                                        b)
        generated = []
        finished = jnp.zeros((b,), bool)
        index = lengths_j
        for _ in range(cfg.max_new_tokens):
            rng, sub = jax.random.split(rng)
            nxt = _sample_logits(logits, sub, cfg).astype(jnp.int32)
            if cfg.eos_token_id is not None:
                nxt = jnp.where(finished, cfg.eos_token_id, nxt)
                finished = finished | (nxt == cfg.eos_token_id)
            generated.append(nxt)
            logits, caches = self._decode(self.params, nxt[:, None], index,
                                          caches)
            index = index + 1
            if cfg.eos_token_id is not None and bool(finished.all()):
                break
        gen = np.stack([np.asarray(g) for g in generated], axis=1) \
            if generated else np.zeros((b, 0), np.int32)
        if len(set(lengths.tolist())) == 1:
            # uniform prompts: 2-D (B, S + T) result, finished rows padded
            # with eos (classic HF-style batch output)
            return np.concatenate([np.stack(prompts), gen], axis=1)
        # mixed lengths: one 1-D row per prompt, truncated at its eos
        outs = []
        for i, p in enumerate(prompts):
            row = gen[i]
            if cfg.eos_token_id is not None:
                hits = np.nonzero(row == cfg.eos_token_id)[0]
                if hits.size:
                    row = row[:hits[0] + 1]
            outs.append(np.concatenate([p, row]))
        return outs


    def generate_speculative(self,
                             draft: "Generator",
                             input_ids,
                             generation_config: Optional[
                                 GenerationConfig] = None,
                             num_draft: int = 4,
                             seed: int = 0):
        """Speculative decoding: ``draft`` (a small Generator over the
        same tokenizer) proposes ``num_draft`` tokens per round; this
        (target) model verifies them in ONE cached forward.

        Exactness: greedy mode accepts the agreeing argmax prefix and
        provably emits the same sequence as plain greedy decoding of the
        target.  With ``cfg.do_sample`` the proposals are sampled from
        the draft's (warped) distribution and accepted by rejection
        sampling (``speculative_accept``), which makes every emitted
        token EXACTLY target-distributed — speculation changes only how
        many target forwards it takes.  Cache rollback after a rejection
        is free under the cache-as-invars design: garbage K/V beyond the
        write index is masked, so rollback is just resetting the index.
        ``seed`` drives the sampled path's host-side randomness.
        Returns (output_row, stats) where stats has ``rounds`` /
        ``proposed`` / ``accepted``.
        """
        cfg = generation_config or GenerationConfig()
        np_rng = np.random.default_rng(seed)
        prompt = np.asarray(input_ids, np.int32).reshape(-1)
        k = int(num_draft)
        if k < 1:
            raise ValueError(f"num_draft must be >= 1, got {k}")
        if len(prompt) + cfg.max_new_tokens > self.config.seq_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens "
                f"{cfg.max_new_tokens} exceeds seq_len "
                f"{self.config.seq_len}")
        if len(prompt) + cfg.max_new_tokens > draft.config.seq_len:
            # a too-small draft cache would overrun silently: proposals
            # degrade to garbage and acceptance collapses with no error
            raise ValueError(
                f"draft seq_len {draft.config.seq_len} cannot hold "
                f"prompt {len(prompt)} + max_new_tokens "
                f"{cfg.max_new_tokens}")

        def pick_target(logits):
            """Next token from target logits: argmax, or a warped draw."""
            if not cfg.do_sample:
                return int(np.argmax(np.asarray(logits)[0]))
            p = _warp_probs_np(np.asarray(logits)[0], cfg)
            return _sample_from_probs(p, np_rng.uniform())

        t_logits, t_caches = self._spec_prefill(self, prompt)
        d_logits, d_caches = self._spec_prefill(draft, prompt)
        del d_logits

        pending = pick_target(t_logits)
        generated = [pending]
        stats = {"rounds": 0, "proposed": 0, "accepted": 0}
        eos = cfg.eos_token_id
        while len(generated) < cfg.max_new_tokens and \
                (eos is None or pending != eos):
            # shrink the round near the KV capacity so the verify write
            # (k_r + 1 tokens incl. a bonus slot) always fits — greedy
            # exactness must hold all the way to the cache edge
            idx = int(np.asarray(t_caches[0][2])[0])
            cap = min(self.config.seq_len, draft.config.seq_len)
            k_r = min(k, cap - idx - 1,
                      cfg.max_new_tokens - len(generated))
            if k_r < 1:
                # no room for a proposal round: plain single decode
                t_logits, t_caches = self._decode(
                    self.params, jnp.asarray([[pending]], jnp.int32),
                    t_caches[0][2], t_caches)
                pending = pick_target(t_logits)
                generated.append(pending)
                continue
            # draft proposes k_r tokens (k_r+1 decodes: the last feed
            # keeps the draft cache in lockstep with the verify write)
            props, q_rows = [], []
            tok = pending
            for _ in range(k_r):
                d_logits, d_caches = draft._decode(
                    draft.params, jnp.asarray([[tok]], jnp.int32),
                    d_caches[0][2], d_caches)
                if cfg.do_sample:
                    q = _warp_probs_np(np.asarray(d_logits)[0], cfg)
                    q_rows.append(q)
                    tok = _sample_from_probs(q, np_rng.uniform())
                else:
                    tok = int(np.argmax(np.asarray(d_logits)[0]))
                props.append(tok)
            _discard, d_caches = draft._decode(
                draft.params, jnp.asarray([[props[-1]]], jnp.int32),
                d_caches[0][2], d_caches)

            # target verifies [pending, p1..p_{k_r}] in one forward
            verify = self._get_verify_step(k_r + 1)
            toks = jnp.asarray([[pending] + props], jnp.int32)
            v_logits, t_caches = verify(self.params, toks,
                                        t_caches[0][2], t_caches)
            if cfg.do_sample:
                p_rows = _warp_probs_np(np.asarray(v_logits)[0], cfg)
                a, extra = speculative_accept(
                    props, np.stack(q_rows), p_rows,
                    np_rng.uniform(size=k_r), np_rng.uniform())
                emitted = props[:a] + [extra]
            else:
                t_preds = np.argmax(np.asarray(v_logits)[0], axis=-1)
                a = 0
                while a < k_r and t_preds[a] == props[a]:
                    a += 1
                emitted = props[:a] + [int(t_preds[a] if a < k_r
                                           else t_preds[k_r])]
            stats["rounds"] += 1
            stats["proposed"] += k_r
            stats["accepted"] += a

            # rollback: confirmed this round = pending + a proposals
            conf = 1 + a
            t_caches = [(kc, vc, idx2 - (k_r + 1) + conf)
                        for (kc, vc, idx2) in t_caches]
            d_caches = [(kc, vc, idx2 - (k_r + 1) + conf)
                        for (kc, vc, idx2) in d_caches]
            for t in emitted:
                generated.append(t)
                if eos is not None and t == eos:
                    break
            pending = generated[-1]

        gen = np.asarray(generated[:cfg.max_new_tokens], np.int32)
        if eos is not None:
            hits = np.nonzero(gen == eos)[0]
            if hits.size:
                gen = gen[:hits[0] + 1]
        return np.concatenate([prompt, gen]), stats

    @staticmethod
    def _spec_prefill(gen: "Generator", prompt):
        lengths = jnp.asarray([len(prompt)], jnp.int32)
        if gen.prefill_chunk:
            return gen._run_chunked_prefill([prompt], lengths, 1)
        return gen._run_bucketed_prefill([prompt], lengths, 1)

    def _get_verify_step(self, s: int):
        """Compiled multi-token cached forward (the verify leg): writes
        ``s`` tokens at the per-row index and returns all logits.
        Compiled through the Generator's parallel method when one is set
        (same placement as prefill/decode — the caches stay sharded)."""
        cached = getattr(self, "_verify_steps", None)
        if cached is None:
            cached = self._verify_steps = {}
        if s not in cached:
            model = self.model

            def verify(params, toks, index, caches):
                b, sl = toks.shape
                pos = index[:, None] + jax.lax.broadcasted_iota(
                    jnp.int32, (b, sl), 1)
                return model.apply(params, toks, pos, caches)

            if self._parallel_method is not None:
                import alpa_tpu
                cached[s] = alpa_tpu.parallelize(
                    verify, method=self._parallel_method,
                    donate_argnums=())
            else:
                cached[s] = jax.jit(verify)
        return cached[s]

    def generate_beam(self,
                      input_ids: np.ndarray,
                      num_beams: int = 4,
                      max_new_tokens: int = 32,
                      length_penalty: float = 1.0,
                      eos_token_id: Optional[int] = None) -> np.ndarray:
        """Beam search for a single prompt (B=1).

        KV caches are replicated per beam and reordered after every step
        with a compiled gather — the analog of the reference's
        ``get_index_select_mesh_executable`` beam-cache reordering
        (ref mesh_executable.py:1168 / wrapper.py:20).
        """
        input_ids = jnp.asarray(input_ids, jnp.int32)
        if input_ids.ndim == 1:
            input_ids = input_ids[None]
        assert input_ids.shape[0] == 1, "beam search takes one prompt"
        s = input_ids.shape[1]
        if s + max_new_tokens > self.config.seq_len:
            raise ValueError(
                f"prompt {s} + max_new_tokens {max_new_tokens} exceeds "
                f"seq_len {self.config.seq_len}")

        # Prefill ONCE (B=1), then broadcast logits + caches across the
        # beam axis — K-times cheaper than prefilling identical copies.
        # Chunked mode keeps its one-compile contract here too.
        if self.prefill_chunk:
            logits1, caches1 = self._run_chunked_prefill(
                [np.asarray(input_ids[0])],
                jnp.full((1,), s, jnp.int32), 1)
        else:
            caches1 = init_kv_caches(self.config, 1)
            logits1, caches1 = self._prefill(self.params, input_ids,
                                             caches1,
                                             jnp.full((1,), s, jnp.int32))
        beams = jnp.repeat(input_ids, num_beams, axis=0)     # (K, S)
        logits = jnp.repeat(logits1, num_beams, axis=0)
        caches = jax.tree_util.tree_map(
            lambda x: jnp.repeat(x, num_beams, axis=0)
            if hasattr(x, "ndim") and x.ndim > 0 else x, caches1)
        scores = jnp.where(jnp.arange(num_beams) == 0, 0.0, -1e9)
        finished = jnp.zeros((num_beams,), bool)
        # generated length per beam, frozen at its eos
        gen_len = jnp.zeros((num_beams,), jnp.float32)

        index = s
        for t in range(max_new_tokens):
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            v = logp.shape[-1]
            cand = scores[:, None] + jnp.where(
                finished[:, None], jnp.where(
                    jnp.arange(v)[None] == (eos_token_id or 0), 0.0, -1e9),
                logp)                                        # (K, V)
            flat = cand.reshape(-1)
            top_scores, top_idx = jax.lax.top_k(flat, num_beams)
            beam_idx = top_idx // v
            tok_idx = (top_idx % v).astype(jnp.int32)
            beams = jnp.take(beams, beam_idx, axis=0)
            beams = jnp.concatenate([beams, tok_idx[:, None]], axis=1)
            scores = top_scores
            finished = jnp.take(finished, beam_idx)
            gen_len = jnp.take(gen_len, beam_idx)
            # beams running at the START of this step count this token
            # (including an EOS, matching the standard length convention)
            gen_len = jnp.where(finished, gen_len, gen_len + 1.0)
            if eos_token_id is not None:
                finished = finished | (tok_idx == eos_token_id)
            last_step = (t == max_new_tokens - 1) or (
                eos_token_id is not None and bool(finished.all()))
            if last_step:
                break
            caches = self._reorder(caches, beam_idx)
            logits, caches = self._decode(
                self.params, tok_idx[:, None],
                jnp.full((num_beams,), index, jnp.int32), caches)
            index += 1
        # best beam by length-normalized score (per-beam generated length)
        norm = scores / (jnp.maximum(gen_len, 1.0)**length_penalty)
        best = int(jnp.argmax(norm))
        return np.asarray(beams[best:best + 1])


def get_model(name_or_config,
              params=None,
              batch_size: int = 1,
              rngkey=None) -> Generator:
    """Build a servable Generator (ref wrapper.py:501 get_model).

    ``name_or_config``: a GPTConfig, or a ladder name like "gpt-125M" /
    "opt-2.7b" (random-initialized — weight loading plugs in via
    ``params``; HF checkpoints via ``serve.get_hf_model``).
    """
    from alpa_tpu.model.gpt_model import (config_from_opt_spec,
                                          config_from_spec, init_gpt_real)

    if isinstance(name_or_config, GPTConfig):
        config = name_or_config
    else:
        name = str(name_or_config)
        if name.lower().startswith("opt"):
            config = config_from_opt_spec(name)
        else:
            config = config_from_spec(name.split("-")[-1])
    model = GPTModel(config)
    if params is None:
        model, params = init_gpt_real(config, batch_size, rngkey)
    return Generator(model, params, config, batch_size)
