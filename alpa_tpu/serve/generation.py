"""Autoregressive generation with resident KV caches.

Analog of ref ``examples/llm_serving/model/wrapper.py:501`` (``get_model``,
the HF-GenerationMixin-compatible wrapper): prefill + decode executables
compiled once, KV caches living on device between steps (ref
``init_cache_dis_array`` opt_model.py:1044 — here plain sharded jax.Arrays
threaded through the jitted step, ref cache-as-invars design).

Supports greedy / temperature / top-k sampling, batched requests, and a
pluggable parallel method (ShardParallel on one mesh today; the pipeshard
inference schedule slots in via the same executable interface).
"""
import dataclasses
import logging
from functools import partial
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu.model.gpt_model import GPTConfig, GPTModel, init_kv_caches

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    temperature: float = 1.0
    top_k: int = 0           # 0 = no top-k filtering
    do_sample: bool = False
    eos_token_id: Optional[int] = None


def _sample_logits(logits, rng, cfg: GenerationConfig):
    logits = logits.astype(jnp.float32)
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1)
    if cfg.temperature != 1.0:
        logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        top = jax.lax.top_k(logits, cfg.top_k)[0][..., -1:]
        logits = jnp.where(logits < top, -1e9, logits)
    return jax.random.categorical(rng, logits, axis=-1)


class Generator:
    """Compiled prefill + decode loop over a GPT-family model."""

    def __init__(self, model: GPTModel, params, config: GPTConfig,
                 batch_size: int = 1):
        self.model = model
        self.params = params
        self.config = config
        self.batch_size = batch_size

        def prefill(params, input_ids, caches):
            b, s = input_ids.shape
            pos = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
            logits, caches = model.apply(params, input_ids, pos, caches)
            return logits[:, -1, :], caches

        def decode(params, token, index, caches):
            b = token.shape[0]
            pos = jnp.full((b, 1), index, jnp.int32)
            logits, caches = model.apply(params, token, pos, caches)
            return logits[:, 0, :], caches

        self._prefill = jax.jit(prefill)
        self._decode = jax.jit(decode)
        # beam-search KV-cache gather, compiled once (per cache shapes)
        self._reorder = jax.jit(
            lambda caches, idx: jax.tree_util.tree_map(
                lambda x: jnp.take(x, idx, axis=0)
                if hasattr(x, "ndim") and x.ndim > 0 else x, caches))

    def generate(self,
                 input_ids: np.ndarray,
                 generation_config: Optional[GenerationConfig] = None,
                 rng: Optional[jax.Array] = None) -> np.ndarray:
        """input_ids: (B, S_prompt) -> (B, S_prompt + max_new_tokens)."""
        cfg = generation_config or GenerationConfig()
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, s = input_ids.shape
        assert s + cfg.max_new_tokens <= self.config.seq_len, (
            f"prompt {s} + max_new_tokens {cfg.max_new_tokens} exceeds "
            f"seq_len {self.config.seq_len}")

        caches = init_kv_caches(self.config, b)
        logits, caches = self._prefill(self.params, input_ids, caches)
        tokens = [input_ids]
        finished = jnp.zeros((b,), bool)
        index = s
        for i in range(cfg.max_new_tokens):
            rng, sub = jax.random.split(rng)
            nxt = _sample_logits(logits, sub, cfg).astype(jnp.int32)
            if cfg.eos_token_id is not None:
                nxt = jnp.where(finished, cfg.eos_token_id, nxt)
                finished = finished | (nxt == cfg.eos_token_id)
            tokens.append(nxt[:, None])
            logits, caches = self._decode(self.params, nxt[:, None], index,
                                          caches)
            index += 1
            if cfg.eos_token_id is not None and bool(finished.all()):
                break
        return np.asarray(jnp.concatenate(tokens, axis=1))


    def generate_beam(self,
                      input_ids: np.ndarray,
                      num_beams: int = 4,
                      max_new_tokens: int = 32,
                      length_penalty: float = 1.0,
                      eos_token_id: Optional[int] = None) -> np.ndarray:
        """Beam search for a single prompt (B=1).

        KV caches are replicated per beam and reordered after every step
        with a compiled gather — the analog of the reference's
        ``get_index_select_mesh_executable`` beam-cache reordering
        (ref mesh_executable.py:1168 / wrapper.py:20).
        """
        input_ids = jnp.asarray(input_ids, jnp.int32)
        if input_ids.ndim == 1:
            input_ids = input_ids[None]
        assert input_ids.shape[0] == 1, "beam search takes one prompt"
        s = input_ids.shape[1]
        assert s + max_new_tokens <= self.config.seq_len

        # Prefill ONCE (B=1), then broadcast logits + caches across the
        # beam axis — K-times cheaper than prefilling identical copies.
        caches1 = init_kv_caches(self.config, 1)
        logits1, caches1 = self._prefill(self.params, input_ids, caches1)
        beams = jnp.repeat(input_ids, num_beams, axis=0)     # (K, S)
        logits = jnp.repeat(logits1, num_beams, axis=0)
        caches = jax.tree_util.tree_map(
            lambda x: jnp.repeat(x, num_beams, axis=0)
            if hasattr(x, "ndim") and x.ndim > 0 else x, caches1)
        scores = jnp.where(jnp.arange(num_beams) == 0, 0.0, -1e9)
        finished = jnp.zeros((num_beams,), bool)
        # generated length per beam, frozen at its eos
        gen_len = jnp.zeros((num_beams,), jnp.float32)

        index = s
        for t in range(max_new_tokens):
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            v = logp.shape[-1]
            cand = scores[:, None] + jnp.where(
                finished[:, None], jnp.where(
                    jnp.arange(v)[None] == (eos_token_id or 0), 0.0, -1e9),
                logp)                                        # (K, V)
            flat = cand.reshape(-1)
            top_scores, top_idx = jax.lax.top_k(flat, num_beams)
            beam_idx = top_idx // v
            tok_idx = (top_idx % v).astype(jnp.int32)
            beams = jnp.take(beams, beam_idx, axis=0)
            beams = jnp.concatenate([beams, tok_idx[:, None]], axis=1)
            scores = top_scores
            finished = jnp.take(finished, beam_idx)
            gen_len = jnp.take(gen_len, beam_idx)
            # beams running at the START of this step count this token
            # (including an EOS, matching the standard length convention)
            gen_len = jnp.where(finished, gen_len, gen_len + 1.0)
            if eos_token_id is not None:
                finished = finished | (tok_idx == eos_token_id)
            last_step = (t == max_new_tokens - 1) or (
                eos_token_id is not None and bool(finished.all()))
            if last_step:
                break
            caches = self._reorder(caches, beam_idx)
            logits, caches = self._decode(self.params, tok_idx[:, None],
                                          index, caches)
            index += 1
        # best beam by length-normalized score (per-beam generated length)
        norm = scores / (jnp.maximum(gen_len, 1.0)**length_penalty)
        best = int(jnp.argmax(norm))
        return np.asarray(beams[best:best + 1])


def get_model(name_or_config,
              params=None,
              batch_size: int = 1,
              rngkey=None) -> Generator:
    """Build a servable Generator (ref wrapper.py:501 get_model).

    ``name_or_config``: a GPTConfig, or a ladder name like "gpt-125M"
    (random-initialized — weight loading plugs in via ``params``).
    """
    from alpa_tpu.model.gpt_model import config_from_spec, init_gpt_real

    if isinstance(name_or_config, GPTConfig):
        config = name_or_config
    else:
        spec = str(name_or_config).split("-")[-1]
        config = config_from_spec(spec)
    model = GPTModel(config)
    if params is None:
        model, params = init_gpt_real(config, batch_size, rngkey)
    return Generator(model, params, config, batch_size)
