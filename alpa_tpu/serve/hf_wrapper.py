"""HuggingFace-compatible inference wrapper.

Analog of ref ``examples/llm_serving/model/wrapper.py:501`` (``get_model``
returning an HF ``GenerationMixin``-compatible object): an HF user calls
``model.generate(input_ids=..., max_new_tokens=..., do_sample=...,
num_beams=...)`` exactly as with ``transformers`` and gets token arrays
back, while prefill/decode run as compiled alpa_tpu executables with
resident KV caches (greedy / sampling / beam search all ride the
``Generator``'s bucketed executables).
"""
import dataclasses
import logging
from typing import Any, Optional

import numpy as np

from alpa_tpu.serve.generation import GenerationConfig, Generator

logger = logging.getLogger(__name__)


class WrappedInferenceModel:
    """Duck-typed HF model front: ``generate`` + ``config`` (ref
    WrappedInferenceFunc, wrapper.py:70)."""

    def __init__(self, generator: Generator, eos_token_id: Optional[int] = None):
        self.generator = generator
        self.eos_token_id = eos_token_id
        self.config = generator.config

    def generate(self,
                 input_ids=None,
                 attention_mask=None,
                 max_new_tokens: int = 32,
                 max_length: Optional[int] = None,
                 do_sample: bool = False,
                 temperature: float = 1.0,
                 top_k: int = 0,
                 num_beams: int = 1,
                 length_penalty: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: Optional[int] = None,
                 seed: Optional[int] = None,
                 **unused_kwargs) -> np.ndarray:
        """HF-``GenerationMixin``-shaped generate.

        ``input_ids``: (B, S) int array (torch tensors accepted).
        ``attention_mask``: optional (B, S) 1/0 — right-padded rows decode
        from their true lengths (mixed-length batching).
        Returns (B, S + T) int array like ``transformers``.
        """
        if unused_kwargs:
            logger.warning("generate: ignoring unsupported kwargs %s",
                           sorted(unused_kwargs))
        ids = _to_numpy(input_ids)
        if ids.ndim == 1:
            ids = ids[None]
        eos = eos_token_id if eos_token_id is not None else self.eos_token_id
        if max_length is not None:
            max_new_tokens = max(0, max_length - ids.shape[1])

        if num_beams > 1:
            assert ids.shape[0] == 1, (
                "beam search supports batch size 1 (ref wrapper.py beam "
                "path)")
            if attention_mask is not None:
                # trim trailing pads so the beam never conditions on them
                n = int(_to_numpy(attention_mask).astype(np.int64).sum())
                ids = ids[:, :n]
            return self.generator.generate_beam(
                ids, num_beams=num_beams, max_new_tokens=max_new_tokens,
                length_penalty=length_penalty, eos_token_id=eos)

        cfg = GenerationConfig(max_new_tokens=max_new_tokens,
                               do_sample=do_sample, temperature=temperature,
                               top_k=top_k, eos_token_id=eos)
        import jax
        if seed is None:
            # fresh entropy per call, matching HF GenerationMixin: repeated
            # do_sample calls on the same prompt must not repeat samples
            seed = int(np.random.SeedSequence().entropy % (2 ** 63))
        rng = jax.random.PRNGKey(seed)
        if attention_mask is not None:
            mask = _to_numpy(attention_mask)
            lengths = mask.astype(np.int64).sum(axis=1)
            prompts = [ids[i, :lengths[i]] for i in range(ids.shape[0])]
            outs = self.generator.generate(prompts, cfg, rng)
            if isinstance(outs, np.ndarray):
                return outs
            # re-pad mixed-length rows into one (B, max) matrix, HF-style
            pad = pad_token_id if pad_token_id is not None else (eos or 0)
            width = max(len(o) for o in outs)
            mat = np.full((len(outs), width), pad, np.int32)
            for i, o in enumerate(outs):
                mat[i, :len(o)] = o
            return mat
        return np.asarray(self.generator.generate(ids, cfg, rng))

    def __call__(self, input_ids, **_):
        """One forward pass returning logits (HF-model shape)."""
        import jax.numpy as jnp
        ids = jnp.asarray(_to_numpy(input_ids))
        return self.generator.model.apply(self.generator.params, ids)


def _to_numpy(t) -> np.ndarray:
    if hasattr(t, "detach"):          # torch tensor
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def get_hf_model(model_name_or_model,
                 dtype=None,
                 shardings=None,
                 eos_token_id: Optional[int] = None,
                 prompt_buckets=None) -> WrappedInferenceModel:
    """Load an HF GPT-2-family checkpoint into a servable wrapper
    (ref get_model, wrapper.py:501 + distributed loading opt_model.py:956).

    ``shardings``: optional params-pytree of NamedShardings — weights
    device_put directly into their target shards (no full replica per
    device)."""
    import jax.numpy as jnp

    from alpa_tpu.model.weight_loading import load_gpt2, load_opt

    loader = load_gpt2
    name = (model_name_or_model if isinstance(model_name_or_model, str)
            else type(model_name_or_model).__name__)
    if "opt" in name.lower():
        loader = load_opt
    model, params, config = loader(model_name_or_model,
                                   dtype=dtype or jnp.float32,
                                   shardings=shardings)
    gen = Generator(model, params, config, prompt_buckets=prompt_buckets)
    return WrappedInferenceModel(gen, eos_token_id=eos_token_id)
