"""Load-aware multi-replica router (ISSUE 11).

One :class:`Router` spreads admission across N serving replicas — each a
:class:`~alpa_tpu.serve.controller.Controller` in this process
(:class:`LocalReplicaHandle`) or a remote controller reached over HTTP
(:class:`HTTPReplicaHandle`).  Placement uses the PR 5 load signals
(queue depth, request p99, tokens in flight — ``Controller.
load_report``, also exported on every controller's ``/healthz``):

* ``least_loaded`` (default) scores each routable replica and picks the
  lightest; ``round_robin`` rotates.  Policy knob: ``router_policy``.
* Load shedding is PER-REPLICA: a saturated replica (queue depth or p99
  over the ``router_shed_*`` knobs) is routed around, and a 503
  (:class:`~alpa_tpu.fault.ServiceDegradedError`) reaches the client
  only when every healthy replica is saturated or sheds.
* Replicas whose ``/healthz`` fails ``router_health_fail_threshold``
  consecutive probes are dropped from rotation; one clean probe
  restores them (vs the RecoveryManager, which degrades ONE backend —
  the router degrades the fleet view; docs/fault_tolerance.md).
* :meth:`Router.rolling_reload` performs a rolling deploy: drain one
  replica at a time (stop placing, wait out router-tracked in-flight
  work), reload it through the existing ``/admin/reload`` hot-swap
  barrier, re-probe, restore — with >= 2 replicas, traffic never sees
  an error.
* Autoscale hooks: sustained aggregate load above/below the
  ``router_autoscale_*`` thresholds fires ``on_want_more`` /
  ``on_want_fewer`` callbacks (the operator's scale signal; the router
  itself never creates replicas).

* Disaggregated prefill/decode (ISSUE 18): replicas join with a
  ``phase`` (``prefill``/``decode``/``any``).  With ``disagg_mode=auto``
  (and both strict pools present) or ``forced``, a request is prefilled
  on the prefill pool, handed off as a content-hashed
  :class:`~alpa_tpu.serve.disagg.KVHandoffArtifact`, and decoded on the
  decode pool.  Each pool gets its own SLO steer (``disagg_ttft_slo_ms``
  for prefill, ``disagg_itl_slo_ms`` for decode inter-token p99), decode
  backlog throttles prefill admission
  (``disagg_backpressure_depth``), and no handoff is ever dropped: the
  prefill side retains every artifact until the stream's clean end is
  acked, so a decode-replica death or a corrupt wire copy re-ingests on
  a survivor (docs/serving.md#disaggregated-prefilldecode).
  ``disagg_mode=off`` is byte-identical to the monolithic path.

:class:`RouterServer` puts the same router behind HTTP (``/completions``
incl. SSE pass-through for both local and HTTP replicas, ``/healthz``
with the per-replica view, ``/metrics``,
``POST /admin/rolling_reload``).
"""
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from alpa_tpu import fault
from alpa_tpu.global_env import global_config
from alpa_tpu.serve import disagg as _disagg
from alpa_tpu.telemetry import metrics as _tmetrics

logger = logging.getLogger(__name__)

_REG = _tmetrics.get_registry()
_ROUTER_REQS = _REG.counter(
    "alpa_router_requests_total",
    "Requests routed, by replica and outcome",
    labelnames=("replica", "outcome"))
_ROUTER_QDEPTH = _REG.gauge(
    "alpa_router_replica_queue_depth",
    "Last observed queue depth per replica",
    labelnames=("replica",))


class LocalReplicaHandle:
    """In-process replica: a Controller (one or more model replicas of
    its own — the router treats the whole controller as one unit)."""

    def __init__(self, controller, model: Optional[str] = None):
        self.controller = controller
        self.model = model

    def completions(self, request: Dict[str, Any]) -> Dict[str, Any]:
        return self.controller.completions(request)

    def completions_stream(self, request: Dict[str, Any]):
        return self.controller.completions_stream(request)

    def healthz(self):
        report = self.controller.health_report()
        recovery = self.controller._recovery
        if recovery is not None:
            report["status"] = recovery.state.value
            code = 503 if report["status"] == "degraded" else 200
        else:
            code = 503 if report["status"] == "shedding" else 200
        report["load"] = self.controller.load_report()
        return code, report

    def load(self) -> Dict[str, Any]:
        return self.controller.load_report()

    def reload(self, model: str, ckpt_dir: str,
               step: Optional[int] = None) -> Dict[str, Any]:
        return self.controller.reload_model(model, ckpt_dir, step=step)

    # disaggregated prefill/decode (same surface as HTTPReplicaHandle)
    def prefill(self, request: Dict[str, Any]) -> Dict[str, Any]:
        return self.controller.disagg_prefill(request)

    def ingest(self, wire: Dict[str, Any]):
        return self.controller.disagg_ingest(wire)

    def disagg_fetch(self, request_id: str) -> Dict[str, Any]:
        return self.controller.disagg_fetch(request_id)

    def disagg_ack(self, request_id: str) -> bool:
        return self.controller.disagg_ack(request_id)


class _SSEStream:
    """Client half of the controller/router SSE wire format: iterates
    token ints from ``data: {"token": t}`` frames, raises on an error
    frame, and raises :class:`ConnectionError` when the transport dies
    before the ``done`` frame — exactly the signal the disaggregated
    failover path (and the router's health accounting) keys on."""

    def __init__(self, resp):
        self._resp = resp
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        while True:
            try:
                line = self._resp.readline()
            except (OSError, urllib.error.URLError):
                self.close()
                raise
            if not line:
                self.close()
                raise ConnectionError(
                    "SSE stream ended before its done frame")
            line = line.strip()
            if not line.startswith(b"data:"):
                continue
            evt = json.loads(line[len(b"data:"):].strip())
            if evt.get("done"):
                self.close()
                raise StopIteration
            if "error" in evt:
                self.close()
                raise RuntimeError(str(evt["error"]))
            if "token" in evt:
                return int(evt["token"])

    def close(self):
        if not self._closed:
            self._closed = True
            try:
                self._resp.close()
            except Exception:  # pylint: disable=broad-except
                pass


class HTTPReplicaHandle:
    """Remote replica behind ``http://host:port`` (a running
    ControllerServer).  Load signals ride the ``/healthz`` body."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _get(self, path: str):
        try:
            with urllib.request.urlopen(self.base_url + path,
                                        timeout=self.timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:  # pylint: disable=broad-except
                return e.code, {}

    def _post(self, path: str, payload: Dict[str, Any]):
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.base_url + path, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:  # pylint: disable=broad-except
                return e.code, {}

    def completions(self, request: Dict[str, Any]) -> Dict[str, Any]:
        code, body = self._post("/completions", request)
        if code == 503:
            raise fault.ServiceDegradedError(
                body.get("error", "replica shedding"))
        if code != 200:
            raise RuntimeError(
                f"replica {self.base_url} returned {code}: "
                f"{body.get('error')}")
        return body

    def _post_stream(self, path: str, payload: Dict[str, Any]):
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.base_url + path, data=body,
            headers={"Content-Type": "application/json"})
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            try:
                err = json.loads(e.read()).get("error", "")
            except Exception:  # pylint: disable=broad-except
                err = ""
            if e.code == 503:
                raise fault.ServiceDegradedError(
                    err or "replica shedding") from e
            if e.code == 422:
                raise _disagg.ArtifactCorruptError(
                    err or "handoff artifact rejected") from e
            raise RuntimeError(
                f"replica {self.base_url} returned {e.code}: "
                f"{err}") from e
        return _SSEStream(resp)

    def completions_stream(self, request: Dict[str, Any]):
        return self._post_stream("/completions",
                                 dict(request, stream=True))

    # disaggregated prefill/decode surface
    def prefill(self, request: Dict[str, Any]) -> Dict[str, Any]:
        code, body = self._post("/disagg/prefill", request)
        if code == 503:
            raise fault.ServiceDegradedError(
                body.get("error", "prefill replica shedding"))
        if code != 200:
            raise RuntimeError(
                f"prefill on {self.base_url} returned {code}: "
                f"{body.get('error')}")
        return body

    def ingest(self, wire: Dict[str, Any]):
        return self._post_stream("/disagg/ingest", wire)

    def disagg_fetch(self, request_id: str) -> Dict[str, Any]:
        code, body = self._post("/disagg/fetch",
                                {"request_id": request_id})
        if code != 200:
            raise KeyError(
                f"no retained artifact {request_id!r} on "
                f"{self.base_url} ({code}: {body.get('error')})")
        return body

    def disagg_ack(self, request_id: str) -> bool:
        code, body = self._post("/disagg/ack",
                                {"request_id": request_id})
        return code == 200 and bool(body.get("acked"))

    def healthz(self):
        return self._get("/healthz")

    def load(self) -> Dict[str, Any]:
        code, body = self._get("/healthz")
        if code not in (200, 503):
            raise RuntimeError(f"healthz returned {code}")
        return body.get("load", {})

    def reload(self, model: str, ckpt_dir: str,
               step: Optional[int] = None) -> Dict[str, Any]:
        payload = {"model": model, "ckpt_dir": ckpt_dir}
        if step is not None:
            payload["step"] = step
        code, body = self._post("/admin/reload", payload)
        if code != 200:
            raise RuntimeError(f"reload failed ({code}): {body}")
        return body


def _p99_ms(samples) -> Optional[float]:
    lat = sorted(samples)
    if not lat:
        return None
    return lat[int(0.99 * (len(lat) - 1))] * 1e3


class _ReplicaState:
    __slots__ = ("name", "handle", "phase", "healthy", "draining",
                 "fails", "inflight", "last_load", "latencies", "itls")

    def __init__(self, name: str, handle, phase: str = "any"):
        self.name = name
        self.handle = handle
        self.phase = phase
        self.healthy = True
        self.draining = False
        self.fails = 0
        self.inflight = 0
        self.last_load: Dict[str, Any] = {}
        self.latencies = deque(maxlen=256)
        #: router-observed inter-token gaps (disagg decode pool SLO)
        self.itls = deque(maxlen=512)

    def view(self) -> Dict[str, Any]:
        return {"healthy": self.healthy, "draining": self.draining,
                "phase": self.phase,
                "inflight": self.inflight,
                "consecutive_failures": self.fails,
                "queue_depth": self.last_load.get("queue_depth"),
                "tokens_in_flight":
                    self.last_load.get("tokens_in_flight"),
                "ttft_p99_ms": self.last_load.get("ttft_p99_ms"),
                "router_p99_ms": _p99_ms(self.latencies),
                "itl_p99_ms": _p99_ms(self.itls)}


class _RoutedStream:
    """Wraps a replica's token stream so the router's in-flight count
    (what rolling_reload drains on) covers streams end to end."""

    def __init__(self, inner, on_end: Callable[[], None]):
        self._inner = inner
        self._on_end = on_end
        self._ended = False

    def _end(self):
        if not self._ended:
            self._ended = True
            self._on_end()

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._inner)
        except BaseException:
            self._end()
            raise

    def close(self):
        try:
            self._inner.close()
        finally:
            self._end()


def _flatten_ids(x) -> List[int]:
    out: List[int] = []

    def rec(v):
        if isinstance(v, (list, tuple)):
            for e in v:
                rec(e)
        else:
            out.append(int(v))
    rec(x if x is not None else [])
    return out


class _DisaggStream:
    """A routed disaggregated stream: carries the decode replica's
    in-flight guard, observes the per-pool TTFT/ITL histograms, and —
    because the prefill side retains the artifact until the clean end
    is acked — survives a decode-replica death mid-stream by
    re-ingesting on a survivor and fast-forwarding the replay (greedy
    decode is deterministic, so the replayed prefix is checked
    token-for-token; sampled streams propagate the failure instead)."""

    def __init__(self, router, decode_st, prefill_st, wire, inner,
                 t0, do_sample):
        self._router = router
        self._dst = decode_st
        self._pst = prefill_st
        self._wire = wire
        self._inner = inner
        self._t0 = t0
        self._do_sample = do_sample
        self._emitted: List[int] = []
        self._replay = 0
        self._last = None
        self._ended = False

    def __iter__(self):
        return self

    def __next__(self):
        r = self._router
        while True:
            if self._ended:
                raise StopIteration
            try:
                t = next(self._inner)
            except StopIteration:
                self._end(ack=True)
                raise
            except (OSError, urllib.error.URLError) as e:
                self._failover(e)
                continue
            except BaseException:
                self._end(ack=False)
                raise
            if self._replay:
                k = len(self._emitted) - self._replay
                if int(t) != self._emitted[k]:
                    self._end(ack=False)
                    raise RuntimeError(
                        "re-ingested decode stream diverged from the "
                        "already-emitted prefix")
                self._replay -= 1
                continue
            now = r._clock()
            if self._last is None:
                _disagg.observe_ttft("prefill", now - self._t0)
            else:
                gap = now - self._last
                _disagg.observe_itl("decode", gap)
                self._dst.itls.append(gap)
            self._last = now
            self._emitted.append(int(t))
            return int(t)

    def _failover(self, err):
        r = self._router
        dead = self._dst
        try:
            self._inner.close()
        except Exception:  # pylint: disable=broad-except
            pass
        with r._lock:
            dead.inflight -= 1
            dead.fails += 1
            if dead.fails >= r.health_fail_threshold:
                dead.healthy = False
        _ROUTER_REQS.labels(dead.name, "error").inc()
        if self._do_sample:
            # sampled decode cannot replay deterministically; surface
            # the failure (the artifact stays retained for a manual or
            # client-driven retry)
            self._ended = True
            raise err
        logger.warning(
            "router: decode replica %s died mid-stream (%s); "
            "re-ingesting the retained handoff", dead.name, err)
        r.disagg_reingests += 1
        _disagg.count_reingest("decode_died")
        wire = self._wire
        try:
            wire = self._pst.handle.disagg_fetch(wire["request_id"])
        except Exception:  # pylint: disable=broad-except
            logger.warning(
                "router: re-fetch from the prefill side failed; using "
                "the router's in-memory copy")
        dst, inner = r._disagg_ingest(self._pst, wire,
                                      exclude={dead.name})
        self._dst, self._inner = dst, inner
        self._replay = len(self._emitted)

    def _end(self, ack: bool):
        if self._ended:
            return
        self._ended = True
        with self._router._lock:
            self._dst.inflight -= 1
        if ack:
            self._dst.fails = 0
            try:
                self._pst.handle.disagg_ack(self._wire["request_id"])
            except Exception:  # pylint: disable=broad-except
                logger.warning("router: disagg ack failed for %s",
                               self._wire.get("request_id"))

    def close(self):
        try:
            self._inner.close()
        except Exception:  # pylint: disable=broad-except
            pass
        self._end(ack=True)


class Router:
    """Spread admission across replicas; see the module docstring."""

    def __init__(self, policy: Optional[str] = None,
                 shed_queue_depth: Optional[int] = None,
                 shed_ttft_ms: Optional[float] = None,
                 health_fail_threshold: Optional[int] = None,
                 autoscale_window_s: Optional[float] = None,
                 autoscale_hi_queue: Optional[float] = None,
                 autoscale_lo_queue: Optional[float] = None,
                 disagg_mode: Optional[str] = None,
                 disagg_backpressure_depth: Optional[int] = None,
                 disagg_ttft_slo_ms: Optional[float] = None,
                 disagg_itl_slo_ms: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.policy = policy or global_config.router_policy
        if self.policy not in ("least_loaded", "round_robin"):
            raise ValueError(f"unknown router_policy {self.policy!r}")
        self.shed_queue_depth = (global_config.router_shed_queue_depth
                                 if shed_queue_depth is None
                                 else shed_queue_depth)
        self.shed_ttft_ms = (global_config.router_shed_ttft_ms
                             if shed_ttft_ms is None else shed_ttft_ms)
        self.health_fail_threshold = (
            global_config.router_health_fail_threshold
            if health_fail_threshold is None else health_fail_threshold)
        self.autoscale_window_s = (
            global_config.router_autoscale_window_s
            if autoscale_window_s is None else autoscale_window_s)
        self.autoscale_hi_queue = (
            global_config.router_autoscale_hi_queue
            if autoscale_hi_queue is None else autoscale_hi_queue)
        self.autoscale_lo_queue = (
            global_config.router_autoscale_lo_queue
            if autoscale_lo_queue is None else autoscale_lo_queue)
        self._clock = clock
        self._lock = threading.RLock()
        self._replicas: "Dict[str, _ReplicaState]" = {}
        self._rr = 0
        #: autoscale callbacks — called with (router, mean_depth)
        self.on_want_more: Optional[Callable] = None
        self.on_want_fewer: Optional[Callable] = None
        self.want_more_signals = 0
        self.want_fewer_signals = 0
        self._as_samples: "deque" = deque()
        self._as_last_fire = -float("inf")
        self.sheds = 0
        # ---- disaggregated prefill/decode (ISSUE 18) ----
        self.disagg_mode = (global_config.disagg_mode
                            if disagg_mode is None else disagg_mode)
        if self.disagg_mode not in ("off", "auto", "forced"):
            raise ValueError(
                f"unknown disagg_mode {self.disagg_mode!r}")
        self.disagg_backpressure_depth = (
            global_config.disagg_backpressure_depth
            if disagg_backpressure_depth is None
            else disagg_backpressure_depth)
        self.disagg_ttft_slo_ms = (
            global_config.disagg_ttft_slo_ms
            if disagg_ttft_slo_ms is None else disagg_ttft_slo_ms)
        self.disagg_itl_slo_ms = (
            global_config.disagg_itl_slo_ms
            if disagg_itl_slo_ms is None else disagg_itl_slo_ms)
        self.disagg_handoffs = 0
        self.disagg_reingests = 0
        self.disagg_backpressure_sheds = 0

    # ---- membership -------------------------------------------------

    def add_replica(self, name: str, handle,
                    phase: str = "any") -> None:
        if phase not in ("any", "prefill", "decode"):
            raise ValueError(f"unknown replica phase {phase!r}")
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already registered")
            self._replicas[name] = _ReplicaState(name, handle, phase)
        logger.info("router: added replica %s (phase=%s)", name, phase)

    def remove_replica(self, name: str) -> None:
        with self._lock:
            self._replicas.pop(name, None)
        logger.info("router: removed replica %s", name)

    def replica_names(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    # ---- health probing ---------------------------------------------

    def probe(self) -> Dict[str, bool]:
        """Probe every replica's ``/healthz`` once, updating rotation
        membership (call periodically, or from a prober thread)."""
        with self._lock:
            states = list(self._replicas.values())
        out = {}
        for st in states:
            try:
                code, _body = st.handle.healthz()
                ok = code == 200
            except Exception:  # pylint: disable=broad-except
                ok = False
            if ok:
                st.fails = 0
                if not st.healthy:
                    logger.info("router: replica %s recovered", st.name)
                st.healthy = True
            else:
                st.fails += 1
                if (st.healthy and
                        st.fails >= self.health_fail_threshold):
                    logger.warning(
                        "router: replica %s dropped after %d failed "
                        "probes", st.name, st.fails)
                    st.healthy = False
            out[st.name] = st.healthy
        return out

    # ---- placement --------------------------------------------------

    def _refresh_load(self, st: _ReplicaState) -> None:
        try:
            st.last_load = st.handle.load() or {}
        except Exception:  # pylint: disable=broad-except
            st.last_load = {}
        qd = st.last_load.get("queue_depth")
        if qd is not None:
            _ROUTER_QDEPTH.labels(st.name).set(int(qd))

    def _saturated(self, st: _ReplicaState) -> bool:
        qd = st.last_load.get("queue_depth") or 0
        if self.shed_queue_depth and \
                qd + st.inflight > self.shed_queue_depth:
            return True
        p99 = st.last_load.get("ttft_p99_ms")
        if self.shed_ttft_ms and p99 is not None and \
                p99 > self.shed_ttft_ms:
            return True
        return False

    def _score(self, st: _ReplicaState) -> float:
        load = st.last_load
        return (2.0 * (load.get("queue_depth") or 0) +
                2.0 * st.inflight +
                0.01 * (load.get("tokens_in_flight") or 0) +
                0.001 * (load.get("ttft_p99_ms") or 0.0))

    def _slo_violated(self, st: _ReplicaState, phase: str) -> bool:
        """Phase SLO steer: prefer replicas inside their pool's SLO
        (router-observed TTFT p99 for prefill, inter-token p99 for
        decode).  A steer, not a shed — when every candidate violates,
        least-loaded placement still proceeds."""
        if phase == "prefill" and self.disagg_ttft_slo_ms:
            p99 = _p99_ms(st.latencies)
            return p99 is not None and p99 > self.disagg_ttft_slo_ms
        if phase == "decode" and self.disagg_itl_slo_ms:
            p99 = _p99_ms(st.itls)
            return p99 is not None and p99 > self.disagg_itl_slo_ms
        return False

    def _pick(self, exclude,
              phase: Optional[str] = None) -> Optional[_ReplicaState]:
        with self._lock:
            cands = [st for st in self._replicas.values()
                     if st.healthy and not st.draining
                     and st.name not in exclude
                     and (phase is None or
                          st.phase in ("any", phase))]
        for st in cands:
            self._refresh_load(st)
        cands = [st for st in cands if not self._saturated(st)]
        if phase is not None and cands:
            inside_slo = [st for st in cands
                          if not self._slo_violated(st, phase)]
            cands = inside_slo or cands
        if not cands:
            return None
        if self.policy == "round_robin":
            with self._lock:
                self._rr += 1
                return cands[self._rr % len(cands)]
        return min(cands, key=self._score)

    # ---- request paths ----------------------------------------------

    def submit(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Route one completion request, failing over across replicas.
        Raises ServiceDegradedError (HTTP 503) only when no routable
        replica remains un-saturated."""
        if self._disagg_active():
            stream = self._submit_disagg_stream(request)
            toks = list(stream)
            return {"output_ids":
                    [_flatten_ids(request.get("prompt_ids")) + toks]}
        excluded: set = set()
        self._observe_autoscale()
        while True:
            st = self._pick(excluded)
            if st is None:
                self.sheds += 1
                _ROUTER_REQS.labels("none", "shed").inc()
                raise fault.ServiceDegradedError(
                    "no replica can take the request (all saturated, "
                    "draining, or unhealthy)")
            with self._lock:
                st.inflight += 1
            tic = self._clock()
            try:
                out = st.handle.completions(request)
            except fault.ServiceDegradedError:
                # THIS replica sheds; others may still have room
                _ROUTER_REQS.labels(st.name, "shed").inc()
                excluded.add(st.name)
                continue
            except (OSError, urllib.error.URLError) as e:
                # transport-level failure: count toward health, fail over
                _ROUTER_REQS.labels(st.name, "error").inc()
                with self._lock:
                    st.fails += 1
                    if st.fails >= self.health_fail_threshold:
                        st.healthy = False
                logger.warning("router: replica %s errored (%s); "
                               "failing over", st.name, e)
                excluded.add(st.name)
                continue
            except Exception:
                # request-level error (bad model, bad payload): the
                # client's fault — do not burn other replicas on it
                _ROUTER_REQS.labels(st.name, "error").inc()
                raise
            finally:
                with self._lock:
                    st.inflight -= 1
            st.fails = 0
            st.latencies.append(self._clock() - tic)
            _ROUTER_REQS.labels(st.name, "ok").inc()
            return out

    def submit_stream(self, request: Dict[str, Any]):
        """Route a streaming request (local or HTTP replicas).  The
        stream counts as in-flight until exhausted or closed, so rolling
        deploys drain it before touching its replica."""
        if self._disagg_active():
            return self._submit_disagg_stream(request)
        self._observe_autoscale()
        st = self._pick(set())
        if st is None:
            self.sheds += 1
            _ROUTER_REQS.labels("none", "shed").inc()
            raise fault.ServiceDegradedError(
                "no replica can take the stream")
        with self._lock:
            st.inflight += 1
        try:
            inner = st.handle.completions_stream(request)
        except BaseException:
            with self._lock:
                st.inflight -= 1
            _ROUTER_REQS.labels(st.name, "error").inc()
            raise
        _ROUTER_REQS.labels(st.name, "ok").inc()

        def on_end():
            with self._lock:
                st.inflight -= 1
        return _RoutedStream(inner, on_end)

    # ---- disaggregated prefill/decode -------------------------------

    def _disagg_active(self) -> bool:
        """Whether requests take the split prefill/decode path.
        ``off`` short-circuits before touching any disagg state, so the
        monolithic path is byte-identical to a router without this
        feature."""
        mode = self.disagg_mode
        if mode == "off":
            return False
        if mode == "forced":
            return True
        with self._lock:
            phases = {st.phase for st in self._replicas.values()
                      if st.healthy}
        return "prefill" in phases and "decode" in phases

    def _decode_pool_depth(self) -> int:
        with self._lock:
            sts = [st for st in self._replicas.values()
                   if st.healthy and st.phase in ("any", "decode")]
        depth = 0
        for st in sts:
            self._refresh_load(st)
            depth += (st.last_load.get("queue_depth") or 0) + \
                st.inflight
        return depth

    def _submit_disagg_stream(self, request: Dict[str, Any]):
        self._observe_autoscale()
        # decode-pool backpressure throttles PREFILL admission: work
        # already prefilled is never dropped, new work sheds up front
        depth = self._decode_pool_depth()
        if self.disagg_backpressure_depth and \
                depth > self.disagg_backpressure_depth:
            self.sheds += 1
            self.disagg_backpressure_sheds += 1
            _disagg.count_backpressure_shed()
            _ROUTER_REQS.labels("none", "shed").inc()
            raise fault.ServiceDegradedError(
                f"decode pool backpressure (depth {depth} > "
                f"{self.disagg_backpressure_depth}); prefill admission "
                f"throttled")
        t0 = self._clock()
        pst, wire = self._disagg_prefill(request)
        handoff_t0 = self._clock()
        dst, inner = self._disagg_ingest(pst, wire)
        _disagg.observe_handoff(self._clock() - handoff_t0)
        self.disagg_handoffs += 1
        return _DisaggStream(self, dst, pst, wire, inner, t0,
                             bool(request.get("do_sample")))

    def _disagg_prefill(self, request: Dict[str, Any]):
        """Run the prefill phase with the same failover taxonomy as
        :meth:`submit`; returns (replica_state, artifact wire dict)."""
        excluded: set = set()
        while True:
            st = self._pick(excluded, phase="prefill")
            if st is None:
                self.sheds += 1
                _ROUTER_REQS.labels("none", "shed").inc()
                raise fault.ServiceDegradedError(
                    "no prefill replica can take the request")
            with self._lock:
                st.inflight += 1
            tic = self._clock()
            try:
                wire = st.handle.prefill(request)
            except fault.ServiceDegradedError:
                _ROUTER_REQS.labels(st.name, "shed").inc()
                excluded.add(st.name)
                continue
            except (OSError, urllib.error.URLError) as e:
                _ROUTER_REQS.labels(st.name, "error").inc()
                with self._lock:
                    st.fails += 1
                    if st.fails >= self.health_fail_threshold:
                        st.healthy = False
                logger.warning(
                    "router: prefill replica %s errored (%s); failing "
                    "over", st.name, e)
                excluded.add(st.name)
                continue
            except Exception:
                _ROUTER_REQS.labels(st.name, "error").inc()
                raise
            finally:
                with self._lock:
                    st.inflight -= 1
            st.fails = 0
            st.latencies.append(self._clock() - tic)
            return st, wire

    def _disagg_ingest(self, pst: _ReplicaState, wire: Dict[str, Any],
                       exclude=()):
        """Ingest the handoff on the decode pool.  A corrupt artifact
        is re-fetched from the prefill side's retained copy (never
        silently decoded); a dead decode replica is health-counted and
        the handoff re-ingests on a survivor."""
        excluded: set = set(exclude)
        refetches = 0
        while True:
            st = self._pick(excluded, phase="decode")
            if st is None:
                self.sheds += 1
                _ROUTER_REQS.labels("none", "shed").inc()
                raise fault.ServiceDegradedError(
                    "no decode replica can ingest the handoff")
            with self._lock:
                st.inflight += 1
            try:
                inner = st.handle.ingest(wire)
            except _disagg.ArtifactCorruptError:
                with self._lock:
                    st.inflight -= 1
                self.disagg_reingests += 1
                _disagg.count_reingest("corrupt")
                if refetches >= 2:
                    raise
                refetches += 1
                logger.warning(
                    "router: decode replica %s rejected corrupt "
                    "handoff %s; re-fetching the retained artifact",
                    st.name, wire.get("request_id"))
                wire = pst.handle.disagg_fetch(wire["request_id"])
                continue
            except fault.ServiceDegradedError:
                with self._lock:
                    st.inflight -= 1
                _ROUTER_REQS.labels(st.name, "shed").inc()
                excluded.add(st.name)
                continue
            except (OSError, urllib.error.URLError) as e:
                with self._lock:
                    st.inflight -= 1
                    st.fails += 1
                    if st.fails >= self.health_fail_threshold:
                        st.healthy = False
                _ROUTER_REQS.labels(st.name, "error").inc()
                self.disagg_reingests += 1
                _disagg.count_reingest("replica_failed")
                logger.warning(
                    "router: decode replica %s failed ingest (%s); "
                    "re-ingesting on a survivor", st.name, e)
                excluded.add(st.name)
                continue
            except Exception:
                with self._lock:
                    st.inflight -= 1
                _ROUTER_REQS.labels(st.name, "error").inc()
                raise
            _ROUTER_REQS.labels(st.name, "ok").inc()
            return st, inner

    # ---- rolling deploys --------------------------------------------

    def rolling_reload(self, model: str, ckpt_dir: str,
                       step: Optional[int] = None,
                       drain_timeout: float = 30.0) -> List[Dict]:
        """Hot-swap ``model`` on every replica, ONE replica at a time:
        stop placing on it, wait out its router-tracked in-flight work,
        reload through the replica's ``/admin/reload`` drain barrier,
        re-probe, restore.  With >= 2 replicas traffic keeps flowing the
        whole time (zero failed requests — pinned in
        tests/serve/test_router.py)."""
        with self._lock:
            names = sorted(self._replicas)
        if len(names) < 2:
            logger.warning(
                "rolling reload over %d replica(s): requests arriving "
                "mid-swap will shed", len(names))
        results = []
        for name in names:
            with self._lock:
                st = self._replicas.get(name)
            if st is None:
                continue
            st.draining = True
            try:
                deadline = self._clock() + drain_timeout
                while st.inflight > 0 and self._clock() < deadline:
                    time.sleep(0.005)
                if st.inflight > 0:
                    logger.warning(
                        "replica %s still has %d in-flight after "
                        "%.0fs; its own drain barrier takes over",
                        name, st.inflight, drain_timeout)
                res = st.handle.reload(model, ckpt_dir, step=step)
                code, _ = st.handle.healthz()
                if code != 200:
                    st.fails = self.health_fail_threshold
                    st.healthy = False
                    raise RuntimeError(
                        f"replica {name} unhealthy after reload "
                        f"(healthz {code})")
                results.append({"replica": name, **res})
            finally:
                st.draining = False
        return results

    # ---- autoscale hooks --------------------------------------------

    def _observe_autoscale(self) -> None:
        with self._lock:
            states = [st for st in self._replicas.values() if st.healthy]
            n = max(1, len(states))
            depth = sum((st.last_load.get("queue_depth") or 0) +
                        st.inflight for st in states) / n
        now = self._clock()
        self._as_samples.append((now, depth))
        self.evaluate_autoscale(now)

    def evaluate_autoscale(self, now: Optional[float] = None) -> Optional[str]:
        """Fire ``on_want_more`` when the mean per-replica queue depth
        stayed above ``router_autoscale_hi_queue`` for a full window,
        ``on_want_fewer`` when it stayed below the lo threshold.  At
        most one signal per window.  Returns the signal fired (or
        None) so pollers can act without registering callbacks."""
        now = self._clock() if now is None else now
        w = self.autoscale_window_s
        while self._as_samples and self._as_samples[0][0] < now - 2 * w:
            self._as_samples.popleft()
        window = [d for (t, d) in self._as_samples if t >= now - w]
        if len(window) < 2 or not self._as_samples or \
                self._as_samples[0][0] > now - w:
            return None  # window not yet covered
        if now - self._as_last_fire < w:
            return None  # rate limit: one signal per window
        signal = None
        if min(window) > self.autoscale_hi_queue:
            signal = "want_more"
            self.want_more_signals += 1
            cb = self.on_want_more
        elif max(window) < self.autoscale_lo_queue:
            signal = "want_fewer"
            self.want_fewer_signals += 1
            cb = self.on_want_fewer
        else:
            return None
        self._as_last_fire = now
        mean = sum(window) / len(window)
        logger.info("router autoscale: %s (mean depth %.1f over %.0fs)",
                    signal, mean, w)
        if cb is not None:
            try:
                cb(self, mean)
            except Exception:  # pylint: disable=broad-except
                logger.exception("autoscale callback failed")
        return signal

    # ---- introspection ----------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Per-replica health/load view (the ``/healthz`` body of
        RouterServer)."""
        with self._lock:
            states = list(self._replicas.values())
        routable = [st for st in states
                    if st.healthy and not st.draining]
        return {"status": "ok" if routable else "degraded",
                "policy": self.policy,
                "replicas": {st.name: st.view() for st in states},
                "sheds": self.sheds,
                "want_more_signals": self.want_more_signals,
                "want_fewer_signals": self.want_fewer_signals,
                "disagg": {
                    "mode": self.disagg_mode,
                    "active": self._disagg_active(),
                    "pools": {ph: sorted(st.name for st in states
                                         if st.phase == ph)
                              for ph in ("prefill", "decode", "any")},
                    "handoffs": self.disagg_handoffs,
                    "reingests": self.disagg_reingests,
                    "backpressure_sheds":
                        self.disagg_backpressure_sheds}}


class _RouterHandler(BaseHTTPRequestHandler):
    router: Router = None  # set by RouterServer

    def log_message(self, fmt, *args):  # quiet
        logger.debug(fmt, *args)

    def _send(self, code: int, payload: Dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            snap = self.router.snapshot()
            self._send(503 if snap["status"] == "degraded" else 200,
                       snap)
        elif self.path == "/metrics":
            import alpa_tpu.monitoring  # noqa: F401  pylint: disable=unused-import
            import alpa_tpu.serve.kv_cache  # noqa: F401  pylint: disable=unused-import
            text = _tmetrics.get_registry().to_prometheus_text()
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        try:
            length = int(self.headers.get("Content-Length", 0))
            request = json.loads(self.rfile.read(length) or b"{}")
            if self.path == "/admin/rolling_reload":
                name = request.get("model")
                ckpt_dir = request.get("ckpt_dir")
                if not name or not ckpt_dir:
                    raise ValueError(
                        "rolling_reload needs 'model' and 'ckpt_dir'")
                step = request.get("step")
                out = self.router.rolling_reload(
                    name, ckpt_dir,
                    step=None if step is None else int(step))
                self._send(200, {"reloads": out})
                return
            if self.path != "/completions":
                self._send(404, {"error": f"unknown path {self.path}"})
                return
            if request.get("stream"):
                self._stream(request)
                return
            self._send(200, self.router.submit(request))
        except fault.ServiceDegradedError as e:
            self._send(503, {"error": str(e)})
        except KeyError as e:
            self._send(404, {"error": str(e)})
        except (json.JSONDecodeError, ValueError, TypeError) as e:
            self._send(400, {"error": f"bad request: {e}"})
        except Exception as e:  # pylint: disable=broad-except
            logger.exception("router request failed")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def _stream(self, request):
        it = self.router.submit_stream(request)  # validates/places
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            try:
                for t in it:
                    self.wfile.write(
                        f"data: {json.dumps({'token': t})}\n\n".encode())
                    self.wfile.flush()
                final = {"done": True}
            except (BrokenPipeError, ConnectionResetError):
                it.close()
                return
            except Exception as e:  # pylint: disable=broad-except
                logger.exception("routed stream failed mid-generation")
                final = {"error": f"{type(e).__name__}: {e}"}
            self.wfile.write(f"data: {json.dumps(final)}\n\n".encode())
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            it.close()
        finally:
            self.close_connection = True


class RouterServer:
    """HTTP front end over a Router (mirror of ControllerServer)."""

    def __init__(self, router: Router, host: str = "127.0.0.1",
                 port: int = 0):
        handler = type("BoundRouterHandler", (_RouterHandler,),
                       {"router": router})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.router = router
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self):
        self.thread.start()

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()
