"""Row-level continuous batching engine.

TPU-native analog of ref ``examples/llm_serving/model/wrapper_1d.py``
(1-D continuous batching): a persistent decode loop over a fixed-size
batch of KV-cache rows.  Finished rows are refilled IMMEDIATELY from the
request queue via a single-row prefill scattered into the resident batch
cache — a long generation never blocks short requests behind it, and the
decode executable compiles exactly once for the engine's lifetime.

The per-row KV-cache indices introduced in ``model.gpt_model`` are what
make this possible: every row decodes at its own position.
"""
import dataclasses
import logging
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu import fault
from alpa_tpu.model.gpt_model import init_kv_caches
from alpa_tpu.serve.generation import (GenerationConfig, Generator,
                                       _sample_logits)
from alpa_tpu.telemetry import metrics as _tmetrics
from alpa_tpu.telemetry import trace as _ttrace

logger = logging.getLogger(__name__)

_REG = _tmetrics.get_registry()
_ADMISSIONS = _REG.counter(
    "alpa_serving_admissions_total", "Requests admitted to a KV-cache row")
_DECODE_STEPS = _REG.counter(
    "alpa_serving_decode_steps_total", "Engine decode ticks executed")
_TOKENS = _REG.counter(
    "alpa_serving_tokens_total", "Tokens generated across all requests")
_STEP_FAILURES = _REG.counter(
    "alpa_serving_step_failures_total", "Engine decode ticks that raised")
_ACTIVE_ROWS = _REG.gauge(
    "alpa_serving_active_rows", "KV-cache rows currently decoding")
_TTFT = _REG.histogram(
    "alpa_serving_ttft_seconds",
    "Time from submit to first generated token")

_STREAM_END = object()


class _TokenStream:
    """Token iterator for ``submit_stream`` with a close() that works at
    ANY point — including before the first token.  A plain generator
    cannot do this: ``close()`` on a never-started generator is a no-op
    (GeneratorExit only reaches a body suspended at a yield), so
    pre-admission cancellation through a generator is unreachable."""

    def __init__(self, item, q):
        self._item = item
        self._q = q

    def __iter__(self):
        return self

    def __next__(self):
        t = self._q.get()
        if t is _STREAM_END:
            if self._item["error"] is not None:
                raise self._item["error"]
            raise StopIteration
        return int(t)

    def close(self):
        """Flag the request cancelled: a queued request is retired at
        admission, an active row is freed next tick."""
        self._item["cancelled"] = True

    def __del__(self):
        self.close()


class _DoneEvent(threading.Event):
    """Event with a completion hook (streams push their end sentinel from
    whichever engine path finishes the item — success, EOS, or error)."""

    def __init__(self, hook=None):
        super().__init__()
        self._hook = hook

    def set(self):
        if self._hook is not None:
            try:
                self._hook()
            except Exception:  # pylint: disable=broad-except
                logger.exception("done hook failed")
        super().set()


class ContinuousBatchingEngine:
    """Persistent decode loop with immediate row refill."""

    def __init__(self, generator: Generator, max_batch: int = 4,
                 prompt_bucket: Optional[int] = None,
                 packed_admission: bool = False,
                 packed_bucket: Optional[int] = None,
                 prefix: Optional[Any] = None,
                 scheduler: Optional[Any] = None,
                 kv_pool: Optional[Any] = None):
        """``packed_admission=True`` admits multiple queued prompts with
        ONE packed prefill (segment-masked, serve.packed.PackedPrefill —
        the 1-D batching analog) instead of one prefill per row; falls
        back to per-row prefill when fewer than two prompts wait or the
        backlog exceeds ``packed_bucket`` total tokens.

        ``prefix``: a ``Generator.cache_prefix`` handle shared by EVERY
        request (system prompt): each admission prefills only its
        suffix over a copy of the prefix K/V.  Requires the generator's
        chunked-prefill mode (per-row admissions ride chunked suffix
        prefill).  Composes with ``packed_admission``: the pack is then
        prefilled at cache offset ``prefix.length`` with the prefix
        region attendable by every segment.

        ``scheduler``: an admission policy speaking the queue protocol
        (``serve.scheduler``: FIFOQueue default, WeightedFairQueue,
        NestedScheduler).  ``submit(..., queue=name)`` routes requests
        to named queues; admission order follows the policy.

        ``kv_pool``: a :class:`serve.kv_cache.KVBlockPool` — every
        admission reserves its block table up front (backpressure
        instead of over-admission), prompts sharing a cached token
        prefix skip recomputing those blocks (gather + chunked suffix
        prefill), and each decode tick scatters the new K/V position
        into the row's current block.  Decode math still runs on the
        dense resident caches, so paged output is bit-exact vs unpaged.
        Mutually exclusive with ``prefix`` (warmed prefixes live in the
        pool's index instead); disables ``packed_admission``."""
        self.gen = generator
        self.B = max_batch
        self.bucket = prompt_bucket or generator.prompt_buckets[0]
        cfgm = generator.config
        self._prefix = prefix
        self._pool = kv_pool
        self._tables: List[Optional[Any]] = [None] * max_batch
        self._pool_reuse = False
        if kv_pool is not None:
            if prefix is not None:
                raise ValueError(
                    "kv_pool supersedes the static PrefixHandle: warm "
                    "system prompts via pool.warm_prefix instead")
            if kv_pool.seq_len != cfgm.seq_len:
                raise ValueError(
                    f"kv_pool seq_len {kv_pool.seq_len} != generator "
                    f"seq_len {cfgm.seq_len}")
            self._pool_reuse = (kv_pool.prefix_reuse and
                                bool(generator.prefill_chunk))
            if kv_pool.prefix_reuse and not generator.prefill_chunk:
                logger.warning(
                    "kv prefix reuse needs Generator(prefill_chunk=...) "
                    "to prefill suffixes from the match offset; paging "
                    "stays on but every admission recomputes its prompt")
            if packed_admission:
                logger.warning(
                    "packed_admission is not block-aware; using per-row "
                    "prefill with the KV pool")
                packed_admission = False
        if prefix is not None:
            if not generator.prefill_chunk:
                raise ValueError(
                    "engine prefix caching requires "
                    "Generator(prefill_chunk=...)")
            if getattr(prefix, "params", None) is not generator.params:
                # same guard Generator.generate enforces: a stale handle
                # would serve plausible-but-wrong tokens silently
                raise ValueError(
                    "PrefixHandle was built for different params")
        self._packed = None
        if packed_admission:
            # packing needs segment-mask support AND position-id-based
            # embeddings (rotary/ALiBi bake GLOBAL positions into the
            # packed KV, which the row-local re-gather would corrupt) —
            # GPT/OPT qualify; Bloom/CodeGen take the per-row path
            import inspect
            sig = inspect.signature(generator.model.__call__)
            if "segment_ids" in sig.parameters:
                from alpa_tpu.serve.packed import PackedPrefill
                # clamp to the KV-cache capacity (minus any shared
                # prefix): a packed forward longer than that cannot be
                # written into the caches
                plen = prefix.length if prefix is not None else 0
                total = max(packed_bucket or 2 * self.bucket, self.bucket)
                self._packed = PackedPrefill(
                    generator.model, generator.params, cfgm,
                    total_bucket=min(total, max(1, cfgm.seq_len - plen)),
                    max_rows=self.B, prefix=prefix)
            else:
                logger.warning(
                    "packed_admission requested but %s takes no "
                    "segment_ids — using per-row prefill",
                    type(generator.model).__name__)
        self.packed_admissions = 0

        # resident state: batch KV caches + per-row bookkeeping
        self._caches = init_kv_caches(cfgm, self.B)
        # replace scalar indices with per-row vectors
        self._caches = [(k, v, jnp.zeros((self.B,), jnp.int32))
                        for (k, v, _i) in self._caches]
        self._logits = jnp.zeros((self.B, cfgm.vocab_size), jnp.float32)
        self._active = np.zeros((self.B,), bool)
        self._rows: List[Optional[dict]] = [None] * self.B
        if scheduler is None:
            from alpa_tpu.serve.scheduler import FIFOQueue
            scheduler = FIFOQueue()
        self._queue = scheduler
        self._cv = threading.Condition()
        self._rng = jax.random.PRNGKey(0)
        self.admissions = 0
        self.decode_steps = 0
        self.step_failures = 0
        self._stop = False

        def scatter_row(caches, caches1, logits, logits1, row):
            new = []
            for (k, v, idx), (k1, v1, idx1) in zip(caches, caches1):
                new.append((k.at[row].set(k1[0]),
                            v.at[row].set(v1[0]),
                            idx.at[row].set(idx1[0])))
            return new, logits.at[row].set(logits1[0])

        self._scatter_row = jax.jit(scatter_row)

        def scatter_packed(caches, rowc, logits, last, rowmap, mask):
            new = []
            m4 = mask[:, None, None, None]
            for (k, v, idx), (rk, rv, rlen) in zip(caches, rowc):
                new.append((jnp.where(m4, rk[rowmap], k),
                            jnp.where(m4, rv[rowmap], v),
                            jnp.where(mask, rlen[rowmap], idx)))
            return new, jnp.where(mask[:, None], last[rowmap], logits)

        self._scatter_packed = jax.jit(scatter_packed)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # ---- public API ----

    def submit(self, prompt: np.ndarray,
               cfg: Optional[GenerationConfig] = None,
               on_token=None, queue: Optional[str] = None) -> np.ndarray:
        """Blocking generate for one prompt; rides the shared batch.
        ``on_token(int)`` is invoked from the engine loop as each token
        lands (streaming hook; must not block).  ``queue`` names the
        scheduler queue this request rides (policy-dependent)."""
        item = self._make_item(prompt, cfg, on_token, queue=queue)
        with self._cv:
            self._queue.append(item)
            self._cv.notify()
        item["done"].wait()
        if item["error"] is not None:
            raise item["error"]
        row = np.asarray(item["tokens"], np.int32)
        return np.concatenate([item["prompt"], row])

    def submit_stream(self, prompt: np.ndarray,
                      cfg: Optional[GenerationConfig] = None,
                      queue: Optional[str] = None):
        """Iterator over generated tokens as they land (SSE-friendly).
        Validates and enqueues EAGERLY (so callers can still fail a
        request before committing to a streamed response); raises at the
        point of failure if the engine errors mid-stream."""
        import queue as _queue

        q: "_queue.Queue" = _queue.Queue()
        item = self._make_item(prompt, cfg, q.put,
                               on_done=lambda: q.put(_STREAM_END),
                               queue=queue)
        with self._cv:
            self._queue.append(item)
            self._cv.notify()
        # consumer abandoning the stream (client disconnect) calls
        # close(), which cancels BEFORE admission too — a queued
        # abandoned request is retired instead of burning a KV row
        return _TokenStream(item, q)

    def submit_prefilled(self, prompt: np.ndarray,
                         cfg: Optional[GenerationConfig],
                         caches1, logits1, on_token=None,
                         queue: Optional[str] = None) -> np.ndarray:
        """Blocking decode for a request whose prefill ALREADY ran
        elsewhere (disaggregated serving, serve.disagg): ``caches1`` is
        the dense single-row per-layer ``[(k, v, index)]`` state
        positioned at the prompt length and ``logits1`` the last-token
        logits — exactly what the in-engine prefill would have produced,
        so decode stays bit-exact vs the monolithic path.  The row joins
        the continuous decode batch at the next admission point
        (mid-tick: between decode steps, never waiting out other
        generations)."""
        item = self._make_item(prompt, cfg, on_token, queue=queue,
                               prefilled=(logits1, caches1))
        with self._cv:
            self._queue.append(item)
            self._cv.notify()
        item["done"].wait()
        if item["error"] is not None:
            raise item["error"]
        row = np.asarray(item["tokens"], np.int32)
        return np.concatenate([item["prompt"], row])

    def submit_prefilled_stream(self, prompt: np.ndarray,
                                cfg: Optional[GenerationConfig],
                                caches1, logits1,
                                queue: Optional[str] = None):
        """Streaming variant of :meth:`submit_prefilled` (the decode
        half of a disaggregated handoff)."""
        import queue as _queue

        q: "_queue.Queue" = _queue.Queue()
        item = self._make_item(prompt, cfg, q.put,
                               on_done=lambda: q.put(_STREAM_END),
                               queue=queue, prefilled=(logits1, caches1))
        with self._cv:
            self._queue.append(item)
            self._cv.notify()
        return _TokenStream(item, q)

    def _make_item(self, prompt, cfg, on_token, on_done=None, queue=None,
                   prefilled=None):
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        cfg = cfg or GenerationConfig()
        seq_len = self.gen.config.seq_len
        plen = self._prefix.length if self._prefix is not None else 0
        if prefilled is not None and self._prefix is not None:
            raise ValueError(
                "prefilled admission is incompatible with a static "
                "PrefixHandle engine (ingested caches carry the full "
                "prompt)")
        if prefilled is None and len(prompt) > self.bucket:
            # prefilled rows never run this engine's prefill, so the
            # prefill bucket does not constrain them (seq_len does)
            raise ValueError(
                f"prompt {len(prompt)} exceeds engine bucket "
                f"{self.bucket}")
        # hard errors (not asserts): -O must not admit a request whose
        # decode would write past the cache
        if plen + len(prompt) + cfg.max_new_tokens > seq_len:
            raise ValueError(
                f"prefix {plen} + prompt {len(prompt)} + max_new_tokens "
                f"{cfg.max_new_tokens} exceeds seq_len {seq_len}")
        if self._pool is not None and not self._pool.fits(
                len(prompt) + cfg.max_new_tokens):
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens "
                f"{cfg.max_new_tokens} needs more KV blocks than the "
                f"pool holds ({self._pool.num_blocks} x "
                f"{self._pool.block_size} tokens)")
        if self._prefix is not None:
            # admission prefills in fixed chunks FROM the prefix offset:
            # reject synchronously what chunk padding cannot fit
            c = self.gen.prefill_chunk
            padded = max(1, -(-len(prompt) // c)) * c if len(prompt) \
                else 0
            if plen + padded > seq_len:
                raise ValueError(
                    f"prompt {len(prompt)} pads to {padded} chunks past "
                    f"prefix {plen}, exceeding seq_len {seq_len}; use a "
                    "smaller chunk size or shorter prompt")
        return {"prompt": prompt, "cfg": cfg, "tokens": [],
                "done": _DoneEvent(on_done), "error": None,
                "on_token": on_token, "cancelled": False,
                "queue": queue or "default", "prefilled": prefilled,
                "t_submit": time.monotonic()}

    def shutdown(self):
        with self._cv:
            self._stop = True
            self._cv.notify()

    # ---- engine loop ----

    def _admit_locked(self):
        """Fill free rows from the queue: one packed prefill when several
        prompts wait (and packing is on), else per-row prefills.

        Admission failures (trace/compile/device errors) fail ONLY the
        requests being admitted — the engine loop and resident rows
        survive (a dead loop thread would deadlock every submitter).
        """
        def next_live():
            """Policy-head item, retiring requests cancelled while still
            queued (client disconnected before admission: prefilling and
            decoding them would burn a row for nobody).  Returns the
            head WITHOUT popping it."""
            while True:
                nxt = self._queue.peek()
                if nxt is None or not nxt.get("cancelled"):
                    return nxt
                self._queue.popleft()["done"].set()

        if self._packed is not None and len(self._queue) >= 2:
            free = [r for r in range(self.B) if not self._active[r]]
            take, total = [], 0
            while len(take) < len(free):
                nxt = next_live()
                if nxt is None or total + len(nxt["prompt"]) > \
                        self._packed.total_bucket:
                    break
                item = self._queue.popleft()
                take.append(item)
                total += len(item["prompt"])
            if len(take) >= 2:
                try:
                    last, row_caches = self._packed(
                        [it["prompt"] for it in take])
                    rowmap = np.zeros((self.B,), np.int32)
                    mask = np.zeros((self.B,), bool)
                    for slot, item in enumerate(take):
                        r = free[slot]
                        rowmap[r] = slot
                        mask[r] = True
                        self._rows[r] = item
                        self._active[r] = True
                        self.admissions += 1
                        _ADMISSIONS.inc()
                    self._caches, self._logits = self._scatter_packed(
                        self._caches, row_caches, self._logits,
                        last.astype(jnp.float32), jnp.asarray(rowmap),
                        jnp.asarray(mask))
                    self.packed_admissions += 1
                except Exception as e:  # pylint: disable=broad-except
                    logger.exception("packed admission failed")
                    for item in take:
                        item["error"] = e
                        item["done"].set()
                        for r in range(self.B):
                            if self._rows[r] is item:
                                self._active[r] = False
                                self._rows[r] = None
            else:
                # not enough for a pack: put back and fall through
                self._queue.pushback(take)
        for r in range(self.B):
            if self._active[r]:
                continue
            nxt = next_live()
            if nxt is None:
                continue
            seq = None
            if self._pool is not None:
                try:
                    seq = self._pool.begin_sequence(
                        nxt["prompt"], nxt["cfg"].max_new_tokens)
                except Exception as e:  # pylint: disable=broad-except
                    self._queue.popleft()
                    nxt["error"] = e
                    nxt["done"].set()
                    continue
                if seq is None:
                    # pool backpressure: live sequences hold the blocks.
                    # Leave the request queued; a retirement frees blocks
                    # and the next tick re-admits.  With NO live rows the
                    # pool can only be out of evictable blocks — fits()
                    # was checked at submit, so fail loudly instead of
                    # spinning.
                    if not self._active.any():
                        from alpa_tpu.serve.kv_cache import \
                            KVPoolExhaustedError
                        item = self._queue.popleft()
                        item["error"] = KVPoolExhaustedError(
                            "KV pool exhausted with no live sequences "
                            "to wait on")
                        item["done"].set()
                    break
            item = self._queue.popleft()
            try:
                p = item["prompt"]
                if item.get("prefilled") is not None:
                    # disaggregated handoff: the prefill ran on another
                    # replica; its dense row state lands here unchanged
                    # (bit-identical to what this engine's own prefill
                    # would produce — serve.disagg pins this)
                    logits1, caches1 = item["prefilled"]
                    item["prefilled"] = None  # drop the reference
                elif seq is not None and seq.matched_tokens:
                    # prefix-reuse hit: gather the cached blocks into a
                    # dense row and prefill ONLY the suffix from the
                    # match offset (gather moves bits unchanged; the
                    # chunk step masks exactly, so this stays bit-exact)
                    m = seq.matched_tokens
                    total = jnp.asarray([len(p)], jnp.int32)
                    gathered = self._pool.gather_dense(seq)
                    logits1, caches1 = self.gen._run_chunked_prefill(
                        [p[m:]], total, 1, caches=gathered, start=m)
                elif self._prefix is not None:
                    # suffix-only prefill OVER the shared prefix K/V.
                    # The handle's arrays are shared read-only: the
                    # chunk step is functional and non-donating, so the
                    # handle survives every admission unchanged.
                    h = self._prefix
                    total = jnp.asarray([h.length + len(p)], jnp.int32)
                    logits1, caches1 = self.gen._run_chunked_prefill(
                        [p], total, 1, caches=h.caches, start=h.length,
                        init_last=h.last_logits)
                else:
                    ids = np.zeros((1, self.bucket), np.int32)
                    ids[0, :len(p)] = p
                    caches1 = init_kv_caches(self.gen.config, 1)
                    logits1, caches1 = self.gen._prefill(
                        self.gen.params, jnp.asarray(ids), caches1,
                        jnp.asarray([len(p)], jnp.int32))
                self._caches, self._logits = self._scatter_row(
                    self._caches, caches1, self._logits,
                    logits1.astype(jnp.float32), r)
                if seq is not None:
                    # publish the prompt's full blocks while the row is
                    # still live, so concurrent shared-prefix requests
                    # hit immediately
                    self._pool.scatter_prompt(seq, caches1)
                    if self._pool_reuse:
                        self._pool.register_prompt(seq, p)
                    self._tables[r] = seq
                self._rows[r] = item
                self._active[r] = True
                self.admissions += 1
                _ADMISSIONS.inc()
            except Exception as e:  # pylint: disable=broad-except
                logger.exception("row admission failed")
                if seq is not None:
                    self._pool.release(seq, register=False)
                item["error"] = e
                item["done"].set()

    def _release_table(self, r: int, item: Optional[dict]):
        """Return row ``r``'s blocks to the pool.  A cleanly finished
        request first publishes its full prompt+output blocks to the
        prefix index ("recently finished" reuse, incl. multi-turn);
        cancelled/errored rows just free."""
        if self._pool is None or self._tables[r] is None:
            return
        seq = self._tables[r]
        self._tables[r] = None
        try:
            clean = (item is not None and item["error"] is None and
                     not item.get("cancelled"))
            toks = None
            if clean and self._pool_reuse:
                toks = np.concatenate(
                    [item["prompt"],
                     np.asarray(item["tokens"], np.int32)])
            self._pool.release(seq, tokens=toks, register=toks is not None)
        except Exception:  # pylint: disable=broad-except
            logger.exception("KV pool release failed for row %d", r)

    def _run(self):
        while True:
            with self._cv:
                while not self._stop and (len(self._queue) == 0 and
                                          not self._active.any()):
                    self._cv.wait()
                if self._stop:
                    # fail pending work so no submitter deadlocks
                    err = RuntimeError("engine shut down")
                    for item in self._queue.drain():
                        item["error"] = err
                        item["done"].set()
                    for r in range(self.B):
                        if self._active[r]:
                            self._rows[r]["error"] = err
                            self._release_table(r, self._rows[r])
                            self._rows[r]["done"].set()
                            self._active[r] = False
                            self._rows[r] = None
                    return
                self._admit_locked()
            try:
                if _ttrace.enabled():
                    with _ttrace.get_recorder().span(
                            "engine.decode-tick", "serving",
                            {"active": int(self._active.sum())},
                            "serve-engine"):
                        self._step()
                else:
                    self._step()
            except Exception as e:  # pylint: disable=broad-except
                logger.exception("engine step failed")
                self.step_failures += 1
                _STEP_FAILURES.inc()
                with self._cv:
                    for r in range(self.B):
                        if self._active[r]:
                            self._rows[r]["error"] = e
                            self._release_table(r, self._rows[r])
                            self._rows[r]["done"].set()
                            self._active[r] = False
                            self._rows[r] = None

    def _step(self):
        """One decode tick for every active row."""
        fault.fire("scheduler_tick", step=self.decode_steps,
                   active=int(self._active.sum()))
        self._rng, sub = jax.random.split(self._rng)
        # sampling settings come from each row's cfg; rows with identical
        # settings dominate in practice — sample with row 0's active cfg
        # and resample per-row only when configs differ (greedy default).
        cfgs = [self._rows[r]["cfg"] if self._active[r] else None
                for r in range(self.B)]
        base = next((c for c in cfgs if c is not None),
                    GenerationConfig())
        nxt = np.asarray(_sample_logits(self._logits, sub, base)
                         ).astype(np.int32)
        for r, c in enumerate(cfgs):
            if c is not None and dataclasses.astuple(c) != \
                    dataclasses.astuple(base):
                self._rng, sub_r = jax.random.split(self._rng)
                nxt[r] = int(np.asarray(_sample_logits(
                    self._logits[r:r + 1], sub_r, c))[0])

        index = self._caches[0][2]          # per-row positions
        tok = jnp.asarray(nxt[:, None])
        logits, self._caches = self.gen._decode(
            self.gen.params, tok, index, self._caches)
        self._logits = logits.astype(jnp.float32)
        self.decode_steps += 1
        _DECODE_STEPS.inc()
        if self._pool is not None:
            # the tick wrote each row's new K/V at its pre-decode index;
            # mirror those positions into the block pool (rows without a
            # table land in the scratch block)
            self._pool.write_tokens(self._caches, list(self._tables),
                                    np.asarray(index))

        with self._cv:
            for r in range(self.B):
                if not self._active[r]:
                    continue
                item = self._rows[r]
                cfg = item["cfg"]
                t = int(nxt[r])
                item["tokens"].append(t)
                _TOKENS.inc()
                if len(item["tokens"]) == 1 and "t_submit" in item:
                    _TTFT.observe(time.monotonic() - item["t_submit"])
                if item.get("on_token") is not None:
                    try:
                        item["on_token"](t)
                    except Exception:  # pylint: disable=broad-except
                        logger.exception("on_token callback failed")
                hit_eos = (cfg.eos_token_id is not None and
                           t == cfg.eos_token_id)
                if (hit_eos or item.get("cancelled") or
                        len(item["tokens"]) >= cfg.max_new_tokens):
                    self._release_table(r, item)
                    item["done"].set()
                    self._active[r] = False
                    self._rows[r] = None
            # refill freed rows before the next tick
            self._admit_locked()
            _ACTIVE_ROWS.set(int(self._active.sum()))
