"""Row-level continuous batching engine.

TPU-native analog of ref ``examples/llm_serving/model/wrapper_1d.py``
(1-D continuous batching): a persistent decode loop over a fixed-size
batch of KV-cache rows.  Finished rows are refilled IMMEDIATELY from the
request queue via a single-row prefill scattered into the resident batch
cache — a long generation never blocks short requests behind it, and the
decode executable compiles exactly once for the engine's lifetime.

The per-row KV-cache indices introduced in ``model.gpt_model`` are what
make this possible: every row decodes at its own position.
"""
import dataclasses
import logging
import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu.model.gpt_model import init_kv_caches
from alpa_tpu.serve.generation import (GenerationConfig, Generator,
                                       _sample_logits)

logger = logging.getLogger(__name__)


class ContinuousBatchingEngine:
    """Persistent decode loop with immediate row refill."""

    def __init__(self, generator: Generator, max_batch: int = 4,
                 prompt_bucket: Optional[int] = None):
        self.gen = generator
        self.B = max_batch
        self.bucket = prompt_bucket or generator.prompt_buckets[0]
        cfgm = generator.config

        # resident state: batch KV caches + per-row bookkeeping
        self._caches = init_kv_caches(cfgm, self.B)
        # replace scalar indices with per-row vectors
        self._caches = [(k, v, jnp.zeros((self.B,), jnp.int32))
                        for (k, v, _i) in self._caches]
        self._logits = jnp.zeros((self.B, cfgm.vocab_size), jnp.float32)
        self._active = np.zeros((self.B,), bool)
        self._rows: List[Optional[dict]] = [None] * self.B
        self._queue: List[dict] = []
        self._cv = threading.Condition()
        self._rng = jax.random.PRNGKey(0)
        self.admissions = 0
        self.decode_steps = 0
        self._stop = False

        def scatter_row(caches, caches1, logits, logits1, row):
            new = []
            for (k, v, idx), (k1, v1, idx1) in zip(caches, caches1):
                new.append((k.at[row].set(k1[0]),
                            v.at[row].set(v1[0]),
                            idx.at[row].set(idx1[0])))
            return new, logits.at[row].set(logits1[0])

        self._scatter_row = jax.jit(scatter_row)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # ---- public API ----

    def submit(self, prompt: np.ndarray,
               cfg: Optional[GenerationConfig] = None) -> np.ndarray:
        """Blocking generate for one prompt; rides the shared batch."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        cfg = cfg or GenerationConfig()
        assert len(prompt) <= self.bucket, (
            f"prompt {len(prompt)} exceeds engine bucket {self.bucket}")
        assert len(prompt) + cfg.max_new_tokens <= \
            self.gen.config.seq_len, (
                f"prompt {len(prompt)} + max_new_tokens "
                f"{cfg.max_new_tokens} exceeds seq_len "
                f"{self.gen.config.seq_len}")
        item = {"prompt": prompt, "cfg": cfg,
                "tokens": [], "done": threading.Event(), "error": None}
        with self._cv:
            self._queue.append(item)
            self._cv.notify()
        item["done"].wait()
        if item["error"] is not None:
            raise item["error"]
        row = np.asarray(item["tokens"], np.int32)
        return np.concatenate([prompt, row])

    def shutdown(self):
        with self._cv:
            self._stop = True
            self._cv.notify()

    # ---- engine loop ----

    def _admit_locked(self):
        """Fill free rows from the queue (single-row prefill + scatter)."""
        for r in range(self.B):
            if self._active[r] or not self._queue:
                continue
            item = self._queue.pop(0)
            p = item["prompt"]
            ids = np.zeros((1, self.bucket), np.int32)
            ids[0, :len(p)] = p
            caches1 = init_kv_caches(self.gen.config, 1)
            logits1, caches1 = self.gen._prefill(
                self.gen.params, jnp.asarray(ids), caches1,
                jnp.asarray([len(p)], jnp.int32))
            self._caches, self._logits = self._scatter_row(
                self._caches, caches1, self._logits,
                logits1.astype(jnp.float32), r)
            self._rows[r] = item
            self._active[r] = True
            self.admissions += 1

    def _run(self):
        while True:
            with self._cv:
                while not self._stop and (not self._queue and
                                          not self._active.any()):
                    self._cv.wait()
                if self._stop:
                    # fail pending work so no submitter deadlocks
                    err = RuntimeError("engine shut down")
                    for item in self._queue:
                        item["error"] = err
                        item["done"].set()
                    self._queue = []
                    for r in range(self.B):
                        if self._active[r]:
                            self._rows[r]["error"] = err
                            self._rows[r]["done"].set()
                            self._active[r] = False
                            self._rows[r] = None
                    return
                self._admit_locked()
            try:
                self._step()
            except Exception as e:  # pylint: disable=broad-except
                logger.exception("engine step failed")
                with self._cv:
                    for r in range(self.B):
                        if self._active[r]:
                            self._rows[r]["error"] = e
                            self._rows[r]["done"].set()
                            self._active[r] = False
                            self._rows[r] = None

    def _step(self):
        """One decode tick for every active row."""
        self._rng, sub = jax.random.split(self._rng)
        # sampling settings come from each row's cfg; rows with identical
        # settings dominate in practice — sample with row 0's active cfg
        # and resample per-row only when configs differ (greedy default).
        cfgs = [self._rows[r]["cfg"] if self._active[r] else None
                for r in range(self.B)]
        base = next((c for c in cfgs if c is not None),
                    GenerationConfig())
        nxt = np.asarray(_sample_logits(self._logits, sub, base)
                         ).astype(np.int32)
        for r, c in enumerate(cfgs):
            if c is not None and dataclasses.astuple(c) != \
                    dataclasses.astuple(base):
                self._rng, sub_r = jax.random.split(self._rng)
                nxt[r] = int(np.asarray(_sample_logits(
                    self._logits[r:r + 1], sub_r, c))[0])

        index = self._caches[0][2]          # per-row positions
        tok = jnp.asarray(nxt[:, None])
        logits, self._caches = self.gen._decode(
            self.gen.params, tok, index, self._caches)
        self._logits = logits.astype(jnp.float32)
        self.decode_steps += 1

        with self._cv:
            for r in range(self.B):
                if not self._active[r]:
                    continue
                item = self._rows[r]
                cfg = item["cfg"]
                t = int(nxt[r])
                item["tokens"].append(t)
                hit_eos = (cfg.eos_token_id is not None and
                           t == cfg.eos_token_id)
                if hit_eos or len(item["tokens"]) >= cfg.max_new_tokens:
                    item["done"].set()
                    self._active[r] = False
                    self._rows[r] = None
            # refill freed rows before the next tick
            self._admit_locked()
