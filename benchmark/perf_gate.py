"""Perf regression gate: fresh measurements vs committed baselines
(ISSUE 9).

Compares a flat metric dict — produced by analyzing a trace with
``alpa_tpu.telemetry.perf`` and/or by the dispatch/resharding benches —
against ``benchmark/results/perf_gate_baseline.json``, which names each
gated metric with its committed value and tolerance::

    {"metrics": {
        "critical_path_us":       {"value": 596.0, "max_ratio": 1.05},
        "modes.registers.per_inst_us": {"value": 40.0, "max_ratio": 5.0}
    }}

``max_ratio`` bounds fresh/baseline above (regressions); optional
``min_ratio`` bounds it below (for metrics where *shrinking* is the
regression, e.g. overlap_fraction); optional ``max_abs`` is an absolute
ceiling.  Only metrics present in BOTH the fresh dict and the baseline
are checked, so one committed baseline serves both the deterministic
fixture-trace test (tier-1) and the machine-dependent bench ``--gate``
runs.  The verdict is machine-readable and every run increments
``alpa_perf_gate_total{result}`` in the central registry.

Usage::

    python benchmark/perf_gate.py --trace TRACE.json [--baseline FILE]
                                  [--update]

Exit status 0 = pass, 1 = fail.  ``--update`` rewrites the baseline's
values from the fresh run (tolerances preserved) instead of checking.
"""
import argparse
import json
import os
import sys
from typing import Any, Dict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "benchmark", "results",
                                "perf_gate_baseline.json")
FIXTURE_TRACE = os.path.join(REPO, "benchmark", "results",
                             "perf_gate_fixture_trace.json")


def flatten_metrics(d: Dict[str, Any], prefix: str = ""
                    ) -> Dict[str, float]:
    """Nested report dict -> flat {dotted.name: float} (bools excluded)."""
    out: Dict[str, float] = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            out.update(flatten_metrics(v, key))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = float(v)
    return out


def check(fresh: Dict[str, float],
          baseline: Dict[str, Any]) -> Dict[str, Any]:
    """Gate ``fresh`` against the baseline spec; returns the verdict."""
    checks = []
    specs = baseline.get("metrics", {})
    for name, spec in sorted(specs.items()):
        if name not in fresh:
            continue
        base_val = float(spec["value"])
        fresh_val = fresh[name]
        ratio = (fresh_val / base_val) if base_val else (
            1.0 if fresh_val == 0 else float("inf"))
        ok = True
        reasons = []
        max_ratio = spec.get("max_ratio")
        if max_ratio is not None and ratio > float(max_ratio):
            ok = False
            reasons.append(f"ratio {ratio:.3f} > max_ratio {max_ratio}")
        min_ratio = spec.get("min_ratio")
        if min_ratio is not None and ratio < float(min_ratio):
            ok = False
            reasons.append(f"ratio {ratio:.3f} < min_ratio {min_ratio}")
        max_abs = spec.get("max_abs")
        if max_abs is not None and fresh_val > float(max_abs):
            ok = False
            reasons.append(f"value {fresh_val:.4f} > max_abs {max_abs}")
        checks.append({
            "metric": name,
            "baseline": base_val,
            "fresh": round(fresh_val, 4),
            "ratio": round(ratio, 4),
            "ok": ok,
            **({"reason": "; ".join(reasons)} if reasons else {}),
        })
    n_failed = sum(1 for c in checks if not c["ok"])
    return {
        "pass": n_failed == 0 and bool(checks),
        "n_checked": len(checks),
        "n_failed": n_failed,
        "n_skipped": len(specs) - len(checks),
        "checks": checks,
    }


def gate(fresh: Dict[str, float],
         baseline_path: str = DEFAULT_BASELINE) -> Dict[str, Any]:
    """Load the baseline, run :func:`check`, record the verdict in the
    metrics registry (``alpa_perf_gate_total{result}``)."""
    with open(baseline_path, encoding="utf-8") as f:
        baseline = json.load(f)
    verdict = check(fresh, baseline)
    from alpa_tpu.telemetry.perf import record_gate_verdict
    record_gate_verdict(verdict["pass"])
    return verdict


def _fresh_from_trace(path: str) -> Dict[str, float]:
    from alpa_tpu.telemetry.perf import report_from_trace
    with open(path, encoding="utf-8") as f:
        trace = json.load(f)
    report = report_from_trace(trace)
    if report is None:
        sys.exit(f"{path}: no analyzable step in trace")
    return flatten_metrics(report.to_dict())


def _update(fresh: Dict[str, float], baseline_path: str):
    if os.path.exists(baseline_path):
        with open(baseline_path, encoding="utf-8") as f:
            baseline = json.load(f)
    else:
        baseline = {"metrics": {}}
    metrics = baseline.setdefault("metrics", {})
    for name, spec in metrics.items():
        if name in fresh:
            spec["value"] = round(fresh[name], 4)
    with open(baseline_path, "w", encoding="utf-8") as f:
        json.dump(baseline, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"updated {len([n for n in metrics if n in fresh])} baseline "
          f"value(s) in {baseline_path}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--trace", default=FIXTURE_TRACE,
                   help="chrome trace to analyze (default: the "
                        "committed fixture)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument("--update", action="store_true",
                   help="rewrite baseline values from this run instead "
                        "of gating")
    args = p.parse_args(argv)

    fresh = _fresh_from_trace(args.trace)
    if args.update:
        _update(fresh, args.baseline)
        return 0
    verdict = gate(fresh, args.baseline)
    print(json.dumps(verdict, indent=1))
    if not verdict["pass"]:
        failed = [c["metric"] for c in verdict["checks"] if not c["ok"]]
        print(f"PERF GATE FAILED: {verdict['n_failed']}/"
              f"{verdict['n_checked']} checks "
              f"({', '.join(failed) or 'no metrics checked'})",
              file=sys.stderr)
        return 1
    print(f"perf gate passed: {verdict['n_checked']} checks, "
          f"{verdict['n_skipped']} baseline metric(s) not measured "
          f"this run", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
