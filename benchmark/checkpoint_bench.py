"""Checkpoint subsystem benchmark (ISSUE 3): save/restore throughput
and the async-vs-sync training-loop blocking time.

Measures, on a CPU mesh (no TPU needed — disk + hashing dominate):

- sync save wall time and GB/s (chunk hashing + tmp/rename writes +
  manifest fsync, inline);
- async save *blocking* time (double-buffer join + device→host staging
  only) and its ratio to the sync save — the <10% acceptance number;
- dedupe-save time (same content again: all chunks hit the store);
- restore GB/s with hash verification on and off.

Usage:  python benchmark/checkpoint_bench.py [--mb 256] [--out F]

Writes JSON next to the other suite results
(benchmark/results/checkpoint_bench.json).
"""
import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_OUT = os.path.join(REPO, "benchmark", "results",
                           "checkpoint_bench.json")


def _state(total_mb: int, n_leaves: int = 8, seed: int = 0):
    import numpy as np
    per = total_mb * (1 << 20) // n_leaves // 4      # float32 elements
    rng = np.random.default_rng(seed)
    return {f"p{i}": rng.standard_normal(per).astype(np.float32)
            for i in range(n_leaves)}


def run(total_mb: int, root: str) -> dict:
    from alpa_tpu.checkpoint.manager import CheckpointManager

    nbytes = total_mb * (1 << 20)
    gb = nbytes / (1 << 30)
    result = {"state_mb": total_mb, "n_leaves": 8}

    # -- sync save baseline -------------------------------------------
    sync_mgr = CheckpointManager(os.path.join(root, "sync"))
    state = _state(total_mb, seed=0)
    t0 = time.perf_counter()
    sync_mgr.save(1, state, sync=True)
    t_sync = time.perf_counter() - t0
    result["sync_save_seconds"] = round(t_sync, 4)
    result["sync_save_gbps"] = round(gb / t_sync, 3)

    # -- async save: blocking vs total --------------------------------
    async_mgr = CheckpointManager(os.path.join(root, "async"))
    state2 = _state(total_mb, seed=1)                # distinct: no dedupe
    t0 = time.perf_counter()
    async_mgr.save(1, state2)
    blocking = async_mgr.last_blocking_seconds
    async_mgr.wait()
    t_total = time.perf_counter() - t0
    result["async_blocking_seconds"] = round(blocking, 4)
    result["async_staging_seconds"] = round(
        async_mgr.last_staging_seconds, 4)
    result["async_total_seconds"] = round(t_total, 4)
    result["blocking_ratio_vs_sync"] = round(blocking / t_sync, 4)

    # -- dedupe save (identical content, next step) -------------------
    t0 = time.perf_counter()
    async_mgr.save(2, state2, sync=True)
    result["dedupe_save_seconds"] = round(time.perf_counter() - t0, 4)

    # -- restore ------------------------------------------------------
    for verify in (True, False):
        t0 = time.perf_counter()
        sync_mgr.restore(state, step=1, verify=verify)
        dt = time.perf_counter() - t0
        key = "restore_verified" if verify else "restore_unverified"
        result[f"{key}_seconds"] = round(dt, 4)
        result[f"{key}_gbps"] = round(gb / dt, 3)
    return result


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--mb", type=int, default=256,
                        help="total state size in MB (default 256)")
    parser.add_argument("--out", default=DEFAULT_OUT)
    args = parser.parse_args()

    root = tempfile.mkdtemp(prefix="ckpt_bench_")
    try:
        result = run(args.mb, root)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    result["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result, indent=2))
    print(f"\nwrote {args.out}")
    assert result["blocking_ratio_vs_sync"] < 0.10, (
        "async save blocked >=10% of a sync save — the double buffer "
        "or staging path regressed")


if __name__ == "__main__":
    main()
