"""Profile-guided replanning benchmark (ISSUE 12): fixture trace ->
calibration store -> deliberately mispriced edge -> strategy flip ->
re-simulated critical path.

Fully deterministic (fixture trace + analytic wire model + injected
measurements — no wall clocks), so the gate tolerances are tight:

1. Ingest the committed ``perf_gate_fixture_trace.json`` into a fresh
   calibration store (per-stage RUN medians, per-edge wire medians).
2. Price a real 2-mesh resharding edge (two 4-device CPU meshes,
   rowshard -> replicated) under the ``link`` wire model: the analytic
   winner is ``slice_all_gather``.
3. Inject the misprice: observed wire samples on the analytic winner
   at 50x its modeled cost.  The drift gauge
   (``alpa_cost_model_drift_ratio{kind="reshard_wire"}``) surfaces it.
4. Replan under ``replan_mode=suggest``: the measured override flips
   the choice back to ``direct_p2p`` (still analytically priced — only
   strategies that actually ran get measured overrides).
5. Re-simulate the fixture step DAG (``simulate_dag``) with the edge
   priced at the measured cost (original plan) vs the replanned
   strategy's cost: the post-replan critical-path ratio must be <= 1.
6. Warm restart: re-ingesting the identical trace leaves the store
   fingerprint unchanged, and ``resolve_strategy`` replays the flipped
   decision from the compile cache without re-solving.

Usage:  python benchmark/replan_bench.py [--out F] [--gate]

``--gate`` checks the ``replan.*`` metrics against
``benchmark/results/perf_gate_baseline.json`` (PR 9 gate) and exits
nonzero on regression.  Writes benchmark/results/replan.json.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from alpa_tpu.platform import pin_cpu_platform  # noqa: E402

DEFAULT_OUT = os.path.join(REPO, "benchmark", "results", "replan.json")
FIXTURE_TRACE = os.path.join(REPO, "benchmark", "results",
                             "perf_gate_fixture_trace.json")

# injected "measured" wire cost on the analytic winner (µs); its
# modeled price under the knobs below is 10 µs -> drift ratio 50
MISPRICED_WIRE_US = 500.0


def run() -> dict:
    import numpy as np
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from alpa_tpu.analysis.critical_path import simulate_dag
    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel import cross_mesh_resharding as cmr
    from alpa_tpu.telemetry import calibration as cal
    from alpa_tpu.telemetry import perf

    prev = (global_config.replan_mode,
            global_config.calibration_min_samples,
            global_config.reshard_strategy,
            global_config.resharding_wire_model,
            global_config.resharding_transfer_latency_s,
            global_config.resharding_wire_bandwidth)
    store = cal.CalibrationStore(None)          # fresh, memory-only
    cal.reset_calibration_store(store)
    try:
        global_config.replan_mode = "suggest"
        global_config.calibration_min_samples = 3
        global_config.reshard_strategy = "auto"
        global_config.resharding_wire_model = "link"
        global_config.resharding_transfer_latency_s = 1e-5
        global_config.resharding_wire_bandwidth = 0.0

        # 1. calibrate from the committed fixture trace
        with open(FIXTURE_TRACE, encoding="utf-8") as f:
            trace = json.load(f)
        ingested = cal.ingest_chrome_trace(trace, store=store)
        report = perf.report_from_trace(trace)
        assert report is not None, "fixture trace has no analyzable step"

        # 2. analytic price of a real 2-mesh edge
        devs = jax.devices()
        src_mesh = Mesh(np.array(devs[:4]), ("x",))
        dst_mesh = Mesh(np.array(devs[4:8]), ("x",))
        src = NamedSharding(src_mesh, P("x", None))
        dst = NamedSharding(dst_mesh, P())
        shape, itemsize = (8, 8), 4
        chosen0, costs0, _ = cmr.choose_strategy(shape, itemsize, src, dst)

        # 3. the deliberately mispriced edge: measured wire on the
        # analytic winner far above its modeled price
        sig = cal.wire_signature(shape, itemsize, cmr._sharding_key(src),
                                 cmr._sharding_key(dst), chosen0)
        for _ in range(global_config.calibration_min_samples + 1):
            store.observe("reshard_wire", sig, MISPRICED_WIRE_US,
                          modeled_us=costs0[chosen0] * 1e6,
                          meta={"source": "replan_bench"})
        drift_worst = max(
            (e.drift_ratio for e in store.entries()
             if e.drift_ratio is not None), default=0.0)

        # 4. replan: the measured override flips the strategy
        chosen1, costs1, _ = cmr.choose_strategy(shape, itemsize, src, dst)
        flipped = chosen1 != chosen0

        # 5. re-simulate the fixture DAG: original plan priced at the
        # measured (mispriced) edge cost vs the replanned strategy
        wait_idx = [i for i, op in enumerate(report.sim_ops)
                    if op.kind == "wait"]
        durs_orig = list(report.sim_durs_us)
        durs_replan = list(report.sim_durs_us)
        for i in wait_idx:
            durs_orig[i] = MISPRICED_WIRE_US
            durs_replan[i] = costs1[chosen1] * 1e6
        baseline_us, _ = simulate_dag(durs_orig, report.sim_preds)
        replanned_us, _ = simulate_dag(durs_replan, report.sim_preds)
        ratio = replanned_us / baseline_us if baseline_us else 1.0

        # 6. warm restart: identical re-ingest keeps the fingerprint,
        # and the flipped decision replays from the compile cache
        fp0 = store.fingerprint()
        cal.ingest_chrome_trace(trace, store=store)
        fp_stable = store.fingerprint() == fp0
        warm0 = cmr.resolve_strategy(shape, itemsize, src, dst)
        warm1 = cmr.resolve_strategy(shape, itemsize, src, dst)
        warm_cached = bool(warm1[2]) and warm1[0] == chosen1 \
            and warm0[0] == chosen1

        # drift gauge actually exported on /metrics text
        from alpa_tpu.telemetry.metrics import get_registry
        gauge_exported = ("alpa_cost_model_drift_ratio" in
                          get_registry().to_prometheus_text())

        gate_metrics = {
            "replan.critical_path_ratio": round(ratio, 4),
            "replan.strategy_flipped": float(flipped),
            "replan.fingerprint_stable": float(fp_stable),
            "replan.warm_resolve_cached": float(warm_cached),
            "replan.drift_ratio_worst": round(drift_worst, 4),
            "replan.drift_gauge_exported": float(gauge_exported),
        }
        return {
            "ingested_signatures": ingested,
            "edge": {
                "shape": list(shape), "itemsize": itemsize,
                "analytic_choice": chosen0,
                "analytic_costs_us": {n: round(c * 1e6, 3)
                                      for n, c in costs0.items()},
                "mispriced_signature": sig,
                "mispriced_measured_us": MISPRICED_WIRE_US,
                "replanned_choice": chosen1,
                "replanned_costs_us": {n: round(c * 1e6, 3)
                                       for n, c in costs1.items()},
            },
            "critical_path": {
                "original_plan_us": round(baseline_us, 3),
                "replanned_plan_us": round(replanned_us, 3),
                "ratio": round(ratio, 4),
            },
            "calibration_fingerprint": fp0,
            "gate_metrics": gate_metrics,
        }
    finally:
        cal.reset_calibration_store(None)
        (global_config.replan_mode,
         global_config.calibration_min_samples,
         global_config.reshard_strategy,
         global_config.resharding_wire_model,
         global_config.resharding_transfer_latency_s,
         global_config.resharding_wire_bandwidth) = prev


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default=DEFAULT_OUT)
    parser.add_argument("--gate", action="store_true",
                        help="check replan.* metrics against the "
                             "committed perf-gate baseline")
    args = parser.parse_args()

    pin_cpu_platform(8)
    result = run()
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(result, indent=2, sort_keys=True))
    print(f"\nwrote {args.out}")

    if args.gate:
        from benchmark.perf_gate import gate
        verdict = gate(result["gate_metrics"])
        print(json.dumps(verdict, indent=1))
        if not verdict["pass"]:
            sys.exit("REPLAN BENCH PERF GATE FAILED")


if __name__ == "__main__":
    main()
