"""Dispatch fast-path microbenchmark: interpreter vs register file (ISSUE 2).

Same near-zero-FLOP payload as scripts/dispatch_overhead_bench.py (MLP
hidden dim 8, 8 stages on 8 single-device CPU meshes, 2 microbatches:
wall time is driver dispatch, not compute), run once per dispatch mode:

* ``sequential`` — the per-instruction interpreter (dict-keyed buffers,
  sharding resolution per RESHARD).
* ``threaded`` — the per-mesh-stream interpreter (the mode the committed
  dispatch_overhead.json artifact was measured in).
* ``registers`` — the build-time register-file lowering (flat slot
  buffers, precomputed index tuples, cached resharding executors).
* ``overlap`` — the register lowering replayed through the instruction
  dataflow graph with cross-mesh RESHARDs launched eagerly on a
  transfer pool (ISSUE 4).

A second, reshard-dominated payload compares ``registers`` vs
``overlap`` end-to-end wall clock under emulated blocking transfers
(``global_config.resharding_transfer_latency_s``): the CPU test
backend's shard moves are asynchronous in-process memcpys that never
block the driver, so the wire time a multi-host send/recv link adds is
reintroduced explicitly.  Under it the overlap replay hides most of the
per-transfer idle time inside its in-flight window while the
synchronous register replay pays it serially.

A third section measures the unified telemetry layer (ISSUE 5): the
same register-mode payload replayed with span tracing off vs on,
recording the per-instruction overhead tracing adds (the
zero-cost-when-off guard asserted by tests/runtime/test_telemetry.py).

A fourth section measures the hook-instrumented graph executor
(ISSUE 6): the register-mode payload with every per-node hook class
compiled in — span tracing on, fault sites armed (a FaultPlan whose
specs never fire), flight recorder on — vs the same payload with all
hooks off.  The hooked per-instruction number is what production
debugging costs; tests/runtime/test_unified_executor.py pins it at
< 2x the unhooked register replay.

Writes ``benchmark/results/dispatch_modes.json`` with per-mode
per-instruction latency, the speedup of the register path over both
live interpreter runs and the committed 160.8 us/inst artifact
baseline, the reshard-heavy wall-clock comparison, and the telemetry
and hooked-executor overhead sections.

Usage::

    python benchmark/bench_dispatch.py [--steps N] [--out FILE] [--trace]
                                       [--gate]

``--trace`` additionally saves the tracing-on run's merged Chrome trace
to ``benchmark/results/dispatch_trace.json`` (Perfetto-loadable).
``--gate`` checks the fresh numbers against the committed
``benchmark/results/perf_gate_baseline.json`` tolerances
(benchmark/perf_gate.py, ISSUE 9) and exits non-zero on regression.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# HISTORICAL: per_inst_us of the committed threaded-mode artifact
# (benchmark/results/dispatch_overhead.json), kept only as the fixed
# denominator of the ISSUE 2 acceptance bar (>= 5x reduction).  Threaded
# mode is a legacy interpreter path — `auto` never selects it — so this
# number must not grow new uses; compare against the live interpreter
# rows instead.
THREADED_ARTIFACT_US_HISTORICAL = 160.8

MODES = ("sequential", "threaded", "registers", "overlap")

# emulated per-transfer wire latency for the reshard-heavy payload
RESHARD_HEAVY_LATENCY_S = 0.002


def run_modes(n_steps: int = 8):
    import alpa_tpu
    from alpa_tpu import PipeshardParallel
    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)
    from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                  get_mlp_train_step)

    alpa_tpu.init(cluster="local")

    results = {}
    for mode in MODES:
        global_config.pipeline_dispatch_mode = mode
        # fresh method + state per mode: TrainState args are donated, and
        # each executable must lower under the mode being measured
        method = PipeshardParallel(
            num_micro_batches=2,
            layer_option=AutoLayerOption(layer_num=8),
            stage_option=UniformStageOption(num_stages=8))
        step = get_mlp_train_step(method, use_value_and_grad=True)
        state, batch = create_mlp_train_state_and_batch(
            batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
            num_layers=8)

        state, loss = step(state, batch)   # compile + lower
        float(loss)
        ex = step.get_last_executable()

        best = None
        for _ in range(n_steps):
            state, loss = step(state, batch)
            float(loss)                    # drain before reading stats
            st = dict(ex.last_dispatch_stats)
            if best is None or st["per_inst_us"] < best["per_inst_us"]:
                best = st
        assert best["mode"] == mode, (
            f"requested {mode!r}, executed {best['mode']!r}")
        results[mode] = best
    global_config.pipeline_dispatch_mode = "auto"

    reg = results["registers"]["per_inst_us"]
    return {
        "payload": "mlp h8 x 8 layers, bs8, 2 microbatches on 8 "
                   "single-device CPU meshes (near-zero FLOPs: wall time "
                   "is driver dispatch, not compute)",
        "n_instructions": results["registers"]["n_instructions"],
        "modes": results,
        "artifact_baseline_us": THREADED_ARTIFACT_US_HISTORICAL,
        "speedup_vs_sequential":
            results["sequential"]["per_inst_us"] / reg,
        "speedup_vs_threaded":
            results["threaded"]["per_inst_us"] / reg,
        "speedup_vs_artifact": THREADED_ARTIFACT_US_HISTORICAL / reg,
    }


def run_reshard_heavy(n_steps: int = 5,
                      latency_s: float = RESHARD_HEAVY_LATENCY_S):
    """End-to-end wall clock, registers vs overlap, on a payload where
    RESHARD dominates: every cross-mesh transfer blocks its issuing
    thread for ``latency_s`` of emulated wire time (see module
    docstring).  The register replay issues transfers inline on the
    driver, so it pays ~n_cross_mesh * latency serially; the overlap
    replay keeps up to ``overlap_window`` transfers' wire time in
    flight on pool workers."""
    import time

    import jax

    import alpa_tpu
    from alpa_tpu import PipeshardParallel
    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)
    from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                  get_mlp_train_step)

    alpa_tpu.init(cluster="local")
    prev_latency = global_config.resharding_transfer_latency_s
    prev_mode = global_config.pipeline_dispatch_mode
    global_config.resharding_transfer_latency_s = latency_s

    results = {}
    try:
        for mode in ("registers", "overlap"):
            global_config.pipeline_dispatch_mode = mode
            method = PipeshardParallel(
                num_micro_batches=4,
                layer_option=AutoLayerOption(layer_num=8),
                stage_option=UniformStageOption(num_stages=8))
            step = get_mlp_train_step(method, use_value_and_grad=True)
            state, batch = create_mlp_train_state_and_batch(
                batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
                num_layers=8)
            state, loss = step(state, batch)   # compile + lower
            float(loss)
            ex = step.get_last_executable()
            best_wall = None
            for _ in range(n_steps):
                t0 = time.perf_counter()
                state, loss = step(state, batch)
                float(loss)
                jax.block_until_ready(
                    jax.tree_util.tree_leaves(state.params))
                wall = time.perf_counter() - t0
                if best_wall is None or wall < best_wall:
                    best_wall = wall
            st = dict(ex.last_dispatch_stats)
            assert st["mode"] == mode, (
                f"requested {mode!r}, executed {st['mode']!r}")
            results[mode] = {"wall_s": best_wall, **st}
    finally:
        global_config.resharding_transfer_latency_s = prev_latency
        global_config.pipeline_dispatch_mode = prev_mode

    ovl, reg = results["overlap"], results["registers"]
    return {
        "payload": "mlp h8 x 8 layers, bs8, 4 microbatches on 8 "
                   "single-device CPU meshes; every cross-mesh transfer "
                   f"blocks {latency_s * 1e3:.1f} ms of emulated wire "
                   "latency (RESHARD dominates wall time)",
        "transfer_latency_s": latency_s,
        "n_cross_mesh": ovl["n_cross_mesh"],
        "overlap_window": ovl["overlap_window"],
        "overlap_fraction": ovl["overlap_fraction"],
        "registers_wall_s": reg["wall_s"],
        "overlap_wall_s": ovl["wall_s"],
        "overlap_vs_registers": ovl["wall_s"] / reg["wall_s"],
    }


def run_telemetry_overhead(n_steps: int = 8,
                           trace_out: "str | None" = None):
    """Register-mode per-instruction latency with span tracing off vs
    on (same payload as ``run_modes``).  The off number exercises the
    disabled fast path (one ``enabled()`` check per step); the on
    number pays a span per instruction.  ``trace_out`` saves the
    traced run's Chrome trace."""
    import alpa_tpu
    from alpa_tpu import PipeshardParallel
    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)
    from alpa_tpu.telemetry import trace as ttrace
    from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                  get_mlp_train_step)

    alpa_tpu.init(cluster="local")
    prev_mode = global_config.pipeline_dispatch_mode
    global_config.pipeline_dispatch_mode = "registers"
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=8),
        stage_option=UniformStageOption(num_stages=8))
    step = get_mlp_train_step(method, use_value_and_grad=True)
    state, batch = create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=8)
    state, loss = step(state, batch)   # compile + lower
    float(loss)
    ex = step.get_last_executable()

    def best_per_inst(state):
        best = None
        for _ in range(n_steps):
            state, loss = step(state, batch)
            float(loss)
            st = dict(ex.last_dispatch_stats)
            if best is None or st["per_inst_us"] < best["per_inst_us"]:
                best = st
        return best["per_inst_us"], state

    try:
        off_us, state = best_per_inst(state)
        prev_enabled = ttrace.set_enabled(True)
        try:
            ttrace.get_recorder().clear()
            on_us, state = best_per_inst(state)
            if trace_out is not None:
                ttrace.get_recorder().save(trace_out)
        finally:
            ttrace.set_enabled(prev_enabled)
    finally:
        global_config.pipeline_dispatch_mode = prev_mode

    return {
        "payload": "registers mode, same dispatch payload as 'modes'",
        "tracing_off_per_inst_us": off_us,
        "tracing_on_per_inst_us": on_us,
        "tracing_overhead_fraction": on_us / off_us - 1.0,
        "trace_file": (os.path.relpath(trace_out, REPO)
                       if trace_out else None),
    }


def run_hooked(n_steps: int = 8):
    """Register-mode per-instruction latency with all per-node hooks
    compiled in vs all hooks off (ISSUE 6).  Hooks-on arms every hook
    class the graph executor supports: span tracing, fault-injection
    sites (an installed FaultPlan whose spec can never fire, so only
    the instrumentation cost is measured), and the flight recorder."""
    import alpa_tpu
    from alpa_tpu import PipeshardParallel
    from alpa_tpu import fault
    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)
    from alpa_tpu.telemetry import trace as ttrace
    from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                  get_mlp_train_step)

    alpa_tpu.init(cluster="local")
    prev_mode = global_config.pipeline_dispatch_mode
    prev_flight = global_config.flight_recorder
    global_config.pipeline_dispatch_mode = "registers"
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=8),
        stage_option=UniformStageOption(num_stages=8))
    step = get_mlp_train_step(method, use_value_and_grad=True)
    state, batch = create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=8)
    state, loss = step(state, batch)   # compile + lower
    float(loss)
    ex = step.get_last_executable()

    def best_stats(state):
        best = None
        for _ in range(n_steps):
            state, loss = step(state, batch)
            float(loss)
            st = dict(ex.last_dispatch_stats)
            if best is None or st["per_inst_us"] < best["per_inst_us"]:
                best = st
        return best, state

    try:
        # hooks off: flight disabled too, so the replay takes the raw
        # closure loop (the ISSUE 5 <2% disabled-overhead path)
        global_config.flight_recorder = False
        off, state = best_stats(state)
        assert not off.get("hooks"), off

        # hooks on: trace + armed-not-firing fault plan + flight
        global_config.flight_recorder = True
        prev_enabled = ttrace.set_enabled(True)
        armed = fault.FaultPlan(
            fault.FaultSpec("stage_launch", kind="error", after=10**9))
        try:
            ttrace.get_recorder().clear()
            with armed:
                on, state = best_stats(state)
        finally:
            ttrace.set_enabled(prev_enabled)
        for h in ("trace", "fault", "flight"):
            assert h in on.get("hooks", ()), on
    finally:
        global_config.pipeline_dispatch_mode = prev_mode
        global_config.flight_recorder = prev_flight

    return {
        "payload": "registers mode, same dispatch payload as 'modes'",
        "hooks_on": list(on["hooks"]),
        "hooks_off_per_inst_us": off["per_inst_us"],
        "hooks_on_per_inst_us": on["per_inst_us"],
        "hooked_overhead_fraction":
            on["per_inst_us"] / off["per_inst_us"] - 1.0,
        "fault_hits_while_armed": armed.hits("stage_launch"),
    }


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--steps", type=int, default=8,
                        help="timed steps per mode (best-of is reported)")
    parser.add_argument("--out", default=os.path.join(
        REPO, "benchmark", "results", "dispatch_modes.json"))
    parser.add_argument("--trace", action="store_true",
                        help="save the tracing-on run's Chrome trace to "
                             "benchmark/results/dispatch_trace.json")
    parser.add_argument("--gate", action="store_true",
                        help="check results against the committed "
                             "perf_gate baseline; exit 1 on regression")
    args = parser.parse_args()

    from alpa_tpu.platform import pin_cpu_platform
    pin_cpu_platform(8)
    trace_out = None
    if args.trace:
        trace_out = os.path.join(
            REPO, "benchmark", "results", "dispatch_trace.json")
        os.makedirs(os.path.dirname(trace_out), exist_ok=True)
    report = run_modes(args.steps)
    report["reshard_heavy"] = run_reshard_heavy(args.steps)
    report["telemetry"] = run_telemetry_overhead(args.steps,
                                                 trace_out=trace_out)
    report["hooked"] = run_hooked(args.steps)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report, indent=1))
    if args.gate:
        from benchmark.perf_gate import flatten_metrics, gate
        verdict = gate(flatten_metrics(report))
        print(json.dumps(verdict, indent=1))
        if not verdict["pass"]:
            sys.exit(1)


if __name__ == "__main__":
    main()
