"""Benchmark case definitions.

Analog of ref ``benchmark/alpa/suite_manual_gpt.py`` /
``suite_auto_gpt.py`` / ``suite_auto_moe.py`` / ``suite_wresnet.py``:
named suites of benchmark cases.  Model ladders match the reference specs
(GPT 125M..76B at seq 1024, vocab 51200, ref suite_manual_gpt.py:18-26).
"""
import dataclasses
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass
class BenchmarkCase:
    name: str
    family: str               # "gpt" | "moe" | "wresnet"
    model: Dict[str, Any]
    batch_size: int
    num_micro_batches: int = 1
    # parallel method: "shard" | "pipeshard" | "dp" | "zero3"
    method: str = "shard"
    method_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    dtype: str = "bfloat16"


# ---- GPT ladder (ref suite_manual_gpt.py:18-26) ----
GPT_SPECS = {
    "125M": dict(hidden_size=768, num_layers=12, num_heads=12),
    "350M": dict(hidden_size=1024, num_layers=24, num_heads=16),
    "760M": dict(hidden_size=1536, num_layers=24, num_heads=16),
    "1.3B": dict(hidden_size=2048, num_layers=24, num_heads=32),
    "2.6B": dict(hidden_size=2560, num_layers=32, num_heads=32),
    "6.7B": dict(hidden_size=4096, num_layers=32, num_heads=32),
    # upper rungs of the ladder (ref suite_manual_gpt.py:24-26); used by
    # compile-only cases — far beyond a single chip's HBM
    "15B": dict(hidden_size=5120, num_layers=48, num_heads=40),
    "39B": dict(hidden_size=8192, num_layers=48, num_heads=64),
    "76B": dict(hidden_size=10240, num_layers=60, num_heads=80),
}


def _gpt(name, spec_name, bs, nmb=1, method="shard", seq=1024,
         attention_impl="reference", **mk):
    spec = dict(GPT_SPECS[spec_name])
    spec.update(seq_len=seq, vocab_size=51200,
                attention_impl=attention_impl)
    return BenchmarkCase(name, "gpt", spec, bs, nmb, method, mk)


suites = {
    # CPU-runnable smoke of the driver itself
    "gpt.micro": [
        BenchmarkCase("gpt-micro", "gpt",
                      dict(hidden_size=64, num_layers=2, num_heads=4,
                           seq_len=64, vocab_size=256),
                      batch_size=8, dtype="float32"),
    ],
    # quick single-chip perf check (the bench.py default case)
    "gpt.tiny": [
        _gpt("gpt-125M-bs8", "125M", 8),
        _gpt("gpt-125M-bs8-flash", "125M", 8, attention_impl="flash"),
    ],
    # ref "perf_test_manual" analog
    "gpt.perf_test_manual": [
        _gpt("gpt-125M-acc4", "125M", 32, nmb=4, method="shard"),
    ],
    "gpt.perf_test_auto": [
        _gpt("gpt-125M-auto", "125M", 16, nmb=2, method="pipeshard"),
    ],
    # long-context: flash attention's advantage grows with sequence length
    "gpt.longseq": [
        _gpt("gpt-125M-s4k-ref", "125M", 1, seq=4096,
             attention_impl="reference"),
        _gpt("gpt-125M-s4k-flash", "125M", 1, seq=4096,
             attention_impl="flash"),
    ],
    "gpt.ladder": [
        _gpt(f"gpt-{k}-bs8", k, 8) for k in ("125M", "350M")
    ],
    "moe.tiny": [
        BenchmarkCase("moe-8e", "moe",
                      dict(hidden_size=512, num_layers=8, num_heads=8,
                           seq_len=512, vocab_size=32000, num_experts=8,
                           expert_group_size=2048, moe_every=2),
                      batch_size=8),
    ],
    "wresnet.tiny": [
        BenchmarkCase("wresnet50-w2", "wresnet",
                      dict(num_layers=50, width_factor=2, num_classes=1000),
                      batch_size=32, dtype="float32"),
    ],
    # diffusion UNet (ref suite_unet.py)
    "unet.tiny": [
        BenchmarkCase("unet-64", "unet",
                      dict(block_channels=(64, 128, 256),
                           layers_per_block=2,
                           attention_resolutions=(2,), num_heads=4,
                           time_embed_dim=256),
                      batch_size=8, dtype="float32",
                      method_kwargs=dict(resolution=32)),
    ],
    # ---- auto-search suites (ref suite_auto_gpt.py / suite_auto_moe.py /
    # suite_wresnet.py): stage DP + per-stage ILP pick the plan ----
    "gpt.auto": [
        _gpt("gpt-125M-auto4", "125M", 16, nmb=4, method="auto_pipeshard",
             layer_num=4),
    ],
    "gpt.auto_micro": [
        # CPU-runnable: exercises the full auto path (profiling DB -> stage
        # DP -> ILP) on a toy model
        BenchmarkCase("gpt-micro-auto", "gpt",
                      dict(hidden_size=64, num_layers=4, num_heads=4,
                           seq_len=64, vocab_size=256),
                      batch_size=8, num_micro_batches=2,
                      method="auto_pipeshard",
                      method_kwargs=dict(layer_num=4), dtype="float32"),
    ],
    "moe.auto": [
        BenchmarkCase("moe-8e-auto", "moe",
                      dict(hidden_size=512, num_layers=8, num_heads=8,
                           seq_len=512, vocab_size=32000, num_experts=8,
                           expert_group_size=2048, moe_every=2),
                      batch_size=16, num_micro_batches=2,
                      method="auto_pipeshard",
                      method_kwargs=dict(layer_num=4)),
    ],
    "wresnet.auto": [
        BenchmarkCase("wresnet50-w2-auto", "wresnet",
                      dict(num_layers=50, width_factor=2, num_classes=1000),
                      batch_size=32, num_micro_batches=2,
                      method="auto_pipeshard",
                      method_kwargs=dict(layer_num=2), dtype="float32"),
    ],
}
