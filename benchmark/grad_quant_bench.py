"""Quantized gradient collectives benchmark (ISSUE 19): fp32 vs
int8 vs fp8 gradient quantization on the committed GPT fixture.

Three training runs from identical init (Adam, 12 steps):

- ``fp32``   — the reference loss curve, gradients untouched;
- ``int8`` / ``fp8`` — every eligible gradient leaf passes through
  :func:`reshard_codec.grad_compress` (blockwise stochastic rounding)
  each step, with the per-tensor error-feedback residual carried
  across steps exactly as the grad-accum scan carries it across
  micro-batches.

Reported per quantized run: the gradient wire-byte reduction (byte
math: ``4N`` fp32 bytes vs ``N + 4·⌈N/256⌉`` quantized), the full
loss curve, and the max per-step loss delta vs the fp32 reference.
A deterministic section compiles the 2-stage pipeshard MLP fixture
under ``grad_quantize=int8`` and reports the seven-analysis verdict's
composed end-to-end gradient bound (``numerics.max_error_bound``) —
the number the launch gate compares against ``numerics_error_budget``.

Usage:  python benchmark/grad_quant_bench.py [--out F] [--gate]
                                             [--steps N]

``--gate`` checks the wire-byte ratio, the loss deltas, and the
certified bound against ``benchmark/results/perf_gate_baseline.json``
(``gradquant.*`` entries) and exits nonzero on regression.  Writes
JSON next to the other suite results
(benchmark/results/grad_quant.json).
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_OUT = os.path.join(REPO, "benchmark", "results",
                           "grad_quant.json")

#: leaves below this are too small to quantize in the bench fixture
#: (the production default is 64 KiB; the fixture model is tiny)
MIN_BYTES = 1024


def _gpt_train_state(batch_size=4):
    import jax
    import jax.numpy as jnp
    import optax
    from flax.training import train_state

    from alpa_tpu.model.gpt_model import GPTConfig, GPTModel

    config = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                       num_heads=4, seq_len=32)
    model = GPTModel(config)
    rngkey = jax.random.PRNGKey(0)
    input_ids = jax.random.randint(rngkey, (batch_size, config.seq_len),
                                   0, config.vocab_size, jnp.int32)
    params = model.init(rngkey, input_ids)
    state = train_state.TrainState.create(
        apply_fn=model.apply, params=params,
        tx=optax.adam(learning_rate=1e-3))
    batch = {"input_ids": input_ids,
             "labels": jnp.roll(input_ids, -1, axis=1)}
    return state, batch


def _wire_bytes(params, mode):
    """(full_bytes, wire_bytes) over the eligible gradient leaves."""
    import jax
    import numpy as np

    from alpa_tpu.pipeline_parallel import reshard_codec as codec

    full = wire = 0.0
    for leaf in jax.tree_util.tree_leaves(params):
        nbytes = float(np.prod(leaf.shape)) * leaf.dtype.itemsize \
            if leaf.shape else leaf.dtype.itemsize
        if codec.grad_eligible(tuple(leaf.shape), leaf.dtype, mode,
                               min_bytes=MIN_BYTES):
            full += nbytes
            wire += codec.grad_wire_bytes(tuple(leaf.shape),
                                          leaf.dtype.itemsize, mode)
    return full, wire


def train_run(mode, n_steps):
    """One training run; mode 'fp32' = reference, else grad codec."""
    import jax
    import jax.numpy as jnp

    from alpa_tpu.model.model_util import gpt_lm_loss
    from alpa_tpu.pipeline_parallel import reshard_codec as codec

    state, batch = _gpt_train_state()

    @jax.jit
    def grads_of(params):
        def loss_fn(p):
            return gpt_lm_loss(state.apply_fn, p, batch)
        return jax.value_and_grad(loss_fn)(params)

    def quantize(grads, residuals, key):
        flat, treedef = jax.tree_util.tree_flatten(grads)
        keys = jax.random.split(key, len(flat))
        new_flat, new_res = [], []
        for i, (g, r) in enumerate(zip(flat, residuals)):
            if codec.grad_eligible(tuple(g.shape), g.dtype, mode,
                                   min_bytes=MIN_BYTES):
                g_hat, r_new = codec.grad_compress(g, mode, keys[i],
                                                   residual=r)
                new_flat.append(g_hat)
                new_res.append(r_new)
            else:
                new_flat.append(g)
                new_res.append(r)
        return jax.tree_util.tree_unflatten(treedef, new_flat), new_res

    residuals = [None] * len(
        jax.tree_util.tree_leaves(state.params))
    losses = []
    for step in range(n_steps):
        loss, grads = grads_of(state.params)
        if mode != "fp32":
            key = jax.random.fold_in(jax.random.PRNGKey(19), step)
            grads, residuals = quantize(grads, residuals, key)
        state = state.apply_gradients(grads=grads)
        losses.append(float(loss))

    out = {"mode": mode, "losses": [round(x, 6) for x in losses],
           "final_loss": round(losses[-1], 6)}
    if mode != "fp32":
        full, wire = _wire_bytes(state.params, mode)
        out["grad_bytes_full"] = full
        out["grad_bytes_wire"] = wire
        out["wire_ratio"] = round(full / max(wire, 1.0), 4)
        res_norm = float(jnp.sqrt(sum(
            jnp.sum(jnp.square(r)) for r in residuals
            if r is not None)))
        out["error_feedback_norm"] = round(res_norm, 6)
        codec.note_error_feedback_norm(res_norm)
    return out


def bench_pipeshard_certified() -> dict:
    """Deterministic: the 2-stage pipeshard MLP fixture compiled under
    ``grad_quantize=int8`` — the seven-analysis verdict composes the
    end-to-end gradient bound the launch gate enforces."""
    from alpa_tpu.global_env import global_config
    from alpa_tpu.parallel_method import PipeshardParallel
    from alpa_tpu.pipeline_parallel.layer_construction import (
        ManualLayerOption)
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)
    from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
    from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                  get_mlp_train_step)

    global_config.grad_quantize = "int8"
    global_config.grad_quantize_min_bytes = 0
    try:
        method = PipeshardParallel(
            num_micro_batches=2,
            layer_option=ManualLayerOption(),
            stage_option=UniformStageOption(num_stages=2),
            default_auto_sharding_option=AutoShardingOption(
                zero_stage="0"))
        state, batch = create_mlp_train_state_and_batch(
            batch_size=64, num_layers=4, manual_pipeline_layer=True)
        pstep = get_mlp_train_step(method, use_value_and_grad=True)
        state, _ = pstep(state, batch)
        v = pstep.get_last_executable().get_plan_verdict()
    finally:
        global_config.grad_quantize = "off"
        global_config.grad_quantize_min_bytes = 65536
    num = v.stats.get("numerics") or {}
    return {
        "ok": bool(v.ok),
        "certified_bound": num.get("max_error_bound", 0.0),
        "budget": num.get("budget"),
        "lossy_edges": num.get("lossy_edges", {}),
    }


def run(n_steps: int) -> dict:
    from alpa_tpu.pipeline_parallel.reshard_codec import have_fp8

    modes = ["fp32", "int8"] + (["fp8"] if have_fp8() else [])
    runs = {m: train_run(m, n_steps) for m in modes}
    certified = bench_pipeshard_certified()

    gate_metrics = {}
    ref = runs["fp32"]["losses"]
    for m in modes[1:]:
        deltas = [abs(a - b) for a, b in zip(runs[m]["losses"], ref)]
        runs[m]["loss_max_delta"] = round(max(deltas), 6)
        gate_metrics[f"gradquant.loss_delta_{m}"] = max(deltas)
        gate_metrics[f"gradquant.wire_ratio_{m}"] = \
            runs[m]["wire_ratio"]
    gate_metrics["gradquant.certified_bound"] = \
        certified["certified_bound"]

    return {"runs": runs, "certified": certified,
            "n_steps": n_steps,
            "gate_metrics": {k: round(v, 6)
                             for k, v in gate_metrics.items()}}


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default=DEFAULT_OUT)
    parser.add_argument("--steps", type=int, default=12)
    parser.add_argument("--gate", action="store_true",
                        help="check wire-byte ratio, loss deltas and "
                             "the certified bound against the "
                             "committed perf-gate baseline")
    args = parser.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if os.environ["JAX_PLATFORMS"] == "cpu":
        # the pipeshard fixture wants 2 stages x a dp submesh
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8")
    import alpa_tpu
    alpa_tpu.init("local")

    result = run(args.steps)
    result["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result, indent=2))
    print(f"\nwrote {args.out}")

    if args.gate:
        from benchmark.perf_gate import gate
        verdict = gate(result["gate_metrics"])
        print(json.dumps(verdict, indent=1))
        if not verdict["pass"]:
            sys.exit("GRAD QUANT BENCH PERF GATE FAILED")


if __name__ == "__main__":
    main()
