"""Cross-mesh resharding microbenchmark.

Analog of ref ``benchmark/alpa/resharding/`` (send/recv vs broadcast
microbenchmarks over NCCL): times every execution mode of
``ReshardingTask`` — runtime-carried ``device_put``, per-tile routed
``tiled`` transfers, and ``broadcast`` fan-out — across a matrix of
(shape, src sharding, dst sharding) cases, and reports planned vs
executed bytes and effective bandwidth.

Runs anywhere: on a virtual CPU mesh (default; set
``--devices N`` to force ``xla_force_host_platform_device_count``) or on
a real multi-chip TPU slice.

Two ISSUE 4 sweeps ride along and write
``benchmark/results/resharding_overlap.json``:

* ``loadbalance`` — for every case in the matrix, the planner's
  max-link objective (busiest per-device egress/ingress link) under
  balanced source selection + broadcast fan-out routing vs the naive
  first-holder baseline.  The fan-out case (rowshard -> replicated)
  shows the headline reduction: naive routing lands every unique tile
  on the replica group's first holder.
* ``overlap`` — end-to-end pipeshard wall clock, overlap vs register
  dispatch, under emulated blocking transfers (the CPU backend's
  copies are async in-process memcpys, so wire latency is
  reintroduced explicitly; see bench_dispatch.run_reshard_heavy).

The ISSUE 7 sweeps write ``benchmark/results/resharding_collectives.json``:

* ``--strategy`` — per case, wall clock of every eligible lowering
  strategy (direct_p2p vs slice_all_gather / all_to_all /
  reduce_scatter_gather executors) under the ``link`` wire model at
  0.5 ms and 2 ms emulated per-message latency, plus the cost model's
  auto choice.
* ``--quantize`` — the int8 (and fp8 when available) codec on an fp32
  edge: wire-byte reduction vs lossless, wall clock vs direct, and the
  observed round-trip error against the documented bound.
* warm-restart replay: per-edge strategy decisions are re-planned from
  a fresh process-state against the same disk compile cache and must
  reproduce an identical plan fingerprint with every edge a cache hit.

Usage:
  python benchmark/resharding_bench.py [--devices 8] [--mb 64]
      [--json benchmark/results/resharding_overlap.json]
      [--collectives-json benchmark/results/resharding_collectives.json]
      [--strategy sweep|<name>] [--quantize sweep|int8|fp8|off]
      [--skip-overlap] [--skip-strategy] [--gate]

``--gate`` checks the overlap sweep against the committed
``benchmark/results/perf_gate_baseline.json`` tolerances
(benchmark/perf_gate.py, ISSUE 9) and exits non-zero on regression.
"""
import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

REPO = str(Path(__file__).parent.parent)


def sweep_loadbalance(shape, src_mesh, dst_mesh, cases):
    """Planner max-link objective, balanced vs naive, per case (the
    allgather rewrite is disabled so the sweep isolates routing)."""
    from jax.sharding import NamedSharding

    from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
        plan_resharding)

    out = {}
    for name, src_spec, dst_spec in cases:
        src_sh = NamedSharding(src_mesh, src_spec)
        dst_sh = NamedSharding(dst_mesh, dst_spec)
        spec = plan_resharding(shape, 4, src_sh, dst_sh,
                               allow_allgather_rewrite=False,
                               loadbalance=True)
        bal = spec.max_link_bytes_broadcast
        naive = spec.max_link_bytes_broadcast_naive
        out[name] = {
            "transfer_bytes": spec.transfer_bytes,
            "broadcast_bytes": spec.broadcast_bytes,
            "max_link_send_recv": {
                "balanced": spec.max_link_bytes,
                "naive": spec.max_link_bytes_naive,
            },
            "max_link_broadcast": {
                "balanced": bal,
                "naive": naive,
                "reduction": (naive / bal) if bal else 1.0,
            },
        }
    return out


def _time_transfer(transfer, val, niter):
    """Best-of-niter wall clock of one edge executor (seconds)."""
    import jax
    out = transfer(val)              # warmup: compiles any jitted leg
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(niter):
        tic = time.perf_counter()
        out = transfer(val)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - tic)
    return best


def sweep_strategies(shape, src_mesh, dst_mesh, cases, niter,
                     latencies, which="sweep"):
    """Wall clock of every eligible strategy per case under the ``link``
    wire model (ISSUE 7 acceptance: collectives must beat direct_p2p on
    the fan-out and transpose-shaped edges at 2 ms)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel import cross_mesh_resharding as cmr

    class _Aval:
        def __init__(self, s):
            self.shape = s
            self.dtype = np.dtype(np.float32)

    x = jnp.arange(int(np.prod(shape)), dtype=jnp.float32).reshape(shape)
    out = {}
    prev = (global_config.resharding_wire_model,
            global_config.resharding_transfer_latency_s,
            global_config.reshard_strategy)
    try:
        global_config.resharding_wire_model = "link"
        for lat in latencies:
            global_config.resharding_transfer_latency_s = lat
            key = f"latency_{lat * 1e3:g}ms"
            out[key] = {}
            for name, src_spec, dst_spec in cases:
                src_sh = NamedSharding(src_mesh, src_spec)
                dst_sh = NamedSharding(dst_mesh, dst_spec)
                val = jax.device_put(x, src_sh)
                global_config.reshard_strategy = "auto"
                auto, _costs, opts = cmr.choose_strategy(
                    shape, 4, src_sh, dst_sh)
                entry = {"auto_choice": auto, "wall_ms": {}, "wire": {}}
                for strat, o in opts.items():
                    if which not in ("sweep", strat):
                        continue
                    global_config.reshard_strategy = strat
                    t = cmr.make_transfer(_Aval(shape), src_sh, dst_sh,
                                          cross=True)
                    got = getattr(t, "strategy", "direct_p2p")
                    assert got == strat, (name, strat, got)
                    ref = np.asarray(x)
                    res = t(val)
                    np.testing.assert_array_equal(np.asarray(res), ref)
                    st = o["stats"]
                    entry["wall_ms"][strat] = round(
                        _time_transfer(t, val, niter) * 1e3, 3)
                    entry["wire"][strat] = {
                        "max_link_messages": st["max_link_messages"],
                        "max_link_bytes": st["max_link_bytes"],
                        "total_bytes": st["total_bytes"],
                    }
                wall = entry["wall_ms"]
                if "direct_p2p" in wall and len(wall) > 1:
                    best = min((v, k) for k, v in wall.items())
                    entry["best"] = best[1]
                    entry["speedup_vs_direct"] = round(
                        wall["direct_p2p"] / best[0], 2) if best[0] else 1.0
                out[key][name] = entry
    finally:
        (global_config.resharding_wire_model,
         global_config.resharding_transfer_latency_s,
         global_config.reshard_strategy) = prev
    return out


def sweep_quantize(shape, src_mesh, dst_mesh, niter, which="sweep"):
    """The transfer codec on the fan-out fp32 edge: wire-byte reduction,
    wall clock vs lossless direct at 2 ms, observed error vs bound."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel import cross_mesh_resharding as cmr
    from alpa_tpu.pipeline_parallel import reshard_codec as codec

    class _Aval:
        def __init__(self, s):
            self.shape = s
            self.dtype = np.dtype(np.float32)

    src_sh = NamedSharding(src_mesh, P("d", None))
    dst_sh = NamedSharding(dst_mesh, P(None, None))
    rng = np.random.default_rng(0)
    xn = rng.standard_normal(shape).astype(np.float32)
    x = jax.device_put(jnp.asarray(xn), src_sh)
    nbytes = xn.nbytes
    modes = [m for m in ("int8", "fp8")
             if which in ("sweep", m) and
             (m != "fp8" or codec.have_fp8())]
    prev = (global_config.resharding_wire_model,
            global_config.resharding_transfer_latency_s,
            global_config.reshard_strategy)
    out = {"case": "rowshard->replicated", "payload_bytes": nbytes,
           "codecs": {}}
    try:
        global_config.resharding_wire_model = "link"
        global_config.resharding_transfer_latency_s = 0.002
        global_config.reshard_strategy = "direct_p2p"
        direct = cmr.make_transfer(_Aval(shape), src_sh, dst_sh,
                                   cross=True)
        out["direct_wall_ms"] = round(
            _time_transfer(direct, x, niter) * 1e3, 3)
        for mode in modes:
            t = codec.maybe_quantized_transfer(_Aval(shape), src_sh,
                                               dst_sh, mode)
            assert t is not None
            res = np.asarray(t(x))
            # per-block error against the documented bound
            flat = xn.reshape(-1)
            nb = -(-flat.size // codec.BLOCK)
            blocks = np.pad(flat, (0, nb * codec.BLOCK - flat.size)) \
                .reshape(nb, codec.BLOCK)
            amax = np.abs(blocks).max(axis=1)
            err = np.abs(res.reshape(-1) - flat)
            err_blocks = np.pad(err, (0, nb * codec.BLOCK - err.size)) \
                .reshape(nb, codec.BLOCK).max(axis=1)
            frac = 1 / 254 if mode == "int8" else 0.07
            wb = t.wire_nbytes
            out["codecs"][mode] = {
                "wire_bytes": wb,
                "reduction_vs_fp32": round(nbytes / wb, 2),
                "wall_ms": round(_time_transfer(t, x, niter) * 1e3, 3),
                "max_abs_err": float(err.max()),
                "bound_frac_of_block_max": frac,
                "within_bound": bool(
                    (err_blocks <= amax * frac + 1e-6).all()),
            }
    finally:
        (global_config.resharding_wire_model,
         global_config.resharding_transfer_latency_s,
         global_config.reshard_strategy) = prev
    return out


def check_warm_restart(shape, src_mesh, dst_mesh, cases):
    """Plan every case twice against one disk compile cache with the
    in-memory tier dropped in between: the second pass must be all
    cache hits with an identical plan fingerprint."""
    import tempfile

    from jax.sharding import NamedSharding

    from alpa_tpu.compile_cache import reset_compile_cache
    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel import cross_mesh_resharding as cmr

    prev = (global_config.compile_cache_dir,
            global_config.resharding_wire_model,
            global_config.resharding_transfer_latency_s)
    tmp = tempfile.mkdtemp(prefix="reshard_cache_")
    try:
        global_config.compile_cache_dir = tmp
        global_config.resharding_wire_model = "link"
        global_config.resharding_transfer_latency_s = 0.002
        reset_compile_cache()

        def plan_all():
            cmr.reset_recent_plans()
            specs = [cmr.plan_resharding(
                shape, 4, NamedSharding(src_mesh, s),
                NamedSharding(dst_mesh, d)) for _, s, d in cases]
            return specs, cmr.strategy_plan_fingerprint()

        cold_specs, cold_fp = plan_all()
        # simulate a restart: drop the in-memory tier, keep the disk
        reset_compile_cache()
        warm_specs, warm_fp = plan_all()
        return {
            "edges": len(cases),
            "cold_fingerprint": cold_fp,
            "warm_fingerprint": warm_fp,
            "identical": cold_fp == warm_fp,
            "warm_all_cached": all(s.strategy_cached
                                   for s in warm_specs),
            "strategies": {name: s.strategy for (name, _, _), s in
                           zip(cases, cold_specs)},
        }
    finally:
        (global_config.compile_cache_dir,
         global_config.resharding_wire_model,
         global_config.resharding_transfer_latency_s) = prev
        reset_compile_cache()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--devices", type=int, default=8,
                        help="virtual CPU device count (ignored on TPU)")
    parser.add_argument("--mb", type=int, default=16,
                        help="approx tensor size in MB")
    parser.add_argument("--niter", type=int, default=5)
    parser.add_argument("--dump", default="resharding_results.tsv")
    parser.add_argument("--json", default=os.path.join(
        REPO, "benchmark", "results", "resharding_overlap.json"))
    parser.add_argument("--skip-overlap", action="store_true",
                        help="skip the pipeshard overlap-dispatch sweep "
                             "(it compiles a full pipelined step)")
    parser.add_argument("--collectives-json", default=os.path.join(
        REPO, "benchmark", "results", "resharding_collectives.json"))
    parser.add_argument("--strategy", default="sweep",
                        help="strategy sweep: 'sweep' (all eligible), a "
                             "single strategy name, or 'off'")
    parser.add_argument("--quantize", default="sweep",
                        choices=("sweep", "int8", "fp8", "off"),
                        help="codec sweep: both codecs, one, or off")
    parser.add_argument("--skip-strategy", action="store_true",
                        help="skip the ISSUE 7 collective sweeps")
    parser.add_argument("--gate", action="store_true",
                        help="check the overlap sweep against the "
                             "committed perf_gate baseline; exit 1 on "
                             "regression")
    args = parser.parse_args()

    if os.environ.get("JAX_PLATFORMS") != "tpu":
        # default to the virtual CPU mesh; pass JAX_PLATFORMS=tpu to
        # bench a real multi-chip slice
        from alpa_tpu.platform import pin_cpu_platform
        pin_cpu_platform(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
        ReshardingTask, plan_resharding)
    from alpa_tpu.util import write_tsv

    devices = jax.devices()
    n = len(devices)
    assert n >= 4, f"need >= 4 devices, have {n}"
    half = n // 2
    src_mesh = Mesh(np.array(devices[:half]), ("d",))
    dst_mesh = Mesh(np.array(devices[half:]), ("d",))

    # rows*cols float32 ~= args.mb MB
    rows = max(half * 4, int((args.mb * 1e6 / 4) ** 0.5) // 8 * 8)
    cols = rows
    shape = (rows, cols)
    x = jnp.arange(rows * cols, dtype=jnp.float32).reshape(shape)

    cases = [
        # (name, src spec, dst spec)
        ("rowshard->rowshard", P("d", None), P("d", None)),
        ("rowshard->colshard", P("d", None), P(None, "d")),
        ("rowshard->replicated", P("d", None), P(None, None)),
        ("replicated->rowshard", P(None, None), P("d", None)),
        ("colshard->rowshard", P(None, "d"), P("d", None)),
    ]

    for name, src_spec, dst_spec in cases:
        src_sh = NamedSharding(src_mesh, src_spec)
        dst_sh = NamedSharding(dst_mesh, dst_spec)
        src = jax.device_put(x, src_sh)
        plan = plan_resharding(shape, 4, src_sh, dst_sh)
        for mode in ("device_put", "tiled", "broadcast"):
            task = ReshardingTask(plan, dst_sh, mode)
            out = task.run(src)          # warmup / correctness
            jax.block_until_ready(out)
            np.testing.assert_allclose(np.asarray(out), np.asarray(x))
            tic = time.perf_counter()
            for _ in range(args.niter):
                out = task.run(src)
                jax.block_until_ready(out)
            dt = (time.perf_counter() - tic) / args.niter
            rep = task.last_report
            moved = (rep.cross_mesh_bytes
                     if rep and rep.mode != "device_put"
                     else plan.transfer_bytes)
            row = {
                "case": name,
                "mode": mode,
                "planned_MB": round(plan.transfer_bytes / 1e6, 2),
                "moved_MB": round(moved / 1e6, 2),
                "intra_MB": round(rep.intra_mesh_bytes / 1e6, 2)
                            if rep else 0.0,
                "ms": round(dt * 1e3, 2),
                "GBps": round(moved / dt / 1e9, 2),
                "allgather_rewrite": plan.allgather_rewrite,
            }
            write_tsv(list(row.keys()), list(row.values()), args.dump)

    # -- ISSUE 4 sweeps -> resharding_overlap.json --------------------
    report = {
        "payload": f"{rows}x{cols} f32 across two {half}-device meshes",
        "loadbalance": sweep_loadbalance(shape, src_mesh, dst_mesh,
                                         cases),
    }
    if not args.skip_overlap:
        from benchmark.bench_dispatch import run_reshard_heavy
        report["overlap"] = {
            "note": "end-to-end pipeshard wall clock, overlap vs "
                    "registers dispatch, under emulated per-transfer "
                    "wire latency (the CPU backend's copies are async "
                    "in-process memcpys and never block the driver, so "
                    "without it both modes tie)",
            "latency_0.5ms": run_reshard_heavy(args.niter,
                                               latency_s=0.0005),
            "latency_2ms": run_reshard_heavy(args.niter,
                                             latency_s=0.002),
        }
    os.makedirs(os.path.dirname(args.json), exist_ok=True)
    with open(args.json, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report, indent=1))
    if args.gate:
        from benchmark.perf_gate import flatten_metrics, gate
        verdict = gate(flatten_metrics(report))
        print(json.dumps(verdict, indent=1))
        if not verdict["pass"]:
            sys.exit(1)

    # -- ISSUE 7 sweeps -> resharding_collectives.json ----------------
    if args.skip_strategy or args.strategy == "off":
        return
    # smaller payload than the mode matrix: the sweeps compare emulated
    # wire idles (0.5-2 ms/message), which a multi-MB CPU memcpy would
    # drown out
    srows = max(half * 4, 1024 // 8 * 8)
    sshape = (srows, srows)
    col_report = {
        "payload": f"{srows}x{srows} f32 across two {half}-device "
                   "meshes",
        "wire_model": "link (idle = latency x busiest-link messages "
                      "per transfer)",
        "strategy_sweep": sweep_strategies(
            sshape, src_mesh, dst_mesh, cases, args.niter,
            latencies=(0.0005, 0.002), which=args.strategy),
        "warm_restart": check_warm_restart(sshape, src_mesh, dst_mesh,
                                           cases),
    }
    if args.quantize != "off":
        col_report["quantize"] = sweep_quantize(
            sshape, src_mesh, dst_mesh, args.niter, which=args.quantize)
    os.makedirs(os.path.dirname(args.collectives_json), exist_ok=True)
    with open(args.collectives_json, "w", encoding="utf-8") as f:
        json.dump(col_report, f, indent=1)
    print(json.dumps(col_report, indent=1))


if __name__ == "__main__":
    main()
