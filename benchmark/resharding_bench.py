"""Cross-mesh resharding microbenchmark.

Analog of ref ``benchmark/alpa/resharding/`` (send/recv vs broadcast
microbenchmarks over NCCL): times every execution mode of
``ReshardingTask`` — runtime-carried ``device_put``, per-tile routed
``tiled`` transfers, and ``broadcast`` fan-out — across a matrix of
(shape, src sharding, dst sharding) cases, and reports planned vs
executed bytes and effective bandwidth.

Runs anywhere: on a virtual CPU mesh (default; set
``--devices N`` to force ``xla_force_host_platform_device_count``) or on
a real multi-chip TPU slice.

Two ISSUE 4 sweeps ride along and write
``benchmark/results/resharding_overlap.json``:

* ``loadbalance`` — for every case in the matrix, the planner's
  max-link objective (busiest per-device egress/ingress link) under
  balanced source selection + broadcast fan-out routing vs the naive
  first-holder baseline.  The fan-out case (rowshard -> replicated)
  shows the headline reduction: naive routing lands every unique tile
  on the replica group's first holder.
* ``overlap`` — end-to-end pipeshard wall clock, overlap vs register
  dispatch, under emulated blocking transfers (the CPU backend's
  copies are async in-process memcpys, so wire latency is
  reintroduced explicitly; see bench_dispatch.run_reshard_heavy).

Usage:
  python benchmark/resharding_bench.py [--devices 8] [--mb 64]
      [--json benchmark/results/resharding_overlap.json]
      [--skip-overlap]
"""
import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

REPO = str(Path(__file__).parent.parent)


def sweep_loadbalance(shape, src_mesh, dst_mesh, cases):
    """Planner max-link objective, balanced vs naive, per case (the
    allgather rewrite is disabled so the sweep isolates routing)."""
    from jax.sharding import NamedSharding

    from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
        plan_resharding)

    out = {}
    for name, src_spec, dst_spec in cases:
        src_sh = NamedSharding(src_mesh, src_spec)
        dst_sh = NamedSharding(dst_mesh, dst_spec)
        spec = plan_resharding(shape, 4, src_sh, dst_sh,
                               allow_allgather_rewrite=False,
                               loadbalance=True)
        bal = spec.max_link_bytes_broadcast
        naive = spec.max_link_bytes_broadcast_naive
        out[name] = {
            "transfer_bytes": spec.transfer_bytes,
            "broadcast_bytes": spec.broadcast_bytes,
            "max_link_send_recv": {
                "balanced": spec.max_link_bytes,
                "naive": spec.max_link_bytes_naive,
            },
            "max_link_broadcast": {
                "balanced": bal,
                "naive": naive,
                "reduction": (naive / bal) if bal else 1.0,
            },
        }
    return out


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--devices", type=int, default=8,
                        help="virtual CPU device count (ignored on TPU)")
    parser.add_argument("--mb", type=int, default=16,
                        help="approx tensor size in MB")
    parser.add_argument("--niter", type=int, default=5)
    parser.add_argument("--dump", default="resharding_results.tsv")
    parser.add_argument("--json", default=os.path.join(
        REPO, "benchmark", "results", "resharding_overlap.json"))
    parser.add_argument("--skip-overlap", action="store_true",
                        help="skip the pipeshard overlap-dispatch sweep "
                             "(it compiles a full pipelined step)")
    args = parser.parse_args()

    if os.environ.get("JAX_PLATFORMS") != "tpu":
        # default to the virtual CPU mesh; pass JAX_PLATFORMS=tpu to
        # bench a real multi-chip slice
        from alpa_tpu.platform import pin_cpu_platform
        pin_cpu_platform(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
        ReshardingTask, plan_resharding)
    from alpa_tpu.util import write_tsv

    devices = jax.devices()
    n = len(devices)
    assert n >= 4, f"need >= 4 devices, have {n}"
    half = n // 2
    src_mesh = Mesh(np.array(devices[:half]), ("d",))
    dst_mesh = Mesh(np.array(devices[half:]), ("d",))

    # rows*cols float32 ~= args.mb MB
    rows = max(half * 4, int((args.mb * 1e6 / 4) ** 0.5) // 8 * 8)
    cols = rows
    shape = (rows, cols)
    x = jnp.arange(rows * cols, dtype=jnp.float32).reshape(shape)

    cases = [
        # (name, src spec, dst spec)
        ("rowshard->rowshard", P("d", None), P("d", None)),
        ("rowshard->colshard", P("d", None), P(None, "d")),
        ("rowshard->replicated", P("d", None), P(None, None)),
        ("replicated->rowshard", P(None, None), P("d", None)),
        ("colshard->rowshard", P(None, "d"), P("d", None)),
    ]

    for name, src_spec, dst_spec in cases:
        src_sh = NamedSharding(src_mesh, src_spec)
        dst_sh = NamedSharding(dst_mesh, dst_spec)
        src = jax.device_put(x, src_sh)
        plan = plan_resharding(shape, 4, src_sh, dst_sh)
        for mode in ("device_put", "tiled", "broadcast"):
            task = ReshardingTask(plan, dst_sh, mode)
            out = task.run(src)          # warmup / correctness
            jax.block_until_ready(out)
            np.testing.assert_allclose(np.asarray(out), np.asarray(x))
            tic = time.perf_counter()
            for _ in range(args.niter):
                out = task.run(src)
                jax.block_until_ready(out)
            dt = (time.perf_counter() - tic) / args.niter
            rep = task.last_report
            moved = (rep.cross_mesh_bytes
                     if rep and rep.mode != "device_put"
                     else plan.transfer_bytes)
            row = {
                "case": name,
                "mode": mode,
                "planned_MB": round(plan.transfer_bytes / 1e6, 2),
                "moved_MB": round(moved / 1e6, 2),
                "intra_MB": round(rep.intra_mesh_bytes / 1e6, 2)
                            if rep else 0.0,
                "ms": round(dt * 1e3, 2),
                "GBps": round(moved / dt / 1e9, 2),
                "allgather_rewrite": plan.allgather_rewrite,
            }
            write_tsv(list(row.keys()), list(row.values()), args.dump)

    # -- ISSUE 4 sweeps -> resharding_overlap.json --------------------
    report = {
        "payload": f"{rows}x{cols} f32 across two {half}-device meshes",
        "loadbalance": sweep_loadbalance(shape, src_mesh, dst_mesh,
                                         cases),
    }
    if not args.skip_overlap:
        from benchmark.bench_dispatch import run_reshard_heavy
        report["overlap"] = {
            "note": "end-to-end pipeshard wall clock, overlap vs "
                    "registers dispatch, under emulated per-transfer "
                    "wire latency (the CPU backend's copies are async "
                    "in-process memcpys and never block the driver, so "
                    "without it both modes tie)",
            "latency_0.5ms": run_reshard_heavy(args.niter,
                                               latency_s=0.0005),
            "latency_2ms": run_reshard_heavy(args.niter,
                                             latency_s=0.002),
        }
    os.makedirs(os.path.dirname(args.json), exist_ok=True)
    with open(args.json, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
