"""Fleet-serving load bench (ISSUE 11): open-loop generator driving the
paged-KV engine and the multi-replica router.

Three scenarios, one committed artifact
(``benchmark/results/serving_load.json``):

* ``reuse``   — Poisson arrivals with a shared-prefix / unique-prompt
  mix against (a) the unpaged engine and (b) the paged engine with
  cross-request prefix reuse; records TTFT p50/p99 per engine, output
  tokens/s, and the pool's prefix-hit rate.  The shared-prefix
  population reuses a long common prefix, so the paged TTFT p99 should
  beat unpaged (the hit path prefills only the suffix).
* ``router``  — 2 local replicas, one artificially degraded (slowed);
  drives the same trace through ``least_loaded`` and ``round_robin``
  and records per-policy p99 plus failure counts (pinned 0), then a
  burst against a tiny shed threshold to measure shed-then-admit.
* ``rolling`` — hammer 2 replicas while ``Router.rolling_reload`` swaps
  weights one replica at a time; records completed requests and
  failures (pinned 0).
* ``disagg`` (``--disagg``, ISSUE 18; own artifact
  ``benchmark/results/serving_disagg.json``) — mixed long-prefill /
  short-decode Poisson workload against (a) a monolithic 2-replica
  fleet and (b) a disaggregated 1-prefill + 2-decode fleet, with one
  decode replica KILLED mid-run.  Records decode inter-token p99 per
  fleet (long chunked prefills convoy the monolithic engine's decode
  ticks; the disaggregated decode pool only pays an ingest scatter),
  TTFT p99, handoff KB/request, re-ingest count, and failures across
  the kill (pinned 0: no handoff is ever dropped).

    python benchmark/serving_load_bench.py [--requests 32] [--seed 0]
        [--out benchmark/results/serving_load.json] [--gate] [--disagg]

``--gate`` flattens the scenario metrics under ``serving.*`` and checks
them against ``benchmark/results/perf_gate_baseline.json``
(``benchmark/perf_gate.py``).
"""
import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
DEFAULT_OUT = os.path.join(REPO, "benchmark", "results",
                           "serving_load.json")


def _tiny_generator(seq_len=128, prefill_chunk=16, hidden=64, layers=2):
    from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
    from alpa_tpu.serve.generation import Generator
    cfg = GPTConfig(hidden_size=hidden, num_layers=layers, num_heads=4,
                    seq_len=seq_len, vocab_size=256)
    model, params = init_gpt_real(cfg, 1)
    return (Generator(model, params, cfg, prefill_chunk=prefill_chunk),
            model, params, cfg)


def _percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * (len(xs) - 1) + 0.999))]


def make_trace(n, rng, shared_frac=0.6, prefix_len=192, suffix_len=12,
               rate_hz=4.0):
    """Open-loop request trace: Poisson arrival offsets, with
    ``shared_frac`` of prompts sharing one long prefix."""
    prefix = rng.randint(2, 250, size=prefix_len).astype(np.int32)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_hz, size=n))
    reqs = []
    for i in range(n):
        suffix = rng.randint(2, 250, size=suffix_len).astype(np.int32)
        shared = bool(rng.random() < shared_frac)
        if shared:
            prompt = np.concatenate([prefix, suffix])
        else:
            prompt = rng.randint(2, 250,
                                 size=prefix_len + suffix_len
                                 ).astype(np.int32)
        reqs.append((float(arrivals[i]), prompt, shared))
    return reqs


def _drive_engine(engine, trace, max_new):
    """Replay the trace open-loop; returns per-request
    (is_shared, ttft_s) samples plus errors and wall time."""
    from alpa_tpu.serve.generation import GenerationConfig
    cfg = GenerationConfig(max_new_tokens=max_new, temperature=0.0)
    samples, errors = [], []
    lock = threading.Lock()
    t0 = time.perf_counter()

    def run(arrival, prompt, shared):
        wait = arrival - (time.perf_counter() - t0)
        if wait > 0:
            time.sleep(wait)
        sent = time.perf_counter()
        try:
            it = engine.submit_stream(prompt, cfg)
            first = None
            for _ in it:
                if first is None:
                    first = time.perf_counter() - sent
            with lock:
                samples.append((shared, first))
        except Exception as e:  # pylint: disable=broad-except
            with lock:
                errors.append(repr(e))

    threads = [threading.Thread(target=run, args=(a, p, s))
               for a, p, s in trace]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return samples, errors, wall


def bench_reuse(n_requests, seed, max_new=8):
    from alpa_tpu.serve.engine import ContinuousBatchingEngine
    from alpa_tpu.serve.kv_cache import KVBlockPool

    from alpa_tpu.serve.generation import GenerationConfig

    rng = np.random.RandomState(seed)
    trace = make_trace(n_requests, rng)
    out = {}
    for mode in ("unpaged", "paged"):
        # prefill-heavy model: long prompts so TTFT is dominated by the
        # work prefix reuse removes
        gen, _m, _p, _c = _tiny_generator(seq_len=256, prefill_chunk=32,
                                          hidden=256, layers=4)
        pool = None
        if mode == "paged":
            # sized so the whole trace's chains stay cached: partial
            # evictions would shift the suffix-prefill start offset and
            # recompile mid-run, polluting the TTFT percentiles
            pool = KVBlockPool.for_generator(gen, max_batch=4,
                                             block_size=16,
                                             num_blocks=640,
                                             prefix_reuse=True)
        engine = ContinuousBatchingEngine(
            gen, max_batch=4, prompt_bucket=gen.prompt_buckets[-1],
            kv_pool=pool)
        # warm ALL compile paths outside the measured window: the miss
        # (bucketed) path, and — for the paged engine — the reuse-hit
        # path (gather + chunked suffix prefill + scatter), by sending
        # a same-shape warmup prompt twice
        warm = np.concatenate([trace[0][1][:-1],
                               np.array([255], np.int32)])
        wcfg = GenerationConfig(max_new_tokens=2, temperature=0.0)
        engine.submit(warm, wcfg)
        engine.submit(warm, wcfg)
        warm_stats = pool.stats() if pool is not None else {}
        samples, errors, wall = _drive_engine(engine, trace, max_new)
        stats = pool.stats() if pool is not None else {}
        engine.shutdown()
        ttfts = [t for _s, t in samples]
        shared_ttfts = [t for s, t in samples if s]
        out[mode] = {
            "requests_ok": len(ttfts),
            "errors": len(errors),
            "ttft_p50_ms": round(_percentile(ttfts, 0.5) * 1e3, 2),
            "ttft_p99_ms": round(_percentile(ttfts, 0.99) * 1e3, 2),
            "shared_ttft_p50_ms": round(
                _percentile(shared_ttfts, 0.5) * 1e3, 2),
            "shared_ttft_p99_ms": round(
                _percentile(shared_ttfts, 0.99) * 1e3, 2),
            "tokens_per_s": round(len(ttfts) * max_new / wall, 1),
            "wall_s": round(wall, 2),
        }
        if pool is not None:
            hits = stats["prefix_hits"] - warm_stats["prefix_hits"]
            out[mode]["prefix_hit_rate"] = round(
                hits / max(1, len(trace)), 3)
            out[mode]["bytes_saved"] = (stats["bytes_saved"]
                                        - warm_stats["bytes_saved"])
    # the reuse win shows on the shared-prefix population (paged hits
    # prefill only the suffix; unpaged prefills the full prompt); the
    # perf gate pins the p50 ratio — p99 over ~20 samples is one
    # scheduler hiccup away from an outlier
    out["reuse_ttft_p99_ratio"] = round(
        out["paged"]["shared_ttft_p99_ms"]
        / out["unpaged"]["shared_ttft_p99_ms"], 3)
    out["reuse_ttft_p50_ratio"] = round(
        out["paged"]["shared_ttft_p50_ms"]
        / out["unpaged"]["shared_ttft_p50_ms"], 3)
    return out


class _SlowHandle:
    """Degrades a replica: inflates its reported queue depth and slows
    every request (a busy / unhealthy box the router should avoid)."""

    def __init__(self, inner, delay_s=0.5, fake_depth=40):
        self.inner = inner
        self.delay_s = delay_s
        self.fake_depth = fake_depth

    def completions(self, request):
        time.sleep(self.delay_s)
        return self.inner.completions(request)

    def completions_stream(self, request):
        time.sleep(self.delay_s)
        return self.inner.completions_stream(request)

    def healthz(self):
        return self.inner.healthz()

    def load(self):
        load = dict(self.inner.load())
        load["queue_depth"] = (load.get("queue_depth") or 0) \
            + self.fake_depth
        return load

    def reload(self, model, ckpt_dir, step=None):
        return self.inner.reload(model, ckpt_dir, step=step)


def _controller_pair():
    from alpa_tpu.serve.controller import Controller
    from alpa_tpu.serve.router import LocalReplicaHandle
    handles = []
    for _ in range(2):
        gen, model, params, cfg = _tiny_generator()
        ctrl = Controller()
        ctrl.register_model("m", gen)
        # warm the replica's executables outside the measured window:
        # the batched path (batch 1) and the streaming engine
        ctrl.completions({"model": "m", "prompt_ids": [1, 2, 3],
                          "max_new_tokens": 2})
        list(ctrl.completions_stream({"model": "m",
                                      "prompt_ids": [1, 2, 3],
                                      "max_new_tokens": 2}))
        handles.append((ctrl, LocalReplicaHandle(ctrl),
                        (model, params, cfg)))
    return handles


def _drive_router(router, n, rng, max_new=4):
    """Streamed requests through the router (the continuous-batching
    engine path: fixed-shape executables, so measured latency is
    queueing + service, not per-batch-size compiles); returns
    request-completion latencies and failures."""
    arrivals = np.cumsum(rng.exponential(1.0 / 2.5, size=n))
    lats, errors = [], []
    lock = threading.Lock()
    t0 = time.perf_counter()

    def run(arrival, prompt):
        wait = arrival - (time.perf_counter() - t0)
        if wait > 0:
            time.sleep(wait)
        sent = time.perf_counter()
        try:
            it = router.submit_stream(
                {"model": "m", "prompt_ids": prompt,
                 "max_new_tokens": max_new})
            n_toks = sum(1 for _ in it)
            assert n_toks == max_new
            with lock:
                lats.append(time.perf_counter() - sent)
        except Exception as e:  # pylint: disable=broad-except
            with lock:
                errors.append(repr(e))

    threads = [
        threading.Thread(
            target=run,
            args=(float(arrivals[i]),
                  rng.randint(2, 250, size=8).tolist()))
        for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lats, errors


def bench_router(n_requests, seed):
    from alpa_tpu.serve.router import Router

    out = {}
    for policy in ("least_loaded", "round_robin"):
        rng = np.random.RandomState(seed + 1)
        pairs = _controller_pair()
        router = Router(policy=policy)
        router.add_replica("good", pairs[0][1])
        router.add_replica("slow", _SlowHandle(pairs[1][1]))
        lats, errors = _drive_router(router, n_requests, rng)
        out[policy] = {
            "requests_ok": len(lats),
            "failures": len(errors),
            "latency_p50_ms": round(
                _percentile(lats, 0.5) * 1e3, 2),
            "latency_p99_ms": round(
                _percentile(lats, 0.99) * 1e3, 2),
        }
    out["degraded_p99_ratio"] = round(
        out["least_loaded"]["latency_p99_ms"]
        / out["round_robin"]["latency_p99_ms"], 3)

    # shed-then-admit burst: one replica, tiny threshold, all-at-once
    from alpa_tpu.serve.router import Router as _R
    rng = np.random.RandomState(seed + 2)
    pairs = _controller_pair()
    router = _R(policy="least_loaded", shed_queue_depth=2)
    router.add_replica("only", pairs[0][1])
    ok, shed = [], []

    def burst(i):
        try:
            router.submit({"model": "m",
                           "prompt_ids": rng.randint(2, 250,
                                                     size=8).tolist(),
                           "max_new_tokens": 4})
            ok.append(i)
        except Exception:  # pylint: disable=broad-except
            shed.append(i)

    threads = [threading.Thread(target=burst, args=(i,))
               for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # after the burst clears, the same replica admits again
    router.submit({"model": "m", "prompt_ids": [1, 2, 3],
                   "max_new_tokens": 2})
    out["burst"] = {"admitted": len(ok), "shed": len(shed),
                    "shed_rate": round(len(shed) / 12.0, 3),
                    "post_burst_admit": 1}
    return out


def bench_rolling(seed, tmp_dir):
    import jax
    from alpa_tpu.checkpoint.manager import CheckpointManager
    from alpa_tpu.serve.router import Router

    pairs = _controller_pair()
    _model, params, _cfg = pairs[0][2]
    new_params = jax.tree_util.tree_map(lambda x: x * 0.5 + 0.25,
                                        params)
    ckpt_dir = os.path.join(tmp_dir, "ckpt")
    ma = CheckpointManager(ckpt_dir, async_save=False)
    ma.save(1, new_params)
    ma.wait()

    router = Router(policy="least_loaded")
    router.add_replica("r0", pairs[0][1])
    router.add_replica("r1", pairs[1][1])

    rng = np.random.RandomState(seed + 3)
    outputs, errors = [], []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                router.submit({"model": "m",
                               "prompt_ids": rng.randint(
                                   2, 250, size=8).tolist(),
                               "max_new_tokens": 4})
                outputs.append(1)
            except Exception as e:  # pylint: disable=broad-except
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    time.sleep(0.3)
    router.rolling_reload("m", ckpt_dir)
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join()
    return {"requests_ok": len(outputs), "failures": len(errors),
            "deploy_wall_s": round(time.perf_counter() - t0, 2),
            "replicas": 2}


class _KillableIter:
    """Stream wrapper that dies with its replica: once the kill switch
    is set, the next ``__next__`` raises like a dropped connection."""

    def __init__(self, handle, inner):
        self.handle = handle
        self.inner = inner

    def __iter__(self):
        return self

    def __next__(self):
        if self.handle.dead.is_set():
            raise ConnectionError("decode replica killed (bench)")
        return next(self.inner)

    def close(self):
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()


class _KillableHandle:
    """Decode-replica handle with a kill switch: when ``dead`` is set,
    new ingests fail and already-open streams raise mid-iteration —
    the shape of a decode-replica crash the router must absorb with
    zero dropped handoffs."""

    def __init__(self, inner):
        self.inner = inner
        self.dead = threading.Event()

    def _check(self):
        if self.dead.is_set():
            raise ConnectionError("decode replica killed (bench)")

    def completions(self, request):
        self._check()
        return self.inner.completions(request)

    def completions_stream(self, request):
        self._check()
        return _KillableIter(self, self.inner.completions_stream(request))

    def ingest(self, wire):
        self._check()
        return _KillableIter(self, self.inner.ingest(wire))

    def prefill(self, request):
        self._check()
        return self.inner.prefill(request)

    def disagg_fetch(self, request_id):
        return self.inner.disagg_fetch(request_id)

    def disagg_ack(self, request_id):
        return self.inner.disagg_ack(request_id)

    def healthz(self):
        return self.inner.healthz()

    def load(self):
        return self.inner.load()

    def reload(self, model, ckpt_dir, step=None):
        return self.inner.reload(model, ckpt_dir, step=step)


def _mixed_trace(n, rng, heavy_frac=0.35, heavy_prompt=320,
                 light_prompt=8, heavy_new=4, light_new=24, rate_hz=5.0):
    """Mixed long-prefill / short-decode Poisson trace (the workload
    disaggregation exists for): heavy requests are prefill-dominated,
    light requests are decode-dominated and carry the ITL samples."""
    arrivals = np.cumsum(rng.exponential(1.0 / rate_hz, size=n))
    reqs = []
    for i in range(n):
        heavy = bool(rng.random() < heavy_frac)
        size = heavy_prompt if heavy else light_prompt
        prompt = rng.randint(2, 250, size=size).astype(np.int32)
        reqs.append((float(arrivals[i]), prompt.tolist(),
                     heavy_new if heavy else light_new, heavy))
    return reqs


def _drive_mixed(router, trace, kill_at=None, on_kill=None):
    """Replay the mixed trace open-loop through the router.  TTFT is
    recorded for every request; inter-token gaps only for the light
    (short-decode) population — heavy streams emit too few tokens to
    say anything about steady-state ITL.  ``on_kill`` fires once, when
    ``kill_at`` requests have completed."""
    res = {"ttfts": [], "gaps": [], "errors": []}
    done = {"n": 0}
    lock = threading.Lock()
    t0 = time.perf_counter()

    def run(arrival, prompt, max_new, heavy):
        wait = arrival - (time.perf_counter() - t0)
        if wait > 0:
            time.sleep(wait)
        sent = time.perf_counter()
        try:
            it = router.submit_stream(
                {"model": "m", "prompt_ids": prompt,
                 "max_new_tokens": max_new, "temperature": 0.0})
            first, last, gaps, n_toks = None, None, [], 0
            for _ in it:
                now = time.perf_counter()
                if first is None:
                    first = now - sent
                else:
                    gaps.append(now - last)
                last = now
                n_toks += 1
            assert n_toks == max_new
            with lock:
                res["ttfts"].append(first)
                if not heavy:
                    res["gaps"].extend(gaps)
                done["n"] += 1
                if on_kill is not None and done["n"] == kill_at:
                    on_kill()
        except Exception as e:  # pylint: disable=broad-except
            with lock:
                res["errors"].append(repr(e))

    threads = [threading.Thread(target=run, args=args)
               for args in trace]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    res["wall"] = time.perf_counter() - t0
    return res


def _disagg_generator():
    # a model where a full-bucket prefill visibly stalls a decode tick
    return _tiny_generator(seq_len=512, prefill_chunk=16, hidden=128,
                           layers=2)


def _disagg_summary(res):
    return {
        "requests_ok": len(res["ttfts"]),
        "failures": len(res["errors"]),
        "ttft_p50_ms": round(_percentile(res["ttfts"], 0.5) * 1e3, 2),
        "ttft_p99_ms": round(_percentile(res["ttfts"], 0.99) * 1e3, 2),
        "itl_p50_ms": round(_percentile(res["gaps"], 0.5) * 1e3, 2),
        "itl_p99_ms": round(_percentile(res["gaps"], 0.99) * 1e3, 2),
        "itl_samples": len(res["gaps"]),
        "wall_s": round(res["wall"], 2),
    }


def bench_disagg(n_requests, seed):
    """Monolithic 3-replica fleet vs 1 prefill + 2 decode disaggregated
    fleet (equal total hardware) on the same mixed trace; one disagg
    decode replica is killed mid-run (every in-flight and future
    request on it must fail over via the retained handoff — zero
    failures)."""
    from alpa_tpu.global_env import global_config
    from alpa_tpu.serve import disagg as disagg_mod
    from alpa_tpu.serve.controller import Controller
    from alpa_tpu.serve.router import LocalReplicaHandle, Router

    warm_heavy = list(range(2, 162))
    warm_light = list(range(2, 10))

    def controller():
        gen, _m, _p, _c = _disagg_generator()
        ctrl = Controller()
        ctrl.register_model("m", gen)
        return ctrl

    def warm_engine(ctrl):
        # compile the bucketed prefill + decode-step executables
        # outside the measured window (both prompt sizes share one
        # prompt bucket, but warm both populations anyway)
        for p in (warm_heavy, warm_light):
            list(ctrl.completions_stream(
                {"model": "m", "prompt_ids": p, "max_new_tokens": 2,
                 "temperature": 0.0}))

    prev = (global_config.kv_paged, global_config.kv_prefix_reuse)
    # paged KV on for both fleets (the disagg ingest scatters into the
    # paged pool); prefix reuse off — every prompt is unique, and one
    # code path per fleet keeps compile noise out of the percentiles
    global_config.kv_paged = True
    global_config.kv_prefix_reuse = False
    try:
        rng = np.random.RandomState(seed + 4)
        trace = _mixed_trace(n_requests, rng)
        out = {"trace": {
            "requests": n_requests,
            "heavy": sum(1 for *_x, h in trace if h),
            "light": sum(1 for *_x, h in trace if not h),
        }}

        # -- monolithic: every replica prefills AND decodes ------------
        mono_router = Router(disagg_mode="off")
        for i in range(3):
            ctrl = controller()
            warm_engine(ctrl)
            mono_router.add_replica(f"r{i}", LocalReplicaHandle(ctrl))
        mono = _drive_mixed(mono_router, trace)
        out["monolithic"] = _disagg_summary(mono)

        # -- disaggregated: 1 prefill + 2 decode, d0 killed mid-run ----
        router = Router(disagg_mode="auto")
        cp = controller()
        router.add_replica("p0", LocalReplicaHandle(cp),
                           phase="prefill")
        kill = None
        for i in range(2):
            ctrl = controller()
            warm_engine(ctrl)
            handle = LocalReplicaHandle(ctrl)
            if i == 0:
                handle = _KillableHandle(handle)
                kill = handle
            router.add_replica(f"d{i}", handle, phase="decode")
        # warm the handoff path end to end on BOTH decode replicas
        # (prefill bucket on p0; ingest transfer + mid-tick join on dX)
        p0 = router._replicas["p0"].handle
        for name in ("d0", "d1"):
            wire = p0.prefill({"model": "m", "prompt_ids": warm_light,
                               "max_new_tokens": 2, "temperature": 0.0})
            list(router._replicas[name].handle.ingest(wire))
            p0.disagg_ack(wire["request_id"])

        bytes0 = disagg_mod._HANDOFF_BYTES.value
        kill_at = max(2, int(n_requests * 0.4))
        dis = _drive_mixed(router, trace, kill_at=kill_at,
                           on_kill=kill.dead.set)
        summary = _disagg_summary(dis)
        handoffs = max(1, router.disagg_handoffs)
        summary["handoff_kb_per_request"] = round(
            (disagg_mod._HANDOFF_BYTES.value - bytes0)
            / 1024.0 / handoffs, 2)
        summary["handoffs"] = router.disagg_handoffs
        summary["reingests"] = router.disagg_reingests
        summary["killed_after_n_requests"] = kill_at
        out["disagg"] = summary

        out["itl_p99_ratio"] = round(
            out["disagg"]["itl_p99_ms"]
            / out["monolithic"]["itl_p99_ms"], 3)
        out["itl_p50_ratio"] = round(
            out["disagg"]["itl_p50_ms"]
            / out["monolithic"]["itl_p50_ms"], 3)
        out["kill_failures"] = (out["disagg"]["failures"]
                                + out["monolithic"]["failures"])
        return out
    finally:
        global_config.kv_paged, global_config.kv_prefix_reuse = prev


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=DEFAULT_OUT)
    p.add_argument("--gate", action="store_true",
                   help="check serving.* metrics against the perf-gate "
                        "baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite matching baseline values from this run")
    p.add_argument("--disagg", action="store_true",
                   help="run ONLY the disaggregated prefill/decode "
                        "scenario (own artifact serving_disagg.json)")
    args = p.parse_args(argv)

    if args.disagg:
        print("== disagg (1 prefill + 2 decode vs 3 monolithic, "
              "decode kill mid-run) ==", flush=True)
        dis = bench_disagg(args.requests, args.seed)
        print(json.dumps(dis, indent=1), flush=True)
        out_path = args.out if args.out != DEFAULT_OUT else \
            os.path.join(REPO, "benchmark", "results",
                         "serving_disagg.json")
        results = {"n_requests": args.requests, "seed": args.seed,
                   "disagg": dis}
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(results, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {out_path}")
        if args.gate or args.update_baseline:
            from benchmark import perf_gate
            fresh = perf_gate.flatten_metrics({"serving": {"disagg": {
                "itl_p99_ratio": dis["itl_p99_ratio"],
                "kill_failures": dis["kill_failures"],
                "reingests": dis["disagg"]["reingests"],
                "handoff_kb_per_request":
                    dis["disagg"]["handoff_kb_per_request"],
                "ttft_p99_ms": dis["disagg"]["ttft_p99_ms"],
            }}})
            if args.update_baseline:
                perf_gate._update(fresh, perf_gate.DEFAULT_BASELINE)
                return 0
            verdict = perf_gate.gate(fresh)
            print(json.dumps(verdict, indent=1))
            if not verdict["pass"]:
                print("SERVING DISAGG GATE FAILED", file=sys.stderr)
                return 1
        return 0

    import tempfile
    print("== reuse (paged prefix reuse vs unpaged) ==", flush=True)
    reuse = bench_reuse(args.requests, args.seed)
    print(json.dumps(reuse, indent=1), flush=True)
    print("== router (1 degraded replica of 2) ==", flush=True)
    router = bench_router(args.requests, args.seed)
    print(json.dumps(router, indent=1), flush=True)
    print("== rolling deploy under load ==", flush=True)
    with tempfile.TemporaryDirectory() as td:
        rolling = bench_rolling(args.seed, td)
    print(json.dumps(rolling, indent=1), flush=True)

    results = {
        "n_requests": args.requests,
        "seed": args.seed,
        "reuse": reuse,
        "router": router,
        "rolling": rolling,
    }
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(results, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}")

    if args.gate or args.update_baseline:
        from benchmark import perf_gate
        fresh = perf_gate.flatten_metrics({"serving": {
            "prefix_hit_rate": reuse["paged"]["prefix_hit_rate"],
            "reuse_ttft_p50_ratio": reuse["reuse_ttft_p50_ratio"],
            "degraded_p99_ratio": router["degraded_p99_ratio"],
            "degraded_failures":
                router["least_loaded"]["failures"]
                + router["round_robin"]["failures"],
            "rolling_failures": rolling["failures"],
        }})
        if args.update_baseline:
            perf_gate._update(fresh, perf_gate.DEFAULT_BASELINE)
            return 0
        verdict = perf_gate.gate(fresh)
        print(json.dumps(verdict, indent=1))
        if not verdict["pass"]:
            print("SERVING LOAD GATE FAILED", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
