"""Serving throughput microbenchmark: continuous batching + packed
admission (the analog of ref ``examples/llm_serving/benchmark``).

Measures, on whatever backend is active (CPU mesh or the chip):

* ``generate``   — plain batched Generator.generate throughput,
* ``engine``     — ContinuousBatchingEngine with per-row admission,
* ``packed``     — the same engine admitting its backlog via ONE packed
  segment-masked prefill,

each over the same mixed-length request trace.  Prints one JSON line per
mode: requests/s, output tokens/s, admissions, packed admissions.

    python benchmark/serving_bench.py [--requests 24] [--model tiny]
"""
import argparse
import json
import threading
import time

import numpy as np


def make_requests(n, rng, max_len=24):
    lens = rng.randint(4, max_len, size=n)
    return [rng.randint(0, 60, size=int(l)).astype(np.int32)
            for l in lens]


def run_engine_mode(gen, requests, new_tokens, packed):
    from alpa_tpu.serve.engine import ContinuousBatchingEngine
    from alpa_tpu.serve.generation import GenerationConfig

    engine = ContinuousBatchingEngine(
        gen, max_batch=4, prompt_bucket=gen.prompt_buckets[-1],
        packed_admission=packed,
        packed_bucket=2 * gen.prompt_buckets[-1])
    cfg = GenerationConfig(max_new_tokens=new_tokens)
    # warmup compiles (prefill + decode + scatter paths); the packed
    # executable is warmed directly so its one-time compile stays out of
    # the measured window
    engine.submit(requests[0], cfg)
    if packed and engine._packed is not None:
        import jax.numpy as jnp
        last, rows = engine._packed([requests[0], requests[1]])
        # no-op scatter (all-False mask) warms its executable too
        engine._scatter_packed(engine._caches, rows, engine._logits,
                               last.astype(jnp.float32),
                               jnp.zeros((engine.B,), jnp.int32),
                               jnp.zeros((engine.B,), bool))

    done = [None] * len(requests)

    def do(i):
        done[i] = engine.submit(requests[i], cfg)

    tic = time.perf_counter()
    threads = [threading.Thread(target=do, args=(i,))
               for i in range(len(requests))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - tic
    out_tokens = sum(len(d) - len(r) for d, r in zip(done, requests))
    stats = {"mode": "packed" if packed else "engine",
             "requests": len(requests), "wall_s": round(wall, 3),
             "req_per_s": round(len(requests) / wall, 2),
             "out_tok_per_s": round(out_tokens / wall, 1),
             "admissions": engine.admissions,
             "packed_admissions": engine.packed_admissions,
             "decode_steps": engine.decode_steps}
    engine.shutdown()
    return stats


def run_generate_mode(gen, requests, new_tokens):
    from alpa_tpu.serve.generation import GenerationConfig

    cfg = GenerationConfig(max_new_tokens=new_tokens)
    gen.generate(requests[0][None], cfg)  # warmup
    tic = time.perf_counter()
    out_tokens = 0
    for r in requests:
        out = gen.generate(r[None], cfg)
        out_tokens += out.shape[-1] - len(r)
    wall = time.perf_counter() - tic
    return {"mode": "generate", "requests": len(requests),
            "wall_s": round(wall, 3),
            "req_per_s": round(len(requests) / wall, 2),
            "out_tok_per_s": round(out_tokens / wall, 1)}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--new-tokens", type=int, default=16)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    args = p.parse_args()

    import jax

    import alpa_tpu
    from alpa_tpu.model.gpt_model import GPTConfig, GPTModel, init_gpt_real
    from alpa_tpu.serve.generation import Generator

    alpa_tpu.init(cluster="local")
    cfg = GPTConfig(hidden_size=args.hidden, num_layers=args.layers,
                    num_heads=max(4, args.hidden // 64), seq_len=128,
                    vocab_size=256)
    model, params = init_gpt_real(cfg, 1)
    gen = Generator(model, params, cfg, batch_size=1,
                    prompt_buckets=[32])
    rng = np.random.RandomState(0)
    requests = make_requests(args.requests, rng)

    print(json.dumps({"platform": jax.devices()[0].platform,
                      "model": f"h{args.hidden}-l{args.layers}",
                      "trace": f"{args.requests} reqs, "
                               f"{args.new_tokens} new tokens"}),
          flush=True)
    for stats in (run_generate_mode(gen, requests, args.new_tokens),
                  run_engine_mode(gen, requests, args.new_tokens,
                                  packed=False),
                  run_engine_mode(gen, requests, args.new_tokens,
                                  packed=True)):
        print(json.dumps(stats), flush=True)


if __name__ == "__main__":
    main()
