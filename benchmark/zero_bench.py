"""ZeRO weight-update sharding benchmark (ISSUE 10): zero_stage x
dp_size sweep on the GPT fixture.

For every (zero_stage, dp_size) cell this measures, on a CPU mesh (the
byte accounting is layout math — identical on TPU):

- per-device parameter bytes and optimizer-state bytes, read off the
  trained state's actual shardings (``sharding.shard_shape``);
- static per-device peak bytes from XLA's memory analysis of the
  compiled executable;
- mean step wall time (3 timed steps after a warmup step).

A second, fully deterministic section compiles the 2-stage pipeshard
MLP fixture under ``zero_stage`` 0 and 2 and reports the plan
verifier's static ``opt_state_bytes`` / ``peak_bytes`` per mesh — the
same numbers the ``alpa_opt_state_bytes{mesh}`` gauge exports.

Usage:  python benchmark/zero_bench.py [--out F] [--gate]

``--gate`` checks the deterministic byte ratios against
``benchmark/results/perf_gate_baseline.json`` (PR 9 gate) and exits
nonzero on regression.  Writes JSON next to the other suite results
(benchmark/results/zero_sharding.json).
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_OUT = os.path.join(REPO, "benchmark", "results",
                           "zero_sharding.json")


def _per_device_bytes(leaf) -> int:
    import numpy as np
    shard = leaf.sharding.shard_shape(leaf.shape)
    n = int(np.prod(shard)) if shard else 1
    return n * leaf.dtype.itemsize


def _tree_bytes(tree) -> int:
    import jax
    return sum(_per_device_bytes(x)
               for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "sharding"))


def _gpt_train_state(batch_size=4):
    import jax
    import jax.numpy as jnp
    import optax
    from flax.training import train_state

    from alpa_tpu.model.gpt_model import GPTConfig, GPTModel

    config = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                       num_heads=4, seq_len=32)
    model = GPTModel(config)
    rngkey = jax.random.PRNGKey(0)
    input_ids = jax.random.randint(rngkey, (batch_size, config.seq_len),
                                   0, config.vocab_size, jnp.int32)
    params = model.init(rngkey, input_ids)
    state = train_state.TrainState.create(
        apply_fn=model.apply, params=params,
        tx=optax.adam(learning_rate=1e-3))
    batch = {"input_ids": input_ids,
             "labels": jnp.roll(input_ids, -1, axis=1)}
    return state, batch


def _train_step(method):
    import jax.numpy as jnp

    import alpa_tpu
    from alpa_tpu.model.model_util import gpt_lm_loss

    def step(state, batch):
        def loss_fn(p):
            return gpt_lm_loss(state.apply_fn, p, batch)
        val, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), val

    return alpa_tpu.parallelize(step, method=method)


def bench_cell(zero_stage: str, dp: int, n_steps: int = 3) -> dict:
    import jax

    from alpa_tpu.parallel_method import ShardParallel
    from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption

    method = ShardParallel(
        devices=jax.devices()[:dp],
        auto_sharding_option=AutoShardingOption(
            enable_auto_sharding=False, force_data_parallel=True,
            zero_stage=zero_stage))
    step = _train_step(method)
    state, batch = _gpt_train_state()
    state, loss = step(state, batch)           # compile + warmup
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, loss = step(state, batch)
        jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / n_steps

    ex = step.get_last_executable()
    return {
        "zero_stage": zero_stage,
        "dp_size": dp,
        "loss": float(loss),
        "param_bytes_per_device": _tree_bytes(state.params),
        "opt_state_bytes_per_device": _tree_bytes(state.opt_state),
        "peak_bytes_per_device": ex.get_total_allocation_size(),
        "step_seconds": round(dt, 4),
    }


def bench_pipeshard_static() -> dict:
    """Deterministic: static plan-verifier byte accounting of the
    2-stage pipeshard fixture under zero_stage 0 vs 2."""
    import alpa_tpu
    from alpa_tpu.parallel_method import PipeshardParallel
    from alpa_tpu.pipeline_parallel.layer_construction import (
        ManualLayerOption)
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)
    from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
    from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                  get_mlp_train_step)

    out = {}
    for stage in ("0", "2"):
        method = PipeshardParallel(
            num_micro_batches=2,
            layer_option=ManualLayerOption(),
            stage_option=UniformStageOption(num_stages=2),
            default_auto_sharding_option=AutoShardingOption(
                zero_stage=stage))
        state, batch = create_mlp_train_state_and_batch(
            batch_size=64, num_layers=4, manual_pipeline_layer=True)
        pstep = get_mlp_train_step(method, use_value_and_grad=True)
        state, _ = pstep(state, batch)
        v = pstep.get_last_executable().get_plan_verdict()
        out[f"stage{stage}"] = {
            "opt_state_bytes": v.stats["opt_state_bytes"],
            "peak_bytes": v.stats["peak_bytes"],
            "zero_bytes_saved": v.stats["zero_bytes_saved"],
        }
    return out


def run() -> dict:
    import jax

    import alpa_tpu
    alpa_tpu.init("local")

    n_dev = len(jax.devices())
    dps = [d for d in (2, 4, 8) if d <= n_dev]
    cells = [bench_cell(zs, dp)
             for zs in ("0", "2", "3") for dp in dps]
    pipeshard = bench_pipeshard_static()

    # deterministic gate metrics: pure layout ratios (byte math only)
    gate_metrics = {}
    by = {(c["zero_stage"], c["dp_size"]): c for c in cells}
    for dp in dps:
        z0, z2 = by[("0", dp)], by[("2", dp)]
        gate_metrics[f"zero.opt_bytes_ratio_stage2_dp{dp}"] = (
            z0["opt_state_bytes_per_device"] /
            max(z2["opt_state_bytes_per_device"], 1))
    p0 = sum(pipeshard["stage0"]["opt_state_bytes"].values())
    p2 = sum(pipeshard["stage2"]["opt_state_bytes"].values())
    gate_metrics["zero.pipeshard_opt_bytes_ratio"] = p0 / max(p2, 1.0)
    gate_metrics["zero.pipeshard_bytes_saved"] = (
        pipeshard["stage2"]["zero_bytes_saved"])

    return {"cells": cells, "pipeshard_static": pipeshard,
            "gate_metrics": {k: round(v, 4)
                             for k, v in gate_metrics.items()},
            "n_devices": n_dev}


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default=DEFAULT_OUT)
    parser.add_argument("--gate", action="store_true",
                        help="check the deterministic byte ratios "
                             "against the committed perf-gate baseline")
    args = parser.parse_args()

    result = run()
    result["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result, indent=2))
    print(f"\nwrote {args.out}")

    if args.gate:
        from benchmark.perf_gate import gate
        verdict = gate(result["gate_metrics"])
        print(json.dumps(verdict, indent=1))
        if not verdict["pass"]:
            sys.exit("ZERO BENCH PERF GATE FAILED")


if __name__ == "__main__":
    main()
