"""Certified plan superoptimization benchmark (ISSUE 17): adversarial
deoptimized plan -> superopt_mode=auto recovery -> bitwise outputs ->
warm-restart cache replay.

Real pipeshard plans come out of the emitter already well-scheduled, so
the bench measures the engine against the hazard-legal adversarial
baseline ``deoptimize_instructions`` produces: a topological reorder of
the full hazard DAG (RAW/WAR/WAW, per-channel FIFO order, and the
production-order invariant all hold — the program is semantically
identical) with inverted list-scheduling priority and every FREE
deferred as late as legality allows.  That is a plan a register-file
emitter *could* legally have produced; ``superopt_mode=auto`` must then
recover it:

1. Compile a real 2-stage / 2-mesh pipeshard MLP (8 CPU devices) and
   run one training step — the reference parameter bytes.
2. Hot-swap the deoptimized instruction stream into the executable
   (the replan path: forget lowered programs + slot tables) and verify
   the step is STILL bitwise identical — the adversary is semantics-
   preserving, only slower and fatter.
3. ``superopt_mode=auto``: the beam search + seven-analysis verdict
   gate accept a rewrite with a strictly smaller simulated critical
   path AND strictly smaller simulated peak live bytes; the step stays
   bitwise identical.
4. Warm restart (fresh compile-cache memory tier over the same disk
   dir): the accepted decision replays with zero search and an
   identical rewritten-plan fingerprint.
5. Fixture cross-check (satellite 1): on the committed
   ``model_check_fixture_plan.json``, ``simulate_dag``'s per-mesh
   simulated peak-live-bytes equals the static liveness analysis'
   ``alpa_plan_peak_bytes`` bit for bit.

Usage:  python benchmark/superopt_bench.py [--out F] [--gate]

``--gate`` checks the ``superopt.*`` metrics against
``benchmark/results/perf_gate_baseline.json`` (critical-path ratio and
peak-bytes ratio <= 1.0, bitwise outputs, zero-search warm replay) and
exits nonzero on regression.  Writes benchmark/results/superopt.json.
"""
import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from alpa_tpu.platform import pin_cpu_platform  # noqa: E402

DEFAULT_OUT = os.path.join(REPO, "benchmark", "results", "superopt.json")
FIXTURE = os.path.join(REPO, "benchmark", "results",
                       "model_check_fixture_plan.json")


def _fresh_pair():
    from alpa_tpu.testing import create_mlp_train_state_and_batch
    return create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=4, manual_pipeline_layer=False)


def _leaves(state):
    import jax
    import numpy as np
    return [np.asarray(x) for x in
            jax.tree_util.tree_leaves(state.params)]


def _bitwise(a, b):
    return float(len(a) == len(b) and
                 all((x == y).all() for x, y in zip(a, b)))


def _forget_lowering(ex):
    """The replan hot-swap: drop every lowered program AND the slot
    tables (instruction order changed, so slot numbering changes)."""
    ex._register_programs.clear()
    ex._register_program = None
    ex._reg_input_loads = None
    ex._reg_const_loads = None
    ex._reg_acc_slots = None
    ex._reg_output_specs = None
    ex._superopt_outcome = None
    ex._superopt_instructions = None


def _fixture_leg() -> dict:
    """Satellite 1: simulated per-mesh peaks == static liveness peaks
    on the committed fixture, serialized in program order."""
    from alpa_tpu.analysis import plan_verifier as pv
    from alpa_tpu.analysis.critical_path import MemSpec, simulate_dag
    from alpa_tpu.analysis.model_check import model_from_dict
    with open(FIXTURE, encoding="utf-8") as f:
        model, _hooks, _window = model_from_dict(json.load(f))
    slots = (model.slots.values() if isinstance(model.slots, dict)
             else model.slots)
    written, preplaced = set(), set()
    for op in model.ops:
        for s in list(op.reads) + list(op.kills):
            if s not in written:
                preplaced.add(s)
        written.update(op.writes)
    mem = MemSpec(writes=[list(o.writes) for o in model.ops],
                  kills=[list(o.kills) for o in model.ops],
                  nbytes={s.slot: float(s.nbytes) for s in slots},
                  mesh_of={s.slot: s.mesh for s in slots},
                  num_meshes=model.num_meshes,
                  preplaced=frozenset(preplaced))
    n = len(model.ops)
    _, _, sim_peaks = simulate_dag(
        [1.0] * n, [set() if i == 0 else {i - 1} for i in range(n)], mem)
    _, stats = pv.check_liveness(model)
    static = stats["peak_bytes"]
    static_list = [static[str(m)] for m in range(model.num_meshes)] \
        if isinstance(static, dict) else list(static)
    return {
        "simulated_peak_bytes": list(sim_peaks),
        "static_peak_bytes": static_list,
        "match": float(list(sim_peaks) == static_list),
    }


def run() -> dict:
    import alpa_tpu
    from alpa_tpu import PipeshardParallel
    from alpa_tpu.analysis import superopt as so
    from alpa_tpu.compile_cache import reset_compile_cache
    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel.layer_construction import (
        AutoLayerOption)
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)
    from alpa_tpu.testing import get_mlp_train_step

    prev = {k: getattr(global_config, k) for k in (
        "pipeline_dispatch_mode", "superopt_mode", "compile_cache_dir")}
    cache_dir = tempfile.mkdtemp(prefix="superopt_bench_cache_")
    try:
        alpa_tpu.init("local")
        global_config.pipeline_dispatch_mode = "registers"
        global_config.superopt_mode = "off"
        global_config.compile_cache_dir = cache_dir
        reset_compile_cache()

        method = PipeshardParallel(
            num_micro_batches=2,
            layer_option=AutoLayerOption(layer_num=4),
            stage_option=UniformStageOption(num_stages=2))
        step = get_mlp_train_step(method, use_value_and_grad=False)
        state, batch = _fresh_pair()
        step(state, batch)
        ex = step.get_last_executable()

        s0, b0 = _fresh_pair()
        ns0, _ = step(s0, b0)
        want = _leaves(ns0)

        # 2. the adversarial baseline, hot-swapped
        cm = so._CostModel()
        nm = ex.num_meshes
        original = so.score_instructions(list(ex.instructions), nm, cm)
        ex.instructions = so.deoptimize_instructions(
            list(ex.instructions), cm)
        pessimized = so.score_instructions(list(ex.instructions), nm, cm)
        _forget_lowering(ex)
        ex._ensure_lowered("registers")
        s1, b1 = _fresh_pair()
        ns1, _ = step(s1, b1)
        pess_bitwise = _bitwise(want, _leaves(ns1))

        # 3. auto recovery through the verdict gate
        global_config.superopt_mode = "auto"
        _forget_lowering(ex)
        ex._ensure_lowered("registers")
        out = ex._superopt_outcome
        s2, b2 = _fresh_pair()
        ns2, _ = step(s2, b2)
        auto_bitwise = _bitwise(want, _leaves(ns2))
        cp_ratio = (out.best_score.makespan_us /
                    out.baseline_score.makespan_us)
        peak_ratio = (out.best_score.total_peak /
                      out.baseline_score.total_peak)

        # 4. warm restart: fresh memory tier, same disk cache
        reset_compile_cache()
        _forget_lowering(ex)
        ex._ensure_lowered("registers")
        warm = ex._superopt_outcome
        s3, b3 = _fresh_pair()
        ns3, _ = step(s3, b3)
        warm_bitwise = _bitwise(want, _leaves(ns3))

        fixture = _fixture_leg()

        gate_metrics = {
            "superopt.accepted": float(bool(out.accepted)),
            "superopt.critical_path_ratio": round(cp_ratio, 4),
            "superopt.peak_bytes_ratio": round(peak_ratio, 4),
            "superopt.outputs_bitwise": min(
                pess_bitwise, auto_bitwise, warm_bitwise),
            "superopt.warm_replay_zero_search": float(
                warm.cache_hit and not warm.searched and
                warm.fingerprint == out.fingerprint),
            "superopt.sim_peaks_match_static": fixture["match"],
        }
        return {
            "plan": {
                "n_instructions": len(ex.instructions),
                "num_meshes": nm,
                "original": original.to_dict(),
                "deoptimized": pessimized.to_dict(),
                "deopt_makespan_inflation": round(
                    pessimized.makespan_us / original.makespan_us, 4),
                "deopt_peak_inflation": round(
                    pessimized.total_peak / original.total_peak, 4),
            },
            "superopt": out.to_dict(),
            "bitwise": {
                "deoptimized": pess_bitwise,
                "auto": auto_bitwise,
                "warm": warm_bitwise,
            },
            "warm_restart": {
                "cache_hit": warm.cache_hit,
                "searched": warm.searched,
                "fingerprint_stable":
                    warm.fingerprint == out.fingerprint,
            },
            "fixture": fixture,
            "gate_metrics": gate_metrics,
        }
    finally:
        reset_compile_cache()
        for k, v in prev.items():
            setattr(global_config, k, v)


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default=DEFAULT_OUT)
    parser.add_argument("--gate", action="store_true",
                        help="check superopt.* metrics against the "
                             "committed perf-gate baseline")
    args = parser.parse_args()

    pin_cpu_platform(8)
    result = run()
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(result, indent=2, sort_keys=True))
    print(f"\nwrote {args.out}")

    if args.gate:
        from benchmark.perf_gate import gate
        verdict = gate(result["gate_metrics"])
        print(json.dumps(verdict, indent=1))
        if not verdict["pass"]:
            sys.exit("SUPEROPT BENCH PERF GATE FAILED")


if __name__ == "__main__":
    main()
