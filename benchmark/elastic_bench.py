"""Elastic-training benchmark (ISSUE 16): injected-kill sweep over the
committed 2-stage pipeshard fixture, one scenario per failure class:

* ``kill``    — half the 8-device mesh dies at a step boundary
  (``worker_lost``); the supervisor re-solves a 2-stage plan over the
  4 survivors, restores the last verified step, resumes.  Scored on
  replay distance, recovery wall clock, and bitwise loss continuity
  against an uninterrupted run restored from the same step on the
  same surviving plan.
* ``preempt`` — an eviction notice (``preemption_notice``) with a
  grace window; scored on whether the synchronous snapshot landed
  inside the window (hit rate must be 1.0) plus recovery wall clock.
* ``wedge``   — a mid-step instruction failure whose WedgeDetector
  probe hangs (the BENCH_r03–r05 failure mode): torn state is never
  snapshotted; the supervisor resets and replays from the last
  verified checkpoint, bitwise.

Deterministic up to wall-clock timings: the loss-continuity and
hit-rate metrics are exact (gated at 1.0), the seconds metrics are
gated with generous absolute bounds (CPU episode recovery is
sub-second; the bound only catches order-of-magnitude regressions
like a quiesce that starts blocking on a dead mesh).

Usage:  python benchmark/elastic_bench.py [--out F] [--gate]

``--gate`` checks the ``elastic.*`` metrics against
``benchmark/results/perf_gate_baseline.json`` and exits nonzero on
regression.  Writes benchmark/results/elastic.json.
"""
import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from alpa_tpu.platform import pin_cpu_platform  # noqa: E402

DEFAULT_OUT = os.path.join(REPO, "benchmark", "results", "elastic.json")

N_STEPS = 4


def _make_solve():
    import numpy as np

    import alpa_tpu
    from alpa_tpu.device_mesh import VirtualPhysicalMesh
    from alpa_tpu.pipeline_parallel.layer_construction import \
        ManualLayerOption
    from alpa_tpu.pipeline_parallel.stage_construction import \
        UniformStageOption
    from alpa_tpu.testing import get_mlp_train_step

    cache = {}

    def solve(devices):
        key = tuple(id(d) for d in devices)
        if key not in cache:
            n = len(devices)
            vm = VirtualPhysicalMesh(
                1, n, np.array(list(devices), dtype=object).reshape(1, n))
            method = alpa_tpu.PipeshardParallel(
                devices=vm, num_micro_batches=2,
                layer_option=ManualLayerOption(),
                stage_option=UniformStageOption(num_stages=2))
            cache[key] = get_mlp_train_step(method,
                                            use_value_and_grad=True)
        return cache[key]

    return solve


def _fresh_state_and_batch():
    from alpa_tpu.testing import create_mlp_train_state_and_batch
    return create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)


def _drive(sup, batch, until):
    import numpy as np
    losses = {}
    for _ in range(50):
        if sup.step_index >= until:
            return losses
        loss = sup.step(batch)
        losses[sup.step_index] = np.asarray(loss)
    raise RuntimeError(f"supervisor stuck at step {sup.step_index}")


def _bitwise_vs_comparator(losses, root, restored_step, step_fn, batch,
                           until):
    """1.0 iff every post-episode committed loss equals an
    uninterrupted run restored from the same step on the same plan."""
    import numpy as np

    from alpa_tpu.checkpoint.manager import CheckpointManager

    mgr = CheckpointManager(root, async_save=False)
    c_state, _ = _fresh_state_and_batch()
    c_state = mgr.restore(c_state, step=restored_step)
    for i in range(restored_step + 1, until + 1):
        c_state, c_loss = step_fn(c_state, batch)
        if not np.array_equal(losses[i], np.asarray(c_loss)):
            return 0.0
    return 1.0


def run() -> dict:
    import jax

    from alpa_tpu import fault
    from alpa_tpu.elastic import (ElasticSupervisor, PreemptionNotice,
                                  WedgeDetector, WorkerLost)
    import alpa_tpu

    alpa_tpu.init(cluster="local")
    solve = _make_solve()
    scratch = tempfile.mkdtemp(prefix="elastic_bench_")
    scenarios = {}

    # ---- kill: 8 -> 4 survivors at a step boundary -------------------
    state, batch = _fresh_state_and_batch()
    root = os.path.join(scratch, "kill")
    sup = ElasticSupervisor(solve, state, checkpoint_root=root,
                            register_globally=False)
    survivors = list(jax.devices())[:4]
    with fault.FaultPlan(fault.FaultSpec(
            "worker_lost", times=1, after=2,
            exc=lambda: WorkerLost(survivors=survivors))):
        losses = _drive(sup, batch, N_STEPS)
    ep = dict(sup.episodes[0])
    kill_bitwise = _bitwise_vs_comparator(
        losses, root, ep["restored_step"], solve(survivors), batch,
        N_STEPS)
    scenarios["kill"] = {"episode": ep, "bitwise": kill_bitwise}

    # ---- preempt: eviction notice with a grace window ----------------
    state, _ = _fresh_state_and_batch()
    root = os.path.join(scratch, "preempt")
    sup = ElasticSupervisor(solve, state, checkpoint_root=root,
                            register_globally=False)
    with fault.FaultPlan(fault.FaultSpec(
            "preemption_notice", times=1, after=2,
            exc=lambda: PreemptionNotice(grace_s=30.0))):
        _drive(sup, batch, N_STEPS)
    ep = dict(sup.episodes[0])
    scenarios["preempt"] = {
        "episode": ep,
        "snapshot_hit": float(bool(ep.get("snapshot_before_kill"))),
    }

    # ---- wedge: mid-step failure + hung probe ------------------------
    state, _ = _fresh_state_and_batch()
    root = os.path.join(scratch, "wedge")
    det = WedgeDetector(mesh_group=[object()],
                        probe=lambda m: time.sleep(5.0),
                        probe_timeout_s=0.1)
    sup = ElasticSupervisor(solve, state, checkpoint_root=root,
                            wedge_detector=det, register_globally=False)
    with fault.FaultPlan(fault.FaultSpec("stage_launch", times=1,
                                         after=12)):
        losses = _drive(sup, batch, N_STEPS)
    ep = dict(sup.episodes[0])
    wedge_bitwise = _bitwise_vs_comparator(
        losses, root, ep["restored_step"], solve(list(jax.devices())),
        batch, N_STEPS)
    scenarios["wedge"] = {"episode": ep, "bitwise": wedge_bitwise}

    all_eps = [s["episode"] for s in scenarios.values()]
    gate_metrics = {
        "elastic.kill_replay_steps":
            float(scenarios["kill"]["episode"]["replay_steps"]),
        "elastic.kill_recovery_seconds":
            round(scenarios["kill"]["episode"]["seconds"], 4),
        "elastic.kill_bitwise": kill_bitwise,
        "elastic.preempt_snapshot_hit_rate":
            scenarios["preempt"]["snapshot_hit"],
        "elastic.preempt_recovery_seconds":
            round(scenarios["preempt"]["episode"]["seconds"], 4),
        "elastic.wedge_recovery_seconds":
            round(scenarios["wedge"]["episode"]["seconds"], 4),
        "elastic.wedge_bitwise": wedge_bitwise,
        "elastic.episodes_within_budget": float(all(
            e["within_step_budget"] and e["within_time_budget"]
            for e in all_eps)),
    }
    return {
        "fixture": {"steps": N_STEPS, "devices": 8,
                    "pipeline": "2-stage 1f1b, 2 microbatches"},
        "scenarios": scenarios,
        "gate_metrics": gate_metrics,
    }


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default=DEFAULT_OUT)
    parser.add_argument("--gate", action="store_true",
                        help="check elastic.* metrics against the "
                             "committed perf-gate baseline")
    args = parser.parse_args()

    pin_cpu_platform(8)
    result = run()
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(result, indent=2, sort_keys=True))
    print(f"\nwrote {args.out}")

    if args.gate:
        from benchmark.perf_gate import gate
        verdict = gate(result["gate_metrics"])
        print(json.dumps(verdict, indent=1))
        if not verdict["pass"]:
            sys.exit("ELASTIC BENCH PERF GATE FAILED")


if __name__ == "__main__":
    main()
