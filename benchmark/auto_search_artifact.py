"""Record an auto-search solution artifact at multi-billion-param scale.

Runs the full auto path (layer clustering -> cost model [checked-in DB or
analytic TPU calibration] -> OSDI'22 stage DP) COMPILE-ONLY on a virtual
8-device mesh for a GPT-6.7B-class model, and commits the chosen plan
(stages x submeshes x microbatches) next to the suites — the analog of the
reference's recorded GPT-39B solution (ref benchmark/alpa/
suite_auto_gpt.py:71-84).  No TPU or model weights needed: parameters are
abstract (jax.eval_shape), the search runs on jaxprs.

Usage:  python benchmark/auto_search_artifact.py [--model 6.7B] [--out F]
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_OUT = os.path.join(REPO, "benchmark", "results",
                           "auto_plan_gpt{model}_8dev.json")


def search_gpt_plan(model_name="6.7B", n_devices=8, batch_size=32,
                    num_micro_batches=8, layer_num=8,
                    profiling_database=None, seq_len=1024, num_hosts=1,
                    memory_budget=16e9, force_ilp=False):
    """Run the plan-only auto search for one GPT rung; returns the plan."""
    import jax
    import jax.numpy as jnp
    import optax
    from flax.training import train_state

    import alpa_tpu
    from alpa_tpu.device_mesh import VirtualPhysicalMesh
    from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
    from alpa_tpu.model.model_util import gpt_lm_loss
    from alpa_tpu.pipeline_parallel.compile_executable import (
        search_pipeshard_plan)
    from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
    from alpa_tpu.pipeline_parallel.stage_construction import AutoStageOption
    from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
    from benchmark.suites import GPT_SPECS

    spec = GPT_SPECS[model_name]
    cfg = GPTConfig(seq_len=seq_len, vocab_size=51200, dtype=jnp.bfloat16,
                    **spec)
    model = GPTModel(cfg)
    rng = jax.random.PRNGKey(0)
    ids_aval = jax.ShapeDtypeStruct((batch_size, seq_len), jnp.int32)

    # abstract parameters: no 6.7B materialization anywhere
    params_aval = jax.eval_shape(model.init, rng, ids_aval)
    state_aval = jax.eval_shape(
        lambda p: train_state.TrainState.create(
            apply_fn=model.apply, params=p, tx=optax.adam(1e-4)),
        params_aval)
    batch_aval = {"input_ids": ids_aval, "labels": ids_aval}

    flat_avals, tree = jax.tree_util.tree_flatten((state_aval, batch_aval))
    batch_invars = [tuple(a.shape[:1]) == (batch_size,)
                    for a in flat_avals]

    def flat_fun(*leaves):
        state, batch = jax.tree_util.tree_unflatten(tree, leaves)

        def loss_fn(p):
            # the same loss formulation bench.py measures (shared helper
            # so the searched jaxpr cannot drift from the benchmarked one)
            return gpt_lm_loss(state.apply_fn, p, batch)

        loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    mesh = VirtualPhysicalMesh(num_hosts, n_devices // num_hosts)
    plan = search_pipeshard_plan(
        flat_fun, mesh, flat_avals, batch_invars, num_micro_batches,
        AutoShardingOption(),
        # per-layer remat, as any real multi-billion-param training run:
        # the activation stash shrinks to layer boundaries, which is what
        # makes the 16 GB/device budget satisfiable at all
        layer_option=AutoLayerOption(layer_num=layer_num, remat_layer=True),
        stage_option=AutoStageOption(
            profiling_database_filename=profiling_database,
            memory_budget_per_device=memory_budget,
            use_hlo_cost_model=not force_ilp))
    plan["model"] = f"gpt-{model_name}"
    plan["model_spec"] = dict(spec, seq_len=seq_len, vocab_size=51200)
    plan["batch_size"] = batch_size
    plan["n_devices"] = n_devices
    plan["num_hosts"] = num_hosts
    plan["memory_budget_per_device"] = memory_budget
    plan["cost_basis"] = (os.path.basename(profiling_database)
                          if profiling_database else "analytic")
    return plan


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="6.7B")
    ap.add_argument("--out", default=None)
    ap.add_argument("--pod", action="store_true",
                    help="pod-scale search: 8 hosts x 8 devices, bigger "
                    "global batch (the reference's recorded GPT-39B "
                    "solution ran at 64 GPUs, suite_auto_gpt.py:80-84)")
    ap.add_argument("--pod4", action="store_true",
                    help="4 hosts x 8 devices (the reference's recorded "
                    "GPT-15B solution ran at 32 GPUs: 4 stages x (1,8), "
                    "suite_auto_gpt.py:75-79)")
    args = ap.parse_args()

    from alpa_tpu.platform import pin_cpu_platform
    pin_cpu_platform(8)

    from alpa_tpu.mesh_profiling import (analytic_calibration,
                                         set_global_calibration)

    def pod_case(suffix, key, num_hosts, num_micro_batches):
        out = args.out or DEFAULT_OUT.format(model=args.model).replace(
            "_8dev", suffix)
        set_global_calibration(analytic_calibration("v5e"))
        plan = search_gpt_plan(args.model, n_devices=8 * num_hosts,
                               num_hosts=num_hosts, batch_size=128,
                               num_micro_batches=num_micro_batches,
                               layer_num=16)
        plan["cost_basis"] = "analytic-v5e"
        os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
        with open(out, "w", encoding="utf-8") as f:
            json.dump({key: plan}, f, indent=1)
        print(json.dumps({"out": out,
                          "plan": plan["forward_stage_layer_ids"],
                          "submeshes": plan["submesh_shapes"]}))

    if args.pod4:
        # the reference's recorded GPT-15B solution ran at 32 GPUs
        pod_case("_4x8dev", "analytic_v5e_4x8", 4, 16)
        return
    if args.pod:
        # the reference's recorded GPT-39B solution ran at 64 GPUs
        pod_case("_8x8dev", "analytic_v5e_8x8", 8, 32)
        return
    out = args.out or DEFAULT_OUT.format(model=args.model)

    # plan 1: under the checked-in CPU-mesh measured DB (deterministic,
    # test-asserted); plan 2: under the analytic v5e TPU calibration
    cpu_db = os.path.join(REPO, "prof_database_cpu8.json")
    plan_db = search_gpt_plan(args.model, profiling_database=cpu_db)
    set_global_calibration(analytic_calibration("v5e"))
    plan_v5e = search_gpt_plan(args.model)
    plan_v5e["cost_basis"] = "analytic-v5e"
    # 2 hosts x 8: the slow cross-host axis should trade TP width for
    # pipeline stages (additive per-layer ILP keeps comm in the costs)
    plan_2host = search_gpt_plan(args.model, n_devices=16, num_hosts=2)
    plan_2host["cost_basis"] = "analytic-v5e"

    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w", encoding="utf-8") as f:
        json.dump({"checked_in_db": plan_db, "analytic_v5e": plan_v5e,
                   "analytic_v5e_2x8": plan_2host}, f, indent=1)
    print(json.dumps({"out": out,
                      "db_plan": plan_db["forward_stage_layer_ids"],
                      "db_submeshes": plan_db["submesh_shapes"],
                      "v5e_plan": plan_v5e["forward_stage_layer_ids"],
                      "v5e_submeshes": plan_v5e["submesh_shapes"],
                      "v5e_2x8_submeshes": plan_2host["submesh_shapes"]}))


if __name__ == "__main__":
    main()
