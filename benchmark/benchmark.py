"""Benchmark driver.

Analog of ref ``benchmark/alpa/benchmark.py``: run a named suite of cases,
time the train step, report latency / TFLOPS / tokens-per-sec, append a
TSV record (ref util.write_tsv).

Usage:
  python benchmark/benchmark.py --suite gpt.tiny [--dump results.tsv]
"""
import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np


def build_case(case):
    import optax
    from flax.training import train_state

    import alpa_tpu
    from alpa_tpu.model.model_util import cross_entropy_loss

    dtype = jnp.bfloat16 if case.dtype == "bfloat16" else jnp.float32
    rng = jax.random.PRNGKey(0)

    if case.family == "gpt":
        from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
        cfg = GPTConfig(dtype=dtype, **case.model)
        model = GPTModel(cfg)
        ids = jax.random.randint(rng, (case.batch_size, cfg.seq_len), 0,
                                 cfg.vocab_size)
        labels = jax.random.randint(jax.random.PRNGKey(1),
                                    (case.batch_size, cfg.seq_len), 0,
                                    cfg.vocab_size)
        batch = {"ids": ids, "labels": labels}
        params = model.init(rng, ids)

        def loss_of(state, p, b):
            logits = state.apply_fn(p, b["ids"])
            return cross_entropy_loss(logits.astype(jnp.float32),
                                      b["labels"])

        def flops(latency):
            from alpa_tpu.util import compute_gpt_tflops
            return compute_gpt_tflops(case.batch_size, cfg.seq_len,
                                      cfg.num_layers, cfg.hidden_size,
                                      cfg.vocab_size, len(jax.devices()),
                                      latency)

        tokens = case.batch_size * cfg.seq_len
    elif case.family == "moe":
        from alpa_tpu.model.moe import MoEConfig, MoELMModel
        cfg = MoEConfig(dtype=dtype, **case.model)
        model = MoELMModel(cfg)
        ids = jax.random.randint(rng, (case.batch_size, cfg.seq_len), 0,
                                 cfg.vocab_size)
        labels = jax.random.randint(jax.random.PRNGKey(1),
                                    (case.batch_size, cfg.seq_len), 0,
                                    cfg.vocab_size)
        batch = {"ids": ids, "labels": labels}
        params = model.init(rng, ids)

        def loss_of(state, p, b):
            logits, aux = state.apply_fn(p, b["ids"])
            return cross_entropy_loss(logits.astype(jnp.float32),
                                      b["labels"]) + 0.01 * aux

        def flops(latency):
            from alpa_tpu.util import compute_moe_tflops
            return compute_moe_tflops(case.batch_size, cfg.seq_len,
                                      cfg.num_layers, cfg.hidden_size,
                                      cfg.expert_group_size, cfg.vocab_size,
                                      cfg.num_experts, len(jax.devices()),
                                      latency)

        tokens = case.batch_size * cfg.seq_len
    elif case.family == "unet":
        from alpa_tpu.model.unet_2d import UNet2D, UNetConfig
        cfg = UNetConfig(dtype=dtype, **case.model)
        model = UNet2D(cfg)
        res = case.method_kwargs.get("resolution", 32)
        x = jax.random.normal(rng, (case.batch_size, res, res,
                                    cfg.in_channels), dtype)
        t = jax.random.randint(jax.random.PRNGKey(2), (case.batch_size,),
                               0, 1000)
        noise = jax.random.normal(jax.random.PRNGKey(3), x.shape, dtype)
        batch = {"x": x, "t": t, "noise": noise}
        params = model.init(rng, x, t)

        def loss_of(state, p, b):
            pred = state.apply_fn(p, b["x"], b["t"])
            return ((pred.astype(jnp.float32) -
                     b["noise"].astype(jnp.float32))**2).mean()

        from alpa_tpu.util import jaxpr_eqn_flops
        fwd_jaxpr = jax.make_jaxpr(lambda p: model.apply(p, x, t))(params)
        fwd_flops = sum(jaxpr_eqn_flops(e) for e in fwd_jaxpr.jaxpr.eqns)

        def flops(latency):
            return 3.0 * fwd_flops / latency / len(jax.devices()) / 1e12

        tokens = case.batch_size
    elif case.family == "wresnet":
        import optax as _optax
        from alpa_tpu.model.wide_resnet import WResNetConfig, WideResNet
        cfg = WResNetConfig(dtype=dtype, **case.model)
        model = WideResNet(cfg)
        x = jax.random.normal(rng, (case.batch_size, 224, 224, 3), dtype)
        y = jax.random.randint(jax.random.PRNGKey(1), (case.batch_size,),
                               0, cfg.num_classes)
        batch = {"x": x, "y": y}
        params = model.init(rng, x)

        def loss_of(state, p, b):
            import optax
            logits = state.apply_fn(p, b["x"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), b["y"]).mean()

        # analytic formulas don't cover convs well: count fwd flops off
        # the traced jaxpr, x3 for fwd+bwd (standard accounting)
        from alpa_tpu.util import jaxpr_eqn_flops
        fwd_jaxpr = jax.make_jaxpr(lambda p: model.apply(p, x))(params)
        fwd_flops = sum(jaxpr_eqn_flops(e) for e in fwd_jaxpr.jaxpr.eqns)

        def flops(latency):
            return 3.0 * fwd_flops / latency / len(jax.devices()) / 1e12

        tokens = case.batch_size
    else:
        raise ValueError(case.family)

    state = train_state.TrainState.create(apply_fn=model.apply,
                                          params=params,
                                          tx=optax.adam(1e-4))

    if case.method == "pipeshard":
        method = alpa_tpu.PipeshardParallel(
            num_micro_batches=case.num_micro_batches,
            layer_option=alpa_tpu.AutoLayerOption(
                layer_num=case.method_kwargs.get("layer_num", 2)),
            stage_option=alpa_tpu.UniformStageOption(
                case.method_kwargs.get("num_stages")))
    elif case.method == "auto_pipeshard":
        # full auto inter+intra search (ref suite_auto_*.py): OSDI'22
        # stage DP over submesh choices, per-stage ILP inside
        from alpa_tpu.pipeline_parallel.stage_construction import (
            AutoStageOption)
        method = alpa_tpu.PipeshardParallel(
            num_micro_batches=case.num_micro_batches,
            layer_option=alpa_tpu.AutoLayerOption(
                layer_num=case.method_kwargs.get("layer_num", 2)),
            stage_option=AutoStageOption(
                profiling_database_filename=case.method_kwargs.get(
                    "prof_db")))
    elif case.method == "dp":
        method = alpa_tpu.DataParallel(
            num_micro_batches=case.num_micro_batches)
    elif case.method == "zero3":
        method = alpa_tpu.Zero3Parallel(
            num_micro_batches=case.num_micro_batches)
    else:
        method = alpa_tpu.ShardParallel(
            num_micro_batches=case.num_micro_batches)

    @alpa_tpu.parallelize(method=method, donate_argnums=(0,))
    def train_step(state, batch):
        loss, grads = alpa_tpu.value_and_grad(
            lambda p: loss_of(state, p, batch))(state.params)
        return state.apply_gradients(grads=grads), loss

    return train_step, state, batch, flops, tokens


def run_case(case, warmup=3, n_iter=8):
    import alpa_tpu
    alpa_tpu.init(cluster="local")
    train_step, state, batch, flops, tokens = build_case(case)
    tic = time.time()
    for _ in range(warmup):
        state, loss = train_step(state, batch)
        float(loss)
    compile_and_warm = time.time() - tic
    tic = time.perf_counter()
    for _ in range(n_iter):
        state, loss = train_step(state, batch)
    float(loss)
    latency = (time.perf_counter() - tic) / n_iter
    return {
        "case": case.name,
        "latency_s": round(latency, 5),
        "tflops_per_device": round(flops(latency), 2),
        "tokens_per_sec": round(tokens / latency, 1),
        "warmup_s": round(compile_and_warm, 1),
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--suite", required=True)
    parser.add_argument("--dump", default="benchmark_results.tsv")
    parser.add_argument("--niter", type=int, default=8)
    parser.add_argument("--platform", default=None, choices=["cpu"],
                        help="'cpu' pins a virtual CPU mesh (required "
                        "for CPU runs on machines whose sitecustomize "
                        "pins a TPU backend — env JAX_PLATFORMS alone "
                        "is not honored there); omit to use whatever "
                        "backend jax selects")
    parser.add_argument("--cpu-devices", type=int, default=8)
    args = parser.parse_args()

    if args.platform == "cpu":
        from alpa_tpu.platform import pin_cpu_platform
        pin_cpu_platform(args.cpu_devices)

    from benchmark.suites import suites
    from alpa_tpu.util import write_tsv

    cases = suites[args.suite]
    for case in cases:
        result = run_case(case, n_iter=args.niter)
        heads = list(result.keys())
        write_tsv(heads, [result[h] for h in heads], args.dump)


if __name__ == "__main__":
    main()
