"""Package setup (ref: the reference repo's setup.py).

Builds the native stage-DP solver as part of installation; the library
also self-builds it lazily at first use via csrc/Makefile.
"""
import os
import subprocess

from setuptools import Command, find_packages, setup
from setuptools.command.build_py import build_py


class BuildNative(build_py):

    def run(self):
        csrc = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "csrc")
        if os.path.exists(os.path.join(csrc, "Makefile")):
            try:
                subprocess.run(["make", "-C", csrc], check=True)
            except Exception as e:  # pylint: disable=broad-except
                print(f"warning: native build skipped ({e})")
        super().run()


setup(
    name="alpa_tpu",
    version="0.1.0",
    description=("TPU-native automatic inter- and intra-operator "
                 "parallelization for JAX programs"),
    packages=find_packages(include=["alpa_tpu", "alpa_tpu.*"]),
    package_data={"alpa_tpu": ["_native/*.so"]},
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "flax",
        "optax",
        "numpy",
        "scipy",
    ],
    cmdclass={"build_py": BuildNative},
)
