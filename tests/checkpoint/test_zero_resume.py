"""Checkpoint round-trip of ZeRO-sharded optimizer state across a
data-parallel degree change (ISSUE 10): saved at dp=4, restored at
dp=2 via ShardStore resharding-on-read, resuming training bitwise
identically — and the plan fingerprint (which covers zero_stage through
the hashed shardings) refuses a silent cross-plan restore.
"""
import jax
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu.checkpoint.manager import (CheckpointManager,
                                         PlanFingerprintMismatch)
from alpa_tpu.parallel_method import Zero2Parallel
from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                              get_mlp_train_step)


@pytest.fixture(autouse=True)
def _reset_ckpt_metrics():
    # keep the process-global checkpoint counters clean for later tests
    # (test_telemetry pins their exact values)
    from alpa_tpu.checkpoint import metrics
    yield
    metrics.reset()


def _tree_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestZeroDpResume:

    def test_saved_dp4_restored_dp2_bitwise_resume(self, tmp_path):
        alpa_tpu.init("local")

        # ---- train 2 steps at dp=4 with sharded optimizer state ----
        m4 = Zero2Parallel(devices=jax.devices()[:4])
        step4 = get_mlp_train_step(m4, use_value_and_grad=True)
        state4, batch = create_mlp_train_state_and_batch(16,
                                                         hidden_dim=64)
        for _ in range(2):
            state4, _ = step4(state4, batch)
        # the state really is ZeRO-partitioned at save time
        mu = state4.opt_state[0].trace["params"]["Dense_0"]["kernel"]
        assert np.prod(mu.sharding.shard_shape(mu.shape)) < \
            np.prod(mu.shape)
        truth = jax.device_get(
            jax.tree_util.tree_map(np.asarray, state4))

        ma = CheckpointManager(str(tmp_path), async_save=False)
        ma.save(2, state4, executable=step4.get_last_executable())
        ma.wait()

        # ---- dp=2: different mesh, different plan ----
        m2 = Zero2Parallel(devices=jax.devices()[:2])
        step2 = get_mlp_train_step(m2, use_value_and_grad=True)
        seed2, _ = create_mlp_train_state_and_batch(16, hidden_dim=64)
        compiled_state, _ = step2(seed2, batch)  # compile; get layouts
        shardings = jax.tree_util.tree_map(lambda x: x.sharding,
                                           compiled_state)

        # the saved fingerprint covers the dp=4 ZeRO plan: restoring
        # under the dp=2 plan must fail loudly, not load silently
        with pytest.raises(PlanFingerprintMismatch):
            ma.restore(
                create_mlp_train_state_and_batch(16, hidden_dim=64)[0],
                executable=step2.get_last_executable())

        # explicit cross-plan restore: reshard-on-read into the dp=2
        # ZeRO layout must reassemble every shard bitwise
        target = create_mlp_train_state_and_batch(16, hidden_dim=64)[0]
        restored = ma.restore(target, shardings=shardings)
        _tree_equal(restored, truth)

        # ---- resumed training is bitwise identical to a replicated
        # restore advanced by the same step (the sharded layout is
        # pure bookkeeping) ----
        host_target = create_mlp_train_state_and_batch(
            16, hidden_dim=64)[0]
        host_restored = ma.restore(host_target)
        next_a, loss_a = step2(restored, batch)
        next_b, loss_b = step2(host_restored, batch)
        np.testing.assert_array_equal(np.asarray(loss_a),
                                      np.asarray(loss_b))
        _tree_equal(next_a, next_b)
