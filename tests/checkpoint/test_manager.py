"""CheckpointManager: async overlap, failure surfacing, resume safety.

Holds two ISSUE 3 acceptance tests: the async save must block the
training loop for <10% of a synchronous save of the same state
(asserted via the manager's recorded blocking time), and a checkpoint
saved on one mesh shape must restore bit-exactly onto another.
"""
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alpa_tpu import fault
from alpa_tpu.checkpoint.manager import (CheckpointManager,
                                         CheckpointSaveError,
                                         PlanFingerprintMismatch,
                                         RecoveryCheckpointer)
from alpa_tpu.checkpoint.policy import RetentionPolicy


def _state(seed=0, n=4, shape=(32, 16)):
    rng = np.random.default_rng(seed)
    return {"params": {f"layer{i}": {
        "kernel": rng.standard_normal(shape).astype(np.float32),
        "bias": rng.standard_normal(shape[1:]).astype(np.float32),
    } for i in range(n)}, "step": np.int64(seed)}


def _assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestRoundtrip:

    def test_nested_pytree_bit_exact(self, tmp_path):
        ma = CheckpointManager(str(tmp_path), async_save=False)
        state = _state(0)
        ma.save(3, state, plan_fingerprint="fp0")
        ma.wait()
        assert ma.latest_step() == 3
        restored = ma.restore(_state(99), expected_plan_fingerprint="fp0")
        _assert_trees_equal(restored, state)

    def test_restore_missing_leaf_is_loud(self, tmp_path):
        ma = CheckpointManager(str(tmp_path), async_save=False)
        ma.save(1, {"a": np.ones(4, np.float32)})
        with pytest.raises(KeyError, match="no leaf"):
            ma.restore({"a": np.zeros(4, np.float32),
                        "b": np.zeros(4, np.float32)})

    def test_retention_applied_after_each_save(self, tmp_path):
        ma = CheckpointManager(str(tmp_path), async_save=False,
                               policy=RetentionPolicy(keep_last_k=2))
        for step in (1, 2, 3, 4):
            ma.save(step, {"w": np.full(8, float(step), np.float32)})
        ma.wait()
        assert ma.all_steps() == [3, 4]
        restored = ma.restore({"w": np.zeros(8, np.float32)})
        np.testing.assert_array_equal(restored["w"], np.full(8, 4.0))


class TestAsyncOverlap:
    """Acceptance: async save blocks <10% of a synchronous save."""

    @staticmethod
    def _big_state(seed):
        # ~64 MB so disk write time dominates staging time; distinct
        # seeds so content-address dedupe cannot shrink either write
        rng = np.random.default_rng(seed)
        return {f"p{i}": jnp.asarray(
            rng.standard_normal((1024, 2048)).astype(np.float32))
            for i in range(8)}

    def test_async_blocking_under_10pct_of_sync(self, tmp_path):
        import time
        sync_ma = CheckpointManager(str(tmp_path / "sync"))
        state = self._big_state(0)
        jax.block_until_ready(state)
        t0 = time.perf_counter()
        sync_ma.save(1, state, sync=True)
        t_sync = time.perf_counter() - t0

        async_ma = CheckpointManager(str(tmp_path / "async"))
        state2 = self._big_state(1)
        jax.block_until_ready(state2)
        async_ma.save(1, state2)
        blocking = async_ma.last_blocking_seconds
        async_ma.wait()

        assert async_ma.latest_step() == 1
        assert async_ma.store.verify_step(1)["ok"]
        # measured locally: ratio ~0.025 — 0.10 leaves 4x CI headroom
        assert blocking < 0.10 * t_sync, (
            f"async save blocked {blocking:.4f}s vs sync {t_sync:.4f}s "
            f"(ratio {blocking / t_sync:.3f} >= 0.10)")
        assert async_ma.last_staging_seconds <= blocking + 1e-9

    def test_double_buffer_serializes_writes(self, tmp_path):
        """save(N+1) joins save(N)'s write: never two writes in
        flight, and every step lands committed."""
        ma = CheckpointManager(str(tmp_path))
        in_flight = []
        max_in_flight = []
        real_write = ma.store.write_step

        def tracking_write(*args, **kwargs):
            in_flight.append(1)
            max_in_flight.append(len(in_flight))
            try:
                return real_write(*args, **kwargs)
            finally:
                in_flight.pop()

        ma.store.write_step = tracking_write
        for step in range(1, 6):
            ma.save(step, {"w": np.full(64, float(step), np.float32)})
        ma.wait()
        assert max(max_in_flight) == 1
        assert ma.all_steps() == [1, 2, 3, 4, 5]


class TestFailureSurfacing:

    def test_background_failure_raises_from_wait(self, tmp_path):
        ma = CheckpointManager(str(tmp_path))

        def boom(*args, **kwargs):
            raise OSError("disk full")

        ma.store.write_step = boom
        ma.save(7, {"w": np.ones(8, np.float32)})
        with pytest.raises(CheckpointSaveError, match="disk full") as ei:
            ma.wait()
        assert ei.value.step == 7
        assert ma.latest_step() is None        # atomic: no manifest

    def test_background_failure_raises_from_next_save(self, tmp_path):
        ma = CheckpointManager(str(tmp_path))
        real_write = ma.store.write_step
        calls = []

        def boom_once(*args, **kwargs):
            calls.append(1)
            if len(calls) == 1:
                raise OSError("disk full")
            return real_write(*args, **kwargs)

        ma.store.write_step = boom_once
        ma.save(1, {"w": np.ones(8, np.float32)})
        ma._pending.join()                     # write thread has failed
        with pytest.raises(CheckpointSaveError):
            ma.save(2, {"w": np.zeros(8, np.float32)})
        # the error was consumed; the manager keeps working
        ma.save(2, {"w": np.zeros(8, np.float32)})
        ma.wait()
        assert ma.latest_step() == 2


class TestPlanFingerprint:

    def test_mismatch_refuses_restore(self, tmp_path):
        ma = CheckpointManager(str(tmp_path), async_save=False)
        ma.save(1, {"w": np.ones(8, np.float32)},
                plan_fingerprint="a" * 64)
        with pytest.raises(PlanFingerprintMismatch, match="saved under"):
            ma.restore({"w": np.zeros(8, np.float32)},
                       expected_plan_fingerprint="b" * 64)
        # matching fingerprint restores fine
        ma.restore({"w": np.zeros(8, np.float32)},
                   expected_plan_fingerprint="a" * 64)

    def test_fingerprint_taken_from_executable(self, tmp_path):

        class FakeExecutable:

            def __init__(self, fp):
                self._fp = fp

            def get_plan_fingerprint(self):
                return self._fp

        ma = CheckpointManager(str(tmp_path), async_save=False)
        ma.save(1, {"w": np.ones(8, np.float32)},
                executable=FakeExecutable("plan-x"))
        assert ma.store.read_manifest(1)["plan_fingerprint"] == "plan-x"
        with pytest.raises(PlanFingerprintMismatch):
            ma.restore({"w": np.zeros(8, np.float32)},
                       executable=FakeExecutable("plan-y"))

    def test_unstamped_checkpoint_restores_with_warning(self, tmp_path):
        ma = CheckpointManager(str(tmp_path), async_save=False)
        ma.save(1, {"w": np.ones(8, np.float32)})
        # no saved fingerprint: cannot validate, must not hard-fail
        ma.restore({"w": np.zeros(8, np.float32)},
                   expected_plan_fingerprint="c" * 64)


class TestCrossMeshRestore:
    """Acceptance: save on mesh shape A, restore onto mesh shape B,
    bit-exact (resharding-on-read)."""

    def test_8x1_to_2x4_bit_exact(self, tmp_path):
        devices = jax.devices()
        assert len(devices) >= 8, "conftest pins 8 virtual CPU devices"
        arr = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)

        mesh_a = Mesh(np.array(devices[:8]).reshape(8), ("x",))
        sharded_a = jax.device_put(
            arr, NamedSharding(mesh_a, P("x", None)))
        ma = CheckpointManager(str(tmp_path), async_save=False)
        ma.save(1, {"w": sharded_a})

        mesh_b = Mesh(np.array(devices[:8]).reshape(2, 4), ("x", "y"))
        shard_b = NamedSharding(mesh_b, P("x", "y"))
        restored = ma.restore({"w": arr}, shardings={"w": shard_b})
        out = restored["w"]
        assert isinstance(out, jax.Array)
        assert out.sharding.is_equivalent_to(shard_b, out.ndim)
        np.testing.assert_array_equal(np.asarray(out), arr)
        # each device holds only its (8, 2) slice
        assert out.addressable_shards[0].data.shape == (8, 2)

    def test_sharded_to_host_bit_exact(self, tmp_path):
        devices = jax.devices()
        arr = np.random.default_rng(3).standard_normal(
            (24, 4)).astype(np.float32)
        mesh = Mesh(np.array(devices[:4]).reshape(4), ("x",))
        sharded = jax.device_put(arr, NamedSharding(mesh, P("x", None)))
        ma = CheckpointManager(str(tmp_path), async_save=False)
        ma.save(1, {"w": sharded})
        restored = ma.restore({"w": np.zeros_like(arr)})
        np.testing.assert_array_equal(restored["w"], arr)


class TestRecoveryCheckpointer:
    """fault.RecoveryManager wiring: snapshot on real degradation only,
    automatic restore of the last verified step on recovery."""

    def _make(self, tmp_path, probe_ok):
        live = {"state": _state(1, n=1, shape=(8,))}
        recovery = fault.RecoveryManager(
            mesh_group=["m0"],
            probe=lambda mesh: probe_ok[0],
            retry_policy=fault.RetryPolicy(max_attempts=1,
                                           base_delay=0.0, max_delay=0.0,
                                           jitter=0.0))
        ma = CheckpointManager(str(tmp_path), async_save=False)
        ckpt = RecoveryCheckpointer(
            ma, recovery,
            state_provider=lambda: live["state"],
            state_setter=lambda s: live.__setitem__("state", s),
            plan_fingerprint="fp")
        return live, recovery, ckpt

    def test_transient_blip_no_snapshot_no_restore(self, tmp_path):
        probe_ok = [True]                      # re-probe passes at once
        live, recovery, ckpt = self._make(tmp_path, probe_ok)
        recovery.observe([0])
        assert recovery.state is fault.MeshHealth.HEALTHY
        assert ckpt.snapshots_saved == 0
        assert ckpt.restores_done == 0

    def test_degrade_snapshots_then_recover_restores(self, tmp_path):
        probe_ok = [False]
        live, recovery, ckpt = self._make(tmp_path, probe_ok)
        original = jax.tree_util.tree_map(np.copy, live["state"])

        recovery.observe([0])                  # -> RECOVERING -> DEGRADED
        assert recovery.state is fault.MeshHealth.DEGRADED
        assert ckpt.snapshots_saved == 1
        assert ckpt.manager.latest_step() == 1
        assert ckpt.manager.store.verify_step(1)["ok"]

        # the in-flight state is lost/corrupted during the outage
        live["state"]["params"]["layer0"]["kernel"][:] = -1.0

        probe_ok[0] = True
        recovery.observe([])                   # clean round -> HEALTHY
        assert recovery.state is fault.MeshHealth.HEALTHY
        assert ckpt.restores_done == 1
        _assert_trees_equal(live["state"], original)
