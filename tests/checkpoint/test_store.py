"""Content-addressed shard store: atomicity, integrity, retention.

Covers the ISSUE 3 acceptance points that live at the store layer: a
``kill -9`` simulated between chunk write and manifest commit leaves the
prior step as the latest restorable one, and retention GC removes
exactly the chunks no surviving manifest references.
"""
import json
import os

import numpy as np
import pytest

from alpa_tpu.checkpoint import metrics
from alpa_tpu.checkpoint.policy import RetentionPolicy
from alpa_tpu.checkpoint.store import (ChunkCorruptionError,
                                       CheckpointNotFoundError, ShardStore)


def _leaves(arr, name="w"):
    index = tuple((0, d) for d in arr.shape) if arr.ndim else ()
    return {name: {"shape": list(arr.shape), "dtype": str(arr.dtype),
                   "pieces": [(index, arr)]}}


def _full(shape):
    return tuple((0, d) for d in shape) if shape else ()


class TestChunks:

    def test_put_read_roundtrip_and_dedupe(self, tmp_path):
        store = ShardStore(str(tmp_path))
        data = b"hello chunk"
        h1 = store.put_chunk(data)
        h2 = store.put_chunk(data)
        assert h1 == h2
        assert store.read_chunk(h1) == data
        # exactly one file on disk for the duplicate put
        assert os.path.exists(store.chunk_path(h1))

    def test_corrupt_chunk_detected(self, tmp_path):
        store = ShardStore(str(tmp_path))
        h = store.put_chunk(b"precious bytes")
        with open(store.chunk_path(h), "wb") as f:
            f.write(b"precious BYTES")          # same length, flipped bits
        with pytest.raises(ChunkCorruptionError, match="hash"):
            store.read_chunk(h)
        # verify=False trusts the name (the fast path hot_swap avoids)
        assert store.read_chunk(h, verify=False) == b"precious BYTES"

    def test_missing_chunk_is_corruption(self, tmp_path):
        store = ShardStore(str(tmp_path))
        h = store.put_chunk(b"x")
        os.unlink(store.chunk_path(h))
        with pytest.raises(ChunkCorruptionError, match="missing"):
            store.read_chunk(h)


class TestManifestAtomicity:

    def test_crash_between_chunks_and_commit(self, tmp_path):
        """kill -9 mid-save: chunks on disk, no manifest — the step does
        not exist and the prior step stays latest AND fully verified."""
        store = ShardStore(str(tmp_path))
        good = np.arange(32.0, dtype=np.float32)
        store.write_step(1, _leaves(good))

        # simulate the kill: write step 2's chunks but die before commit
        doomed = np.full(64, 7.0, dtype=np.float32)
        store.put_chunk(np.ascontiguousarray(doomed).tobytes())

        assert store.all_steps() == [1]
        assert store.latest_step() == 1
        assert store.last_verified_step() == 1
        report = store.verify_step(1)
        assert report["ok"] and report["n_chunks"] == 1
        out = store.read_leaf_slice(store.read_manifest(1)["leaves"]["w"],
                                    _full(good.shape))
        np.testing.assert_array_equal(out, good)
        # gc reclaims the orphaned chunks of the dead save
        removed = store.gc()
        assert removed["chunks_removed"] == 1
        assert store.verify_step(1)["ok"]

    def test_crash_during_commit_leaves_no_manifest(self, tmp_path,
                                                    monkeypatch):
        store = ShardStore(str(tmp_path))
        store.write_step(5, _leaves(np.ones(4, np.float32)))

        real_rename = os.rename

        def dying_rename(src, dst):
            if "manifests" in dst:
                raise OSError("simulated kill -9 during rename")
            return real_rename(src, dst)

        monkeypatch.setattr(os, "rename", dying_rename)
        with pytest.raises(OSError):
            store.write_step(6, _leaves(np.zeros(4, np.float32)))
        monkeypatch.undo()
        assert store.latest_step() == 5
        assert store.last_verified_step() == 5

    def test_read_missing_step(self, tmp_path):
        store = ShardStore(str(tmp_path))
        with pytest.raises(CheckpointNotFoundError):
            store.read_manifest()
        with pytest.raises(CheckpointNotFoundError):
            store.read_manifest(3)


class TestVerification:

    def test_verify_step_flags_corruption(self, tmp_path):
        store = ShardStore(str(tmp_path))
        arr = np.random.default_rng(0).standard_normal(128).astype(
            np.float32)
        manifest = store.write_step(1, _leaves(arr))
        h = manifest["leaves"]["w"]["chunks"][0]["hash"]
        with open(store.chunk_path(h), "r+b") as f:
            f.write(b"\x00\x00\x00\x00")
        report = store.verify_step(1)
        assert not report["ok"]
        assert report["bad"][0]["leaf"] == "w"
        assert store.last_verified_step() is None

    def test_last_verified_skips_corrupt_newest(self, tmp_path):
        store = ShardStore(str(tmp_path))
        store.write_step(1, _leaves(np.arange(8, dtype=np.int32)))
        m2 = store.write_step(2, _leaves(np.arange(8, 16,
                                                   dtype=np.int32)))
        os.unlink(store.chunk_path(m2["leaves"]["w"]["chunks"][0]["hash"]))
        assert store.latest_step() == 2
        assert store.last_verified_step() == 1


class TestChunkingAndResharding:

    def test_large_piece_splits_and_reassembles(self, tmp_path):
        store = ShardStore(str(tmp_path))
        arr = np.random.default_rng(1).standard_normal(
            (64, 32)).astype(np.float32)
        manifest = store.write_step(1, _leaves(arr), chunk_bytes=1024)
        ents = manifest["leaves"]["w"]["chunks"]
        assert len(ents) > 1                       # actually chunked
        out = store.read_leaf_slice(manifest["leaves"]["w"],
                                    _full(arr.shape))
        np.testing.assert_array_equal(out, arr)

    def test_read_arbitrary_slice_across_chunk_boundaries(self, tmp_path):
        store = ShardStore(str(tmp_path))
        arr = np.arange(64 * 8, dtype=np.float32).reshape(64, 8)
        manifest = store.write_step(1, _leaves(arr), chunk_bytes=256)
        leaf = manifest["leaves"]["w"]
        # a slice no single saved chunk covers
        out = store.read_leaf_slice(leaf, ((5, 40), (2, 7)))
        np.testing.assert_array_equal(out, arr[5:40, 2:7])

    def test_hole_in_index_map_raises(self, tmp_path):
        store = ShardStore(str(tmp_path))
        arr = np.ones((8, 4), np.float32)
        manifest = store.write_step(1, _leaves(arr), chunk_bytes=64)
        leaf = json.loads(json.dumps(manifest["leaves"]["w"]))
        assert len(leaf["chunks"]) > 1
        del leaf["chunks"][0]                           # half missing
        with pytest.raises(ChunkCorruptionError, match="holes"):
            store.read_leaf_slice(leaf, _full((8, 4)))

    def test_scalar_leaf(self, tmp_path):
        store = ShardStore(str(tmp_path))
        arr = np.float32(3.25)
        manifest = store.write_step(1, _leaves(arr))
        out = store.read_leaf_slice(manifest["leaves"]["w"], ())
        assert out.shape == () and out == np.float32(3.25)


class TestRetentionGC:

    def test_policy_selection(self):
        pol = RetentionPolicy(keep_last_k=2, keep_every_n=10)
        steps = [1, 5, 10, 15, 20, 21, 22]
        assert pol.surviving(steps) == [10, 20, 21, 22]
        assert pol.to_delete(steps) == [1, 5, 15]
        assert RetentionPolicy(keep_last_k=0).to_delete(steps) == []

    def test_gc_removes_only_unreferenced_chunks(self, tmp_path):
        """keep-last-K: deleted steps' chunks vanish UNLESS a surviving
        manifest still references them (content addressing shares
        chunks across steps)."""
        store = ShardStore(str(tmp_path))
        shared = np.arange(16, dtype=np.float32)       # same every step
        for step in (1, 2, 3):
            unique = np.full(16, float(step), np.float32)
            leaves = {}
            leaves.update(_leaves(shared, "frozen"))
            leaves.update(_leaves(unique, "hot"))
            store.write_step(step, leaves)

        pol = RetentionPolicy(keep_last_k=2)
        doomed_hash = store.read_manifest(1)["leaves"]["hot"]["chunks"][0][
            "hash"]
        shared_hash = store.read_manifest(1)["leaves"]["frozen"]["chunks"][
            0]["hash"]
        for s in pol.to_delete(store.all_steps()):
            store.delete_step(s)
        result = store.gc()

        assert store.all_steps() == [2, 3]
        assert result["chunks_removed"] == 1           # step 1's "hot"
        assert not store.has_chunk(doomed_hash)
        assert store.has_chunk(shared_hash)            # still referenced
        for s in (2, 3):
            assert store.verify_step(s)["ok"]

    def test_gc_metrics_accumulate(self, tmp_path):
        metrics.reset()
        store = ShardStore(str(tmp_path))
        store.write_step(1, _leaves(np.ones(8, np.float32)))
        store.delete_step(1)
        store.gc()
        stats = metrics.snapshot()
        assert stats["gc_chunks_removed"] == 1
        assert stats["gc_bytes_freed"] == 32
