"""Paged KV cache (serve/kv_cache.py, ISSUE 11): block refcounting and
copy-on-write, cross-request prefix reuse, LRU eviction safety, and the
tier-1 pinned invariant — paged decode is bit-exact vs the unpaged
engine on both the miss and the reuse-hit path."""
import threading

import numpy as np
import pytest

from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
from alpa_tpu.serve.engine import ContinuousBatchingEngine
from alpa_tpu.serve.generation import GenerationConfig, Generator
from alpa_tpu.serve.kv_cache import KVBlockPool, KVPoolExhaustedError

BS = 8  # tokens per block in these tests (seq_len 32 -> 4 blocks/seq)


def _cfg(seq_len=32):
    return GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                     seq_len=seq_len, vocab_size=64)


def _tiny(seq_len=32, **gen_kwargs):
    cfg = _cfg(seq_len)
    model, params = init_gpt_real(cfg, 1)
    return Generator(model, params, cfg, **gen_kwargs)


def _paged_engine(max_batch=2, num_blocks=None, prefix_reuse=True,
                  **pool_kwargs):
    gen = _tiny(prefill_chunk=BS)
    pool = KVBlockPool.for_generator(gen, max_batch=max_batch,
                                     block_size=BS,
                                     num_blocks=num_blocks,
                                     prefix_reuse=prefix_reuse,
                                     **pool_kwargs)
    eng = ContinuousBatchingEngine(gen, max_batch=max_batch,
                                   kv_pool=pool)
    return eng, pool


PROMPT = np.array([5, 9, 3, 7, 1, 2, 8, 4, 6, 11, 13, 2], np.int32)
GCFG = GenerationConfig(max_new_tokens=6, temperature=0.0)


class TestBlockPool:
    """Pool mechanics without an engine."""

    def test_alloc_release_refcount(self):
        pool = KVBlockPool(_cfg(), block_size=BS, prefix_reuse=False)
        toks = np.arange(20, dtype=np.int32)
        seq = pool.begin_sequence(toks, 8)     # 28 tokens -> 4 blocks
        assert len(seq.ids) == 4
        assert pool.blocks_in_use() == 4
        assert 0 not in seq.ids, "block 0 is scratch, never handed out"
        pool.release(seq, register=False)
        assert pool.blocks_in_use() == 0

    def test_prefix_reuse_hit_and_bytes_saved(self):
        pool = KVBlockPool(_cfg(), block_size=BS)
        toks = np.arange(20, dtype=np.int32)
        s1 = pool.begin_sequence(toks, 4)
        assert s1.matched_tokens == 0
        s1_ids = list(s1.ids)
        pool.release(s1, tokens=toks, register=True)
        before = pool.stats()
        s2 = pool.begin_sequence(toks, 4)
        # match is capped below the full prompt: the last prompt token
        # is always recomputed (its logits seed decode)
        assert s2.matched_tokens == 16
        after = pool.stats()
        assert after["prefix_hits"] == before["prefix_hits"] + 1
        assert (after["bytes_saved"] - before["bytes_saved"]
                == 16 * pool.token_bytes)
        # matched blocks are SHARED with the cache entries
        assert s2.ids[0] == s1_ids[0] and s2.ids[1] == s1_ids[1]
        pool.release(s2, register=False)

    def test_divergent_suffix_shares_only_common_blocks(self):
        pool = KVBlockPool(_cfg(), block_size=BS)
        a = np.arange(24, dtype=np.int32)
        b = np.concatenate([a[:8], np.array([99] * 16, np.int32)])
        s1 = pool.begin_sequence(a, 4)
        s1_ids = list(s1.ids)
        pool.release(s1, tokens=a, register=True)
        s2 = pool.begin_sequence(b, 4)
        assert s2.matched_tokens == 8          # only the first block
        assert s2.ids[0] == s1_ids[0]
        assert s2.ids[1] != s1_ids[1]
        pool.release(s2, register=False)

    def test_fork_and_cow(self):
        pool = KVBlockPool(_cfg(), block_size=BS, prefix_reuse=False)
        toks = np.arange(16, dtype=np.int32)
        s1 = pool.begin_sequence(toks, 8)
        s2 = pool.fork(s1)
        assert s1.ids == s2.ids
        shared = s2.ids[0]
        nb = pool.ensure_writable(s2, 0)       # rc 2 -> copy
        assert s2.ids[0] != shared and s1.ids[0] == shared
        assert nb == s2.ids[0]
        # now exclusive: no further copy
        assert pool.ensure_writable(s2, 0) == nb
        pool.release(s1, register=False)
        pool.release(s2, register=False)
        assert pool.blocks_in_use() == 0

    def test_exhaustion_and_transient_backpressure(self):
        pool = KVBlockPool(_cfg(), block_size=BS, num_blocks=4,
                           prefix_reuse=False)
        with pytest.raises(KVPoolExhaustedError):
            pool.begin_sequence(np.arange(30, dtype=np.int32), 8)
        s1 = pool.begin_sequence(np.arange(20, dtype=np.int32), 8)
        # pool full -> transient None, not an exception
        assert pool.begin_sequence(np.arange(4, dtype=np.int32),
                                   8) is None
        pool.release(s1, register=False)
        s2 = pool.begin_sequence(np.arange(4, dtype=np.int32), 8)
        assert s2 is not None
        pool.release(s2, register=False)

    def test_eviction_under_pressure_never_touches_live_blocks(self):
        pool = KVBlockPool(_cfg(), block_size=BS, num_blocks=8)
        live_toks = np.arange(16, dtype=np.int32)
        live = pool.begin_sequence(live_toks, 8)      # 3 blocks live
        live_ids = list(live.ids)
        # populate the cache with a finished chain, then demand enough
        # blocks that the LRU cache must be evicted
        done_toks = np.array([40 + i for i in range(16)], np.int32)
        done = pool.begin_sequence(done_toks, 8)
        pool.release(done, tokens=done_toks, register=True)
        assert pool.stats()["cached_entries"] >= 2
        big = pool.begin_sequence(np.array([70 + i for i in range(16)],
                                           np.int32), 16)
        assert big is not None
        assert pool.stats()["evictions"] >= 1
        assert live.ids == live_ids, "live block table must not move"
        assert not set(big.ids) & set(live_ids), \
            "evictor handed out a live block"
        pool.release(big, register=False)
        pool.release(live, register=False)

    def test_warm_prefix_is_pinned_against_eviction(self):
        gen = _tiny(prefill_chunk=BS)
        pool = KVBlockPool.for_generator(gen, max_batch=1, block_size=BS,
                                         num_blocks=8)
        prefix = np.arange(16, dtype=np.int32)
        assert pool.warm_prefix(gen, prefix) == 16
        assert pool.stats()["pinned_entries"] == 2
        # churn: fill and release unrelated sequences to pressure LRU
        for i in range(4):
            toks = np.array([30 + 8 * i + j for j in range(16)], np.int32)
            s = pool.begin_sequence(toks, 8)
            if s is not None:
                pool.release(s, tokens=toks, register=True)
        s = pool.begin_sequence(np.concatenate(
            [prefix, np.array([1, 2, 3], np.int32)]), 4)
        assert s is not None and s.matched_tokens == 16, \
            "pinned warm prefix must survive cache churn"
        pool.release(s, register=False)


class TestPagedEngineBitExact:
    """Tier-1 pinned invariant: the paged engine's greedy outputs are
    IDENTICAL (np.array_equal, not allclose) to the unpaged engine's
    for the same weights and prompts — miss path, reuse-hit path, and
    shared-prefix partial-hit path."""

    def test_paged_matches_unpaged_bitwise(self):
        gen_u = _tiny(prefill_chunk=BS)
        eng_u = ContinuousBatchingEngine(gen_u, max_batch=2)
        eng_p, pool = _paged_engine()
        try:
            want = eng_u.submit(PROMPT, GCFG)
            # 1) cold pool: the no-hit admission path
            miss = eng_p.submit(PROMPT, GCFG)
            np.testing.assert_array_equal(want, miss)
            assert pool.stats()["prefix_hits"] == 0
            # 2) identical prompt again: full-prefix reuse hit
            hit = eng_p.submit(PROMPT, GCFG)
            np.testing.assert_array_equal(want, hit)
            assert pool.stats()["prefix_hits"] == 1
            # 3) shared prefix, divergent suffix: partial hit
            p2 = np.concatenate([PROMPT[:8],
                                 np.array([20, 21, 22], np.int32)])
            want2 = eng_u.submit(p2, GCFG)
            got2 = eng_p.submit(p2, GCFG)
            np.testing.assert_array_equal(want2, got2)
            assert pool.stats()["prefix_hits"] == 2
        finally:
            eng_u.shutdown()
            eng_p.shutdown()

    def test_concurrent_paged_requests_all_exact(self):
        gen_u = _tiny(prefill_chunk=BS)
        eng_u = ContinuousBatchingEngine(gen_u, max_batch=2)
        eng_p, _pool = _paged_engine(max_batch=2)
        prompts = [np.array([1 + i, 2, 3, 4 + i], np.int32)
                   for i in range(6)]
        try:
            want = [eng_u.submit(p, GCFG) for p in prompts]
            got = [None] * len(prompts)
            errs = []

            def run(i):
                try:
                    got[i] = eng_p.submit(prompts[i], GCFG)
                except Exception as e:  # pylint: disable=broad-except
                    errs.append(e)

            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs, errs
            for w, g in zip(want, got):
                np.testing.assert_array_equal(w, g)
        finally:
            eng_u.shutdown()
            eng_p.shutdown()


class TestPagedEngineBehavior:

    def test_backpressure_serializes_on_tiny_pool(self):
        """One sequence's worth of blocks: concurrent submits must
        serialize via admission backpressure, not error or corrupt."""
        eng, pool = _paged_engine(max_batch=2, num_blocks=4,
                                  prefix_reuse=False)
        gen_u = _tiny(prefill_chunk=BS)
        eng_u = ContinuousBatchingEngine(gen_u, max_batch=2)
        prompts = [np.array([i + 1, i + 2, i + 3], np.int32)
                   for i in range(4)]
        try:
            want = [eng_u.submit(p, GCFG) for p in prompts]
            got = [None] * 4
            errs = []

            def run(i):
                try:
                    got[i] = eng.submit(prompts[i], GCFG)
                except Exception as e:  # pylint: disable=broad-except
                    errs.append(e)

            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs, errs
            for w, g in zip(want, got):
                np.testing.assert_array_equal(w, g)
            assert pool.blocks_in_use() == 0
        finally:
            eng.shutdown()
            eng_u.shutdown()

    def test_oversized_request_rejected_at_submit(self):
        eng, _pool = _paged_engine(max_batch=1, num_blocks=2,
                                   prefix_reuse=False)
        try:
            with pytest.raises((ValueError, KVPoolExhaustedError)):
                eng.submit(np.arange(20, dtype=np.int32), GCFG)
        finally:
            eng.shutdown()

    def test_pool_and_static_prefix_are_mutually_exclusive(self):
        gen = _tiny(prefill_chunk=BS)
        pool = KVBlockPool.for_generator(gen, max_batch=1, block_size=BS)
        prefix = gen.cache_prefix(np.arange(8, dtype=np.int32))
        with pytest.raises(ValueError):
            ContinuousBatchingEngine(gen, max_batch=1, kv_pool=pool,
                                     prefix=prefix)

    def test_released_rows_return_blocks(self):
        eng, pool = _paged_engine(max_batch=2)
        try:
            for i in range(3):
                eng.submit(np.array([i + 1, 5, 9], np.int32), GCFG)
            # live tables are gone; only cached (reusable) entries hold
            # blocks, and every cached entry has rc exactly 1
            stats = pool.stats()
            assert stats["blocks_in_use"] == stats["cached_entries"]
        finally:
            eng.shutdown()
