"""Zero-downtime weight hot-swap (ISSUE 3 acceptance).

A registered model's weights are swapped from a checkpoint while
requests are in flight: zero requests error, every response is EITHER
the old-weight or the new-weight greedy output (never a blend), and
post-swap responses reflect the new weights.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from alpa_tpu.checkpoint.manager import CheckpointManager
from alpa_tpu.checkpoint.store import ChunkCorruptionError
from alpa_tpu.model.gpt_model import GPTConfig, GPTModel, init_gpt_real
from alpa_tpu.serve import GenerationConfig, Generator, run_controller
from alpa_tpu.serve.controller import Controller

PROMPT = [1, 2, 3]


def _tiny(seq_len=32, **gen_kwargs):
    cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                    seq_len=seq_len, vocab_size=64)
    model, params = init_gpt_real(cfg, 1)
    return Generator(model, params, cfg, **gen_kwargs), model, params, cfg


def _perturb(params):
    # same shapes/dtypes (executables reuse), different values
    return jax.tree_util.tree_map(lambda x: x * 0.5 + 0.25, params)


def _save_ckpt(tmp_path, params, step=1):
    ma = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    ma.save(step, params)
    ma.wait()
    return str(tmp_path / "ckpt")


def _solo(model, params, cfg, n_new=4, prompt=PROMPT):
    gen = Generator(model, params, cfg)
    out = gen.generate(np.array([prompt], np.int32),
                       GenerationConfig(max_new_tokens=n_new))
    return np.asarray(out)[0].tolist()


class TestInFlightSwap:

    def test_swap_under_concurrent_requests(self, tmp_path):
        gen, model, params, cfg = _tiny()
        new_params = _perturb(params)
        ckpt_dir = _save_ckpt(tmp_path, new_params)
        want_old = _solo(model, params, cfg)
        want_new = _solo(model, new_params, cfg)
        assert want_old != want_new, "perturbation must change outputs"

        controller = Controller()
        controller.register_model("m", gen)

        errors = []
        outputs = []
        stop = threading.Event()

        def hammer():
            try:
                while not stop.is_set():
                    out = controller.completions({
                        "model": "m", "prompt_ids": PROMPT,
                        "max_new_tokens": 4})
                    outputs.append(out["output_ids"][0])
            except Exception as e:  # pylint: disable=broad-except
                errors.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            # requests flowing on old weights before, during, after
            time.sleep(0.3)
            result = controller.reload_model("m", ckpt_dir)
            time.sleep(0.3)
        finally:
            stop.set()
            for t in threads:
                t.join()

        assert not errors, f"in-flight requests errored: {errors}"
        assert result == {"model": "m", "step": 1, "replicas_swapped": 1}
        assert controller.reloads[-1] == result
        # no torn reads: every response is exactly one weight set's output
        assert outputs
        for row in outputs:
            assert row in (want_old, want_new)
        assert want_old in outputs            # traffic before the swap
        # post-swap requests reflect the new weights
        post = controller.completions({"model": "m", "prompt_ids": PROMPT,
                                       "max_new_tokens": 4})
        assert post["output_ids"][0] == want_new

    def test_streaming_request_survives_swap(self, tmp_path):
        gen, model, params, cfg = _tiny()
        new_params = _perturb(params)
        ckpt_dir = _save_ckpt(tmp_path, new_params)

        controller = Controller()
        controller.register_model("m", gen)
        toks = []
        errors = []

        def stream():
            try:
                for t in controller.completions_stream({
                        "model": "m", "prompt_ids": PROMPT,
                        "max_new_tokens": 16}):
                    toks.append(t)
            except Exception as e:  # pylint: disable=broad-except
                errors.append(e)

        t = threading.Thread(target=stream)
        t.start()
        time.sleep(0.1)                   # stream is mid-decode
        controller.reload_model("m", ckpt_dir)
        t.join()
        # the stream either drained before the swap or finished on the
        # new weights — it must complete fully and without error
        assert not errors
        assert len(toks) == 16

    def test_prefix_model_swap_recomputes_prefix(self, tmp_path):
        """A shared-system-prompt model must never mix old prefix KV
        with new params: post-swap outputs equal whole-prompt decoding
        under the new weights."""
        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=64, vocab_size=64)
        model, params = init_gpt_real(cfg, 1)
        gen = Generator(model, params, cfg, prompt_buckets=[32],
                        prefill_chunk=8)
        system = np.random.RandomState(7).randint(0, 64, (11,)) \
            .astype(np.int32)
        new_params = _perturb(params)
        ckpt_dir = _save_ckpt(tmp_path, new_params)

        controller = Controller()
        controller.register_model("sys", gen, prefix_ids=system)
        controller.reload_model("sys", ckpt_dir)

        out = controller.completions({"model": "sys",
                                      "prompt_ids": [5, 6, 7],
                                      "max_new_tokens": 5})
        ref = Generator(model, new_params, cfg, prompt_buckets=[32],
                        prefill_chunk=8)
        want = ref.generate(np.concatenate([system, [5, 6, 7]])[None],
                            GenerationConfig(max_new_tokens=5))
        np.testing.assert_array_equal(
            np.concatenate([system, out["output_ids"][0]]),
            np.asarray(want)[0])

    def test_corrupt_checkpoint_never_touches_serving(self, tmp_path):
        """Hash verification fails in the staging phase; the replica
        keeps serving the old weights."""
        import os
        gen, model, params, cfg = _tiny()
        new_params = _perturb(params)
        ckpt_dir = _save_ckpt(tmp_path, new_params)
        want_old = _solo(model, params, cfg)

        controller = Controller()
        controller.register_model("m", gen)

        # flip bits in one chunk
        ma = CheckpointManager(ckpt_dir)
        manifest = ma.store.read_manifest(1)
        leaf = next(iter(manifest["leaves"].values()))
        with open(ma.store.chunk_path(leaf["chunks"][0]["hash"]),
                  "r+b") as f:
            f.write(b"\xff\xff\xff\xff")
        with pytest.raises(ChunkCorruptionError):
            controller.reload_model("m", ckpt_dir)
        assert controller.reloads == []
        out = controller.completions({"model": "m", "prompt_ids": PROMPT,
                                      "max_new_tokens": 4})
        assert out["output_ids"][0] == want_old


class TestAdminReloadHTTP:

    def test_post_admin_reload(self, tmp_path):
        gen, model, params, cfg = _tiny()
        new_params = _perturb(params)
        ckpt_dir = _save_ckpt(tmp_path, new_params)
        want_new = _solo(model, new_params, cfg)

        server = run_controller(port=0)
        try:
            server.controller.register_model("tiny", gen)
            base = f"http://127.0.0.1:{server.port}"

            def post(path, body):
                return urllib.request.urlopen(urllib.request.Request(
                    base + path, data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"}))

            with post("/admin/reload", {"model": "tiny",
                                        "ckpt_dir": ckpt_dir}) as r:
                out = json.load(r)
            assert out["step"] == 1 and out["replicas_swapped"] == 1

            with post("/completions", {"model": "tiny",
                                       "prompt_ids": PROMPT,
                                       "max_new_tokens": 4}) as r:
                assert json.load(r)["output_ids"][0] == want_new

            # missing fields -> 400
            with pytest.raises(urllib.error.HTTPError) as e:
                post("/admin/reload", {"model": "tiny"})
            assert e.value.code == 400
            # unknown model -> 404
            with pytest.raises(urllib.error.HTTPError) as e:
                post("/admin/reload", {"model": "nope",
                                       "ckpt_dir": ckpt_dir})
            assert e.value.code == 404
            # empty store -> 400 (no committed steps)
            with pytest.raises(urllib.error.HTTPError) as e:
                post("/admin/reload", {"model": "tiny",
                                       "ckpt_dir": str(tmp_path / "nope")})
            assert e.value.code == 400
        finally:
            server.shutdown()
