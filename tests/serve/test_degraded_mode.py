"""Graceful degradation in the serving stack.

The acceptance scenario: a scheduler exception mid-batch must not fail
the queued requests — the batcher demotes itself to a fresh FIFO queue,
carries every drained request over, and serves them all (ZERO collateral
failures) while reporting degraded health.  Watchdog-driven load
shedding: a RecoveryManager that gives up flips the controller to
"shedding", new requests bounce with 503s, and recovery restores
service.  See docs/fault_tolerance.md.
"""
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from alpa_tpu import fault
from alpa_tpu.fault import (FaultPlan, FaultSpec, InjectedFault,
                            ServiceDegradedError)
from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
from alpa_tpu.serve import (Controller, GenerationConfig, Generator,
                            run_controller)
from alpa_tpu.serve.controller import RequestBatcher
from alpa_tpu.serve.scheduler import FIFOQueue, WeightedFairQueue

pytestmark = pytest.mark.fault


def _tiny_generator(batch_size=1):
    cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4, seq_len=32,
                    vocab_size=64)
    model, params = init_gpt_real(cfg, batch_size)
    return Generator(model, params, cfg, batch_size)


def _submit_many(batcher, n, max_new_tokens=3):
    """Submit n requests from n threads; return (results, errors)."""
    results, errors = [None] * n, [None] * n

    def worker(i):
        try:
            results[i] = batcher.submit(
                [np.array([1 + i, 2, 3], np.int32)],
                GenerationConfig(max_new_tokens=max_new_tokens))
        except Exception as e:  # pylint: disable=broad-except
            errors[i] = e

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    return results, errors


class TestBatcherDegradedMode:

    def test_take_fault_serves_all_queued_requests(self):
        """THE acceptance criterion: scheduler exception during batch
        formation -> every queued request still completes, zero
        collateral failures, batcher reports degraded."""
        batcher = RequestBatcher(_tiny_generator(4), max_batch=4,
                                 scheduler=WeightedFairQueue())
        with FaultPlan(FaultSpec("scheduler_take", times=1)) as plan:
            results, errors = _submit_many(batcher, 5)
        assert plan.fired("scheduler_take") == 1
        assert errors == [None] * 5, f"collateral failures: {errors}"
        assert all(r is not None for r in results)
        assert batcher.degraded
        assert "InjectedFault" in batcher.degraded_reason
        # the replacement queue is a plain FIFO
        assert isinstance(batcher._queue, FIFOQueue)

    def test_broken_scheduler_object_degrades_once(self):
        """A custom policy whose take() itself raises: after the first
        failure the FIFO fallback owns the queue — the broken object is
        never consulted again and requests flow normally."""

        class BrokenQueue(FIFOQueue):
            take_calls = 0

            def take(self, selector):
                BrokenQueue.take_calls += 1
                raise RuntimeError("policy bug")

        batcher = RequestBatcher(_tiny_generator(2), max_batch=2,
                                 scheduler=BrokenQueue())
        results, errors = _submit_many(batcher, 3)
        assert errors == [None] * 3
        assert all(r is not None for r in results)
        assert batcher.degraded
        assert BrokenQueue.take_calls == 1
        # still serving post-degradation
        more, errs = _submit_many(batcher, 2)
        assert errs == [None] * 2 and all(r is not None for r in more)

    def test_on_degraded_callback_fires_once(self):
        batcher = RequestBatcher(_tiny_generator(2), max_batch=2)
        seen = []
        batcher.on_degraded = seen.append
        with FaultPlan(FaultSpec("scheduler_take", times=2)):
            _, errors = _submit_many(batcher, 2)
        assert errors == [None, None]
        assert len(seen) == 1
        assert isinstance(seen[0], InjectedFault)

    def test_healthy_batcher_unchanged(self):
        batcher = RequestBatcher(_tiny_generator(2), max_batch=2)
        results, errors = _submit_many(batcher, 3)
        assert errors == [None] * 3 and all(r is not None
                                            for r in results)
        assert not batcher.degraded


class TestEngineTickFaults:

    def test_mid_decode_fault_fails_batch_but_engine_survives(self):
        """A decode-tick exception loses in-flight rows (their KV state
        is gone — failing them is correct), but the engine thread stays
        alive and serves the NEXT requests."""
        from alpa_tpu.serve.engine import ContinuousBatchingEngine
        eng = ContinuousBatchingEngine(_tiny_generator(1), max_batch=1)
        try:
            with FaultPlan(FaultSpec("scheduler_tick", times=1)) as plan:
                with pytest.raises(InjectedFault):
                    eng.submit(np.array([1, 2], np.int32),
                               GenerationConfig(max_new_tokens=3))
                assert plan.fired("scheduler_tick") == 1
            assert eng.step_failures == 1
            out = eng.submit(np.array([3, 4], np.int32),
                             GenerationConfig(max_new_tokens=3))
            assert len(out) == 5
        finally:
            eng.shutdown()


class TestControllerShedding:

    def test_shedding_rejects_then_recovers(self):
        controller = Controller()
        controller.register_model("tiny", _tiny_generator())
        req = {"model": "tiny", "prompt_ids": [1, 2, 3],
               "max_new_tokens": 2}
        assert controller.completions(req)["output_ids"]
        controller.set_health("shedding", "mesh 0 unrecovered")
        with pytest.raises(ServiceDegradedError):
            controller.completions(req)
        with pytest.raises(ServiceDegradedError):
            controller.completions_stream(req)
        assert controller.health_report()["status"] == "shedding"
        controller.set_health("ok")
        assert controller.completions(req)["output_ids"]

    def test_attach_recovery_drives_shedding(self):
        """RecoveryManager DEGRADED -> controller sheds; recovery ->
        service restored.  This is the watchdog-to-serving wire."""
        from alpa_tpu.fault import MeshHealth, RecoveryManager, RetryPolicy
        controller = Controller()
        controller.register_model("tiny", _tiny_generator())
        req = {"model": "tiny", "prompt_ids": [1, 2], "max_new_tokens": 2}
        alive = {"ok": True}
        rm = RecoveryManager(
            [object()],
            retry_policy=RetryPolicy(max_attempts=2, base_delay=0.001,
                                     jitter=0.0),
            probe=lambda mesh: alive["ok"])
        controller.attach_recovery(rm)
        alive["ok"] = False
        assert rm.tick() is MeshHealth.DEGRADED
        assert controller.health_report()["status"] == "shedding"
        with pytest.raises(ServiceDegradedError):
            controller.completions(req)
        alive["ok"] = True
        assert rm.tick() is MeshHealth.HEALTHY
        assert controller.health_report()["status"] == "ok"
        assert controller.completions(req)["output_ids"]

    def test_degraded_batcher_surfaces_in_health_report(self):
        controller = Controller()
        controller.register_model("tiny", _tiny_generator(2),
                                  scheduler_factory=WeightedFairQueue)
        replica = controller._models["tiny"][0]
        with FaultPlan(FaultSpec("scheduler_take", times=1)):
            _, errors = _submit_many(replica.batcher, 2)
        assert errors == [None, None]
        report = controller.health_report()
        assert report["status"] == "degraded"
        assert report["degraded_models"] == ["tiny"]


class TestHTTPShedding:

    def test_503_and_health_endpoint(self):
        server = run_controller(port=0)
        try:
            server.controller.register_model("tiny", _tiny_generator())
            base = f"http://127.0.0.1:{server.port}"
            body = json.dumps({"model": "tiny", "prompt_ids": [1, 2],
                               "max_new_tokens": 2}).encode()

            def post():
                return urllib.request.urlopen(urllib.request.Request(
                    base + "/completions", data=body,
                    headers={"Content-Type": "application/json"}))

            with post() as r:
                assert r.status == 200
            server.controller.set_health("shedding", "recovering")
            with pytest.raises(urllib.error.HTTPError) as e:
                post()
            assert e.value.code == 503
            assert "unavailable" in json.loads(
                e.value.read())["error"]
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(base + "/health")
            assert e.value.code == 503
            assert json.loads(e.value.read())["status"] == "shedding"
            server.controller.set_health("ok")
            with post() as r:
                assert r.status == 200
            with urllib.request.urlopen(base + "/health") as r:
                assert json.loads(r.read())["status"] == "ok"
        finally:
            server.shutdown()
