"""Packed 1-D prefill (VERDICT r2 missing#5; ref opt_model_1d.py /
wrapper_1d.py): many prompts share one segment-masked forward, and the
packed KV re-gathers into the continuous-batching engine's row caches.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from alpa_tpu.model.gpt_model import GPTConfig, GPTModel, init_gpt_real
from alpa_tpu.serve.engine import ContinuousBatchingEngine
from alpa_tpu.serve.generation import GenerationConfig, Generator
from alpa_tpu.serve.packed import PackedPrefill, pack_prompts

CFG = GPTConfig(hidden_size=32, num_layers=2, num_heads=4, seq_len=32,
                vocab_size=64)


@pytest.fixture(scope="module")
def model_params():
    return init_gpt_real(CFG, 1)


PROMPTS = [np.array([1, 2, 3, 4, 5], np.int32),
           np.array([9, 8, 7], np.int32),
           np.array([11, 12, 13, 14, 15, 16, 17], np.int32)]


class TestSegmentMask:

    def test_packed_logits_match_individual(self, model_params):
        """Each prompt's logits inside the packed row equal its own
        standalone forward — segments are perfectly isolated."""
        model, params = model_params
        ids, seg, pos, starts, lens = pack_prompts(PROMPTS, 24, 4)
        packed = np.asarray(model.apply(
            params, jnp.asarray(ids), jnp.asarray(pos),
            segment_ids=jnp.asarray(seg)))
        for r, p in enumerate(PROMPTS):
            solo = np.asarray(model.apply(params, jnp.asarray(p[None])))
            span = packed[0, starts[r]:starts[r] + lens[r]]
            np.testing.assert_allclose(span, solo[0], rtol=2e-4, atol=2e-4)


class TestPackedPrefill:

    def test_rows_decode_like_plain_prefill(self, model_params):
        """Packed prefill + per-row greedy decode == plain generate."""
        model, params = model_params
        gen = Generator(model, params, CFG, batch_size=1)
        pp = PackedPrefill(model, params, CFG, total_bucket=24, max_rows=3)
        last, row_caches = pp(PROMPTS)
        assert pp.traces == 1

        for r, p in enumerate(PROMPTS):
            want = gen.generate(p[None],
                                GenerationConfig(max_new_tokens=5))
            # greedy decode row r from the packed caches
            caches = [(k[r:r + 1], v[r:r + 1], idx[r:r + 1])
                      for (k, v, idx) in row_caches]
            toks = [int(np.argmax(np.asarray(last[r])))]
            for _ in range(4):
                step, caches = gen._decode(
                    gen.params, jnp.asarray([[toks[-1]]], jnp.int32),
                    caches[0][2], caches)
                toks.append(int(np.argmax(np.asarray(step)[0])))
            got = np.concatenate([p, np.asarray(toks, np.int32)])
            np.testing.assert_array_equal(got, want[0])


class TestPackedEngine:

    def test_packed_admission_matches_generate(self, model_params):
        """Engine with packed admission returns the same greedy outputs
        and actually packs (packed_admissions >= 1)."""
        import threading

        model, params = model_params
        gen = Generator(model, params, CFG, batch_size=1,
                        prompt_buckets=[8, 16])
        engine = ContinuousBatchingEngine(gen, max_batch=3,
                                          packed_admission=True,
                                          packed_bucket=24)
        try:
            want = [gen.generate(p[None],
                                 GenerationConfig(max_new_tokens=6))
                    for p in PROMPTS]
            results = [None] * 3

            def do(i):
                results[i] = engine.submit(
                    PROMPTS[i], GenerationConfig(max_new_tokens=6))

            ts = [threading.Thread(target=do, args=(i,)) for i in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for i in range(3):
                np.testing.assert_array_equal(results[i], want[i][0])
            assert engine.packed_admissions >= 1
        finally:
            engine.shutdown()


class TestEnginePrefix:

    def test_engine_rows_share_the_prefix(self, model_params):
        """Engine with a shared system-prompt prefix: each admission
        prefills only its suffix; outputs equal Generator-with-prefix."""
        import threading

        model, params = model_params
        gen = Generator(model, params, CFG, batch_size=1,
                        prompt_buckets=[16], prefill_chunk=8)
        prefix = np.array([9, 9, 8, 7, 6], np.int32)
        handle = gen.cache_prefix(prefix)
        engine = ContinuousBatchingEngine(gen, max_batch=2,
                                          prompt_bucket=16,
                                          prefix=handle)
        try:
            from alpa_tpu.serve.generation import GenerationConfig
            suffixes = [np.array([1, 2], np.int32),
                        np.array([5, 4, 3], np.int32),
                        np.array([7], np.int32)]
            want = [gen.generate([s], GenerationConfig(max_new_tokens=5),
                                 prefix=handle)[0] for s in suffixes]
            res = [None] * 3

            def do(i):
                res[i] = engine.submit(suffixes[i],
                                       GenerationConfig(max_new_tokens=5))

            ts = [threading.Thread(target=do, args=(i,)) for i in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for i in range(3):
                np.testing.assert_array_equal(res[i], want[i])
        finally:
            engine.shutdown()

    def test_prefix_engine_guards(self):
        from alpa_tpu.model.gpt_model import init_gpt_real
        model, params = init_gpt_real(CFG, 1)
        gen_nochunk = Generator(model, params, CFG, prompt_buckets=[16])
        import pytest as _pytest

        class _H:
            length = 3
            params = None
        with _pytest.raises(ValueError, match="prefill_chunk"):
            ContinuousBatchingEngine(gen_nochunk, prefix=_H())
        # a stale/foreign handle is rejected on the packed path too
        gen_c = Generator(model, params, CFG, prompt_buckets=[16],
                          prefill_chunk=8)
        with _pytest.raises(ValueError, match="different params"):
            ContinuousBatchingEngine(gen_c, prefix=_H(),
                                     packed_admission=True)

    def test_packed_admission_over_shared_prefix(self, model_params):
        """Prefix caching COMPOSES with packed admission (VERDICT r4
        weak #6): queued suffixes are packed into one segment-masked
        prefill written after the shared prefix K/V, every segment
        attending to the prefix plus its own span.  Outputs must equal
        Generator-with-prefix exactly."""
        import threading

        model, params = model_params
        gen = Generator(model, params, CFG, batch_size=1,
                        prompt_buckets=[8], prefill_chunk=8)
        prefix = np.array([9, 9, 8, 7, 6], np.int32)
        handle = gen.cache_prefix(prefix)
        engine = ContinuousBatchingEngine(gen, max_batch=3,
                                          prompt_bucket=8,
                                          packed_admission=True,
                                          packed_bucket=16,
                                          prefix=handle)
        try:
            suffixes = [np.array([1, 2], np.int32),
                        np.array([5, 4, 3], np.int32),
                        np.array([7], np.int32)]
            want = [gen.generate([s], GenerationConfig(max_new_tokens=5),
                                 prefix=handle)[0] for s in suffixes]
            res = [None] * 3

            def do(i):
                res[i] = engine.submit(suffixes[i],
                                       GenerationConfig(max_new_tokens=5))

            ts = [threading.Thread(target=do, args=(i,)) for i in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for i in range(3):
                np.testing.assert_array_equal(res[i], want[i])
            assert engine.packed_admissions >= 1
        finally:
            engine.shutdown()


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
