"""Multi-process fleet + router SSE pass-through (ISSUE 18 satellites
1 and 2).

Tier-1: a RouterServer fronting HTTP ControllerServers streams SSE
frames through (``HTTPReplicaHandle.completions_stream``), with the
router's in-flight guard covering the whole stream; the disaggregated
``/disagg/*`` endpoints work over real HTTP.  Slow: the
``scripts/serve_fleet.py`` recipe boots a 2-process fleet and runs one
streamed request end to end.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
from alpa_tpu.serve.controller import Controller, ControllerServer
from alpa_tpu.serve.generation import Generator
from alpa_tpu.serve.router import (HTTPReplicaHandle, Router,
                                   RouterServer)

PROMPT = [5, 9, 3, 7, 1, 2, 8, 4]
REQ = {"model": "m", "prompt_ids": PROMPT, "max_new_tokens": 4,
       "temperature": 0.0}


@pytest.fixture(scope="module")
def tiny():
    cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                    seq_len=64, vocab_size=64)
    model, params = init_gpt_real(cfg, 1)
    return model, params, cfg


def _controller_server(tiny):
    model, params, cfg = tiny
    gen = Generator(model, params, cfg, prefill_chunk=8)
    c = Controller()
    c.register_model("m", gen)
    server = ControllerServer(c, "127.0.0.1", 0)
    server.start()
    return server


def _sse_tokens(base, req, timeout=60):
    body = json.dumps(dict(req, stream=True)).encode()
    http_req = urllib.request.Request(
        base + "/completions", data=body,
        headers={"Content-Type": "application/json"})
    tokens, final = [], None
    with urllib.request.urlopen(http_req, timeout=timeout) as resp:
        assert resp.headers["Content-Type"] == "text/event-stream"
        for raw in resp:
            raw = raw.strip()
            if not raw.startswith(b"data:"):
                continue
            evt = json.loads(raw[len(b"data:"):])
            if evt.get("done") or "error" in evt:
                final = evt
                break
            tokens.append(evt["token"])
    return tokens, final


@pytest.fixture
def paged(monkeypatch):
    from alpa_tpu.global_env import global_config
    monkeypatch.setattr(global_config, "kv_paged", True)
    monkeypatch.setattr(global_config, "kv_prefix_reuse", True)


class TestRouterSSEPassThrough:
    """Satellite 1: RouterServer /completions?stream=true works against
    HTTP replicas, in-flight guard covering the full stream."""

    def test_stream_through_router_http_replicas(self, tiny, paged):
        backends = [_controller_server(tiny) for _ in range(2)]
        router = Router(disagg_mode="off")
        for i, b in enumerate(backends):
            router.add_replica(
                f"r{i}", HTTPReplicaHandle(f"http://127.0.0.1:{b.port}"))
        server = RouterServer(router, port=0)
        server.start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            # reference: non-streamed through the same router
            ref = router.submit(dict(REQ))["output_ids"][0]
            tokens, final = _sse_tokens(base, REQ)
            assert final == {"done": True}
            assert PROMPT + tokens == ref
            assert sum(st.inflight
                       for st in router._replicas.values()) == 0, \
                "in-flight guard must release at stream end"
        finally:
            server.shutdown()
            for b in backends:
                b.shutdown()

    def test_inflight_guard_covers_open_stream(self, tiny, paged):
        backend = _controller_server(tiny)
        router = Router(disagg_mode="off")
        router.add_replica(
            "r0", HTTPReplicaHandle(f"http://127.0.0.1:{backend.port}"))
        try:
            stream = router.submit_stream(dict(REQ, stream=True))
            st = router._replicas["r0"]
            assert st.inflight == 1
            first = next(stream)
            assert st.inflight == 1, "guard holds while streaming"
            rest = list(stream)
            assert st.inflight == 0, "guard releases on exhaustion"
            assert len([first] + rest) == 4
            # early close also releases the guard
            stream2 = router.submit_stream(dict(REQ, stream=True))
            next(stream2)
            stream2.close()
            assert st.inflight == 0
        finally:
            backend.shutdown()

    def test_disagg_over_http(self, tiny, paged):
        """1 prefill + 1 decode ControllerServer behind the router:
        the handoff crosses real HTTP and stays bit-exact with the
        monolithic answer."""
        mono = _controller_server(tiny)
        pre = _controller_server(tiny)
        dec = _controller_server(tiny)
        router = Router(disagg_mode="auto")
        router.add_replica(
            "p0", HTTPReplicaHandle(f"http://127.0.0.1:{pre.port}"),
            phase="prefill")
        router.add_replica(
            "d0", HTTPReplicaHandle(f"http://127.0.0.1:{dec.port}"),
            phase="decode")
        try:
            ref = mono.controller.completions(dict(REQ))
            out = router.submit(dict(REQ))
            assert out == ref
            assert router.disagg_handoffs == 1
            # retained artifact was acked over HTTP at stream end
            pe = pre.controller._models["m"][0]._prefill_engine
            with pe._cv:
                assert len(pe._retained) == 0
        finally:
            mono.shutdown()
            pre.shutdown()
            dec.shutdown()


@pytest.mark.slow
class TestFleetScript:
    """Satellite 2: the multi-process recipe boots and serves."""

    def test_two_process_fleet_smoke(self):
        script = os.path.join(os.path.dirname(__file__), "..", "..",
                              "scripts", "serve_fleet.py")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(script), "--prefill", "1",
             "--decode", "1", "--disagg-mode", "auto", "--smoke"],
            capture_output=True, text=True, timeout=600, env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "FLEET_READY" in proc.stdout
        assert "SMOKE_OK" in proc.stdout
