"""Serving /metrics and /healthz endpoints (ISSUE 5 satellites).

``GET /metrics`` must return valid Prometheus text exposition carrying
series from every subsystem wired to the registry (compile cache,
overlap dispatch, checkpointing, serving); ``GET /healthz`` follows the
:class:`alpa_tpu.fault.RecoveryManager` state machine — 200 while
HEALTHY/SUSPECT/RECOVERING, 503 once DEGRADED — and falls back to the
controller health report when no recovery manager is attached.
"""
import json
import urllib.error
import urllib.request

import pytest

from alpa_tpu.fault import MeshHealth, RecoveryManager, RetryPolicy
from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
from alpa_tpu.serve import Generator, run_controller

pytestmark = pytest.mark.fault


def _tiny_generator(batch_size=1):
    cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4, seq_len=32,
                    vocab_size=64)
    model, params = init_gpt_real(cfg, batch_size)
    return Generator(model, params, cfg, batch_size)


def _get(base, path):
    """(status, body bytes) — 4xx/5xx don't raise."""
    try:
        with urllib.request.urlopen(base + path) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


class TestMetricsEndpoint:

    def test_metrics_exposition(self):
        server = run_controller(port=0)
        try:
            server.controller.register_model("tiny", _tiny_generator())
            base = f"http://127.0.0.1:{server.port}"
            # drive one request through so serving series carry traffic
            req = urllib.request.Request(
                base + "/completions",
                data=json.dumps({"model": "tiny", "prompt_ids": [1, 2],
                                 "max_new_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                assert r.status == 200

            status, body, headers = _get(base, "/metrics")
            assert status == 200
            assert headers["Content-Type"].startswith("text/plain")
            text = body.decode()

            # basic exposition validity: every non-comment line is
            # "name{labels} value"
            for line in text.strip().splitlines():
                if line.startswith("#"):
                    assert line.startswith(("# HELP ", "# TYPE "))
                    continue
                name_part, _, value = line.rpartition(" ")
                assert name_part and value
                if value != "+Inf":
                    float(value)

            # one series per instrumented subsystem
            assert "alpa_compile_cache_memory_entries" in text
            assert "alpa_overlap_steps_total" in text
            assert "alpa_checkpoint_stat_total" in text
            assert "alpa_serving_requests_total" in text
            assert 'alpa_serving_requests_total{outcome="ok"}' in text
            assert "alpa_serving_batch_size_bucket" in text
            assert "alpa_serving_queue_depth" in text
            assert "alpa_fault_health_state" in text
            assert "alpa_watchdog_last_ok_timestamp" in text
        finally:
            server.shutdown()


class TestHealthzEndpoint:

    def test_healthz_without_recovery_follows_health_report(self):
        server = run_controller(port=0)
        try:
            base = f"http://127.0.0.1:{server.port}"
            status, body, _ = _get(base, "/healthz")
            assert status == 200
            assert json.loads(body)["status"] == "ok"
            server.controller.set_health("shedding", "test")
            status, _, _ = _get(base, "/healthz")
            assert status == 503
        finally:
            server.shutdown()

    def test_healthz_flips_503_when_recovery_degrades(self):
        """THE acceptance wire: the watchdog's recovery manager entering
        DEGRADED (via failing probes) flips /healthz from 200 to 503;
        recovery flips it back."""
        server = run_controller(port=0)
        try:
            base = f"http://127.0.0.1:{server.port}"
            alive = {"ok": True}
            rm = RecoveryManager(
                [object()],
                retry_policy=RetryPolicy(max_attempts=2,
                                         base_delay=0.001, jitter=0.0),
                probe=lambda mesh: alive["ok"])
            server.controller.attach_recovery(rm)

            status, body, _ = _get(base, "/healthz")
            assert status == 200
            assert json.loads(body)["status"] == "healthy"

            alive["ok"] = False
            assert rm.tick() is MeshHealth.DEGRADED
            status, body, _ = _get(base, "/healthz")
            assert status == 503
            assert json.loads(body)["status"] == "degraded"

            alive["ok"] = True
            assert rm.tick() is MeshHealth.HEALTHY
            status, _, _ = _get(base, "/healthz")
            assert status == 200
        finally:
            server.shutdown()

    def test_recovery_state_mirrored_to_registry(self):
        from alpa_tpu.telemetry import metrics as tmetrics
        alive = {"ok": True}
        rm = RecoveryManager(
            [object()],
            retry_policy=RetryPolicy(max_attempts=2, base_delay=0.001,
                                     jitter=0.0),
            probe=lambda mesh: alive["ok"])
        alive["ok"] = False
        assert rm.tick() is MeshHealth.DEGRADED
        reg = tmetrics.get_registry()
        assert reg.get("alpa_fault_health_state").value == 3
        alive["ok"] = True
        assert rm.tick() is MeshHealth.HEALTHY
        assert reg.get("alpa_fault_health_state").value == 0
        snap = reg.snapshot()
        assert snap.get(
            'alpa_fault_state_transitions_total{to="degraded"}', 0) >= 1
