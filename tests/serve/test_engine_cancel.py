"""Requests cancelled while still QUEUED (client gone before admission)
are retired at admission time instead of burning a KV row decoding for
nobody.  submit_stream returns an iterator OBJECT because a plain
generator's close() is a no-op before the first next() — GeneratorExit
never reaches an unstarted body, which made pre-admission cancellation
unreachable (round-5 review catch, verified empirically)."""
import threading
import time

import numpy as np

from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
from alpa_tpu.serve.engine import ContinuousBatchingEngine
from alpa_tpu.serve.generation import GenerationConfig, Generator

CFG = GPTConfig(hidden_size=32, num_layers=2, num_heads=4, seq_len=64,
                vocab_size=64)


def test_queued_cancelled_request_never_admitted():
    model, params = init_gpt_real(CFG, 1)
    gen = Generator(model, params, CFG, batch_size=1, prompt_buckets=[8])
    eng = ContinuousBatchingEngine(gen, max_batch=1, prompt_bucket=8)
    try:
        long_done = []

        def long_req():
            out = eng.submit(np.array([1, 2], np.int32),
                             GenerationConfig(max_new_tokens=40))
            long_done.append(out)

        t = threading.Thread(target=long_req)
        t.start()
        # wait until the long request occupies the single row
        for _ in range(400):
            if eng.admissions >= 1:
                break
            time.sleep(0.05)
        assert eng.admissions == 1

        # queue a second request, then abandon its stream BEFORE it was
        # ever admitted (never call next())
        it = eng.submit_stream(np.array([3, 4], np.int32),
                               GenerationConfig(max_new_tokens=40))
        it.close()
        assert it._item["cancelled"] is True  # close() reaches the item

        # the engine retires the cancelled item at its next admission
        # pass (while the long request still holds the only row)
        for _ in range(400):
            if len(eng._queue) == 0 and it._item["done"].is_set():
                break
            time.sleep(0.05)
        assert it._item["done"].is_set()
        assert len(eng._queue) == 0

        t.join(timeout=180)
        assert long_done and len(long_done[0]) == 42
        # settle, then confirm the cancelled request never took a row
        time.sleep(0.5)
        assert eng.admissions == 1, "cancelled request was admitted"
    finally:
        eng.shutdown()


def test_mid_stream_close_still_frees_the_row():
    """Post-admission close keeps its old semantics: the row frees on
    the next tick instead of decoding to max_new_tokens."""
    model, params = init_gpt_real(CFG, 1)
    gen = Generator(model, params, CFG, batch_size=1, prompt_buckets=[8])
    eng = ContinuousBatchingEngine(gen, max_batch=1, prompt_bucket=8)
    try:
        it = eng.submit_stream(np.array([5, 6], np.int32),
                               GenerationConfig(max_new_tokens=60))
        first = next(it)
        assert isinstance(first, int)
        it.close()
        for _ in range(400):
            if not eng._active.any():
                break
            time.sleep(0.05)
        assert not eng._active.any(), "row not freed after close()"
    finally:
        eng.shutdown()
