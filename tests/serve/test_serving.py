"""Serving tests: generation engine + HTTP controller
(ref tests/serve/test_controller.py)."""
import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alpa_tpu.model.gpt_model import GPTConfig, GPTModel, init_gpt_real
from alpa_tpu.serve import (Controller, GenerationConfig, Generator,
                            get_model, run_controller)


def _tiny_generator(batch_size=1):
    cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4, seq_len=32,
                    vocab_size=64)
    model, params = init_gpt_real(cfg, batch_size)
    return Generator(model, params, cfg, batch_size)


class TestGeneration:

    def test_greedy_matches_no_cache(self):
        """Greedy decode with KV cache == argmax over full re-forward."""
        gen = _tiny_generator()
        prompt = np.array([[1, 2, 3, 4]], np.int32)
        out = gen.generate(prompt,
                           GenerationConfig(max_new_tokens=6))
        assert out.shape == (1, 10)
        # replay without cache
        ids = prompt
        for _ in range(6):
            logits = gen.model.apply(gen.params, jnp.asarray(ids))
            nxt = np.argmax(np.asarray(logits[:, -1]), axis=-1)
            ids = np.concatenate([ids, nxt[:, None].astype(np.int32)],
                                 axis=1)
        np.testing.assert_array_equal(out, ids)

    def test_sampling_reproducible(self):
        gen = _tiny_generator()
        prompt = np.array([[5, 6]], np.int32)
        cfg = GenerationConfig(max_new_tokens=5, do_sample=True,
                               temperature=0.8, top_k=10)
        a = gen.generate(prompt, cfg, rng=jax.random.PRNGKey(7))
        b = gen.generate(prompt, cfg, rng=jax.random.PRNGKey(7))
        np.testing.assert_array_equal(a, b)

    def test_eos_early_stop(self):
        gen = _tiny_generator()
        out = gen.generate(
            np.array([[1]], np.int32),
            GenerationConfig(max_new_tokens=20, eos_token_id=0))
        assert out.shape[1] <= 21


class TestController:

    def test_http_roundtrip(self):
        server = run_controller(port=0)
        try:
            server.controller.register_model("tiny", _tiny_generator())
            base = f"http://127.0.0.1:{server.port}"
            with urllib.request.urlopen(base + "/models") as r:
                assert json.load(r)["models"] == ["tiny"]
            req = urllib.request.Request(
                base + "/completions",
                data=json.dumps({
                    "model": "tiny",
                    "prompt_ids": [1, 2, 3],
                    "max_new_tokens": 4,
                }).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                out = json.load(r)["output_ids"]
            assert len(out) == 1 and len(out[0]) == 7
            # unknown model -> 404 with message
            req2 = urllib.request.Request(
                base + "/completions",
                data=json.dumps({"model": "nope", "prompt_ids": [1]
                                 }).encode())
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req2)
            assert e.value.code == 404
        finally:
            server.shutdown()


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestBeamSearch:

    def test_beam_width_one_equals_greedy(self):
        gen = _tiny_generator()
        prompt = np.array([[1, 2, 3]], np.int32)
        greedy = gen.generate(prompt, GenerationConfig(max_new_tokens=5))
        beam1 = gen.generate_beam(prompt, num_beams=1, max_new_tokens=5)
        np.testing.assert_array_equal(greedy, beam1)

    def test_beam_search_finds_higher_likelihood(self):
        gen = _tiny_generator()
        prompt = np.array([[1, 2]], np.int32)
        greedy = gen.generate(prompt, GenerationConfig(max_new_tokens=6))
        beam = gen.generate_beam(prompt, num_beams=4, max_new_tokens=6)

        def seq_logprob(ids):
            logits = gen.model.apply(gen.params, jnp.asarray(ids))
            logp = jax.nn.log_softmax(
                np.asarray(logits, np.float32), axis=-1)
            total = 0.0
            for t in range(1, ids.shape[1]):
                total += float(logp[0, t - 1, ids[0, t]])
            return total

        # the beam result's sequence log-prob must be >= greedy's
        assert seq_logprob(beam) >= seq_logprob(greedy) - 1e-4
