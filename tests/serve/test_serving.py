"""Serving tests: generation engine + HTTP controller
(ref tests/serve/test_controller.py)."""
import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alpa_tpu.model.gpt_model import GPTConfig, GPTModel, init_gpt_real
from alpa_tpu.serve import (Controller, GenerationConfig, Generator,
                            get_model, run_controller)


def _tiny_generator(batch_size=1):
    cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4, seq_len=32,
                    vocab_size=64)
    model, params = init_gpt_real(cfg, batch_size)
    return Generator(model, params, cfg, batch_size)


class TestGeneration:

    def test_greedy_matches_no_cache(self):
        """Greedy decode with KV cache == argmax over full re-forward."""
        gen = _tiny_generator()
        prompt = np.array([[1, 2, 3, 4]], np.int32)
        out = gen.generate(prompt,
                           GenerationConfig(max_new_tokens=6))
        assert out.shape == (1, 10)
        # replay without cache
        ids = prompt
        for _ in range(6):
            logits = gen.model.apply(gen.params, jnp.asarray(ids))
            nxt = np.argmax(np.asarray(logits[:, -1]), axis=-1)
            ids = np.concatenate([ids, nxt[:, None].astype(np.int32)],
                                 axis=1)
        np.testing.assert_array_equal(out, ids)

    def test_sampling_reproducible(self):
        gen = _tiny_generator()
        prompt = np.array([[5, 6]], np.int32)
        cfg = GenerationConfig(max_new_tokens=5, do_sample=True,
                               temperature=0.8, top_k=10)
        a = gen.generate(prompt, cfg, rng=jax.random.PRNGKey(7))
        b = gen.generate(prompt, cfg, rng=jax.random.PRNGKey(7))
        np.testing.assert_array_equal(a, b)

    def test_eos_early_stop(self):
        gen = _tiny_generator()
        out = gen.generate(
            np.array([[1]], np.int32),
            GenerationConfig(max_new_tokens=20, eos_token_id=0))
        assert out.shape[1] <= 21


class TestShapeBucketing:
    """VERDICT r1 next#7: varied prompt lengths must share executables.
    Done-criterion: 2 compiles total (one prefill bucket + one decode)
    across requests of different prompt lengths."""

    def test_two_compiles_across_prompt_lengths(self):
        gen = _tiny_generator()
        cfg = GenerationConfig(max_new_tokens=4)
        for n in (3, 5, 7, 11):   # all land in one bucket at batch 1
            out = gen.generate(np.arange(1, n + 1, dtype=np.int32)[None],
                               cfg)
            assert out.shape == (1, n + 4)
        assert gen.prefill_traces == 1, gen.prefill_traces
        assert gen.decode_traces == 1, gen.decode_traces

    def test_mixed_lengths_one_batch_matches_separate(self):
        """Per-row KV indices: a mixed-length batch must reproduce each
        prompt's solo greedy decode exactly."""
        gen = _tiny_generator()
        cfg = GenerationConfig(max_new_tokens=5)
        p1 = np.array([1, 2, 3], np.int32)
        p2 = np.array([4, 5, 6, 7, 8, 9, 10], np.int32)
        mixed = gen.generate([p1, p2], cfg)
        solo1 = gen.generate(p1[None], cfg)
        solo2 = gen.generate(p2[None], cfg)
        np.testing.assert_array_equal(mixed[0], solo1[0])
        np.testing.assert_array_equal(mixed[1], solo2[0])


class TestChunkedPrefill:
    """One compiled step serves EVERY prompt length (the long-context
    serving mode; no bucket ladder)."""

    def test_matches_bucketed_prefill(self):
        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=64, vocab_size=64)
        model, params = init_gpt_real(cfg, 1)
        plain = Generator(model, params, cfg, prompt_buckets=[32])
        chunked = Generator(model, params, cfg, prompt_buckets=[32],
                            prefill_chunk=8)
        rng = np.random.RandomState(0)
        for n in (3, 8, 11, 21, 29):
            prompt = rng.randint(0, 64, (1, n)).astype(np.int32)
            g1 = plain.generate(prompt, GenerationConfig(max_new_tokens=5))
            g2 = chunked.generate(prompt,
                                  GenerationConfig(max_new_tokens=5))
            np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
        # the point: five different prompt lengths, ONE chunk compile
        assert chunked.prefill_traces == 1
        # and no bucket ceiling: a prompt past the largest bucket still
        # serves (chunks stream to KV capacity)
        long_p = rng.randint(0, 64, (1, 40)).astype(np.int32)
        out = chunked.generate(long_p, GenerationConfig(max_new_tokens=4))
        assert np.asarray(out).shape == (1, 44)
        assert chunked.prefill_traces == 1

    def test_mixed_length_batch(self):
        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=64, vocab_size=64)
        model, params = init_gpt_real(cfg, 1)
        plain = Generator(model, params, cfg, prompt_buckets=[32])
        chunked = Generator(model, params, cfg, prompt_buckets=[32],
                            prefill_chunk=8)
        prompts = [np.array([1, 2, 3], np.int32),
                   np.array([7, 8, 9, 1, 2, 3, 4, 5, 6, 7, 11],
                            np.int32)]
        g1 = plain.generate(prompts, GenerationConfig(max_new_tokens=4))
        g2 = chunked.generate(prompts, GenerationConfig(max_new_tokens=4))
        for a, b in zip(g1, g2):
            np.testing.assert_array_equal(a, b)

    def test_capacity_guard(self):
        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=16, vocab_size=64)
        model, params = init_gpt_real(cfg, 1)
        chunked = Generator(model, params, cfg, prompt_buckets=[16],
                            prefill_chunk=10)
        # 12 tokens pad to 2 chunks x 10 = 20 > seq_len 16; hard error
        # (survives python -O, where a clamped write would corrupt)
        with pytest.raises(ValueError, match="KV capacity"):
            chunked.generate(np.arange(12, dtype=np.int32)[None],
                             GenerationConfig(max_new_tokens=2))

    def test_prefix_caching_matches_full_prompt(self):
        """System-prompt caching: prefix KV computed once, suffixes ride
        it — generations identical to prefilling prefix+suffix whole."""
        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=64, vocab_size=64)
        model, params = init_gpt_real(cfg, 1)
        gen = Generator(model, params, cfg, prompt_buckets=[48],
                        prefill_chunk=8)
        rng = np.random.RandomState(4)
        prefix = rng.randint(0, 64, (21,)).astype(np.int32)
        handle = gen.cache_prefix(prefix)
        assert handle.length == 21
        for n in (1, 4, 9):
            suffix = rng.randint(0, 64, (1, n)).astype(np.int32)
            want = gen.generate(
                np.concatenate([prefix[None], suffix], axis=1),
                GenerationConfig(max_new_tokens=5))
            got = gen.generate(suffix, GenerationConfig(max_new_tokens=5),
                               prefix=handle)
            # got rows are suffix + generation (caller holds the prefix)
            np.testing.assert_array_equal(
                np.concatenate([prefix[None], np.asarray(got)], axis=1),
                np.asarray(want))
        # EMPTY suffix: generate straight from the cached prompt (the
        # handle carries the prefix's last-token logits)
        want = gen.generate(prefix[None], GenerationConfig(max_new_tokens=5))
        got = gen.generate([np.zeros((0,), np.int32)],
                           GenerationConfig(max_new_tokens=5),
                           prefix=handle)
        np.testing.assert_array_equal(np.concatenate([prefix, got[0]]),
                                      np.asarray(want)[0])
        # mixed-length batch over the same prefix
        sfx = [rng.randint(0, 64, (3,)).astype(np.int32),
               rng.randint(0, 64, (7,)).astype(np.int32)]
        got = gen.generate(sfx, GenerationConfig(max_new_tokens=4),
                           prefix=handle)
        for s, g in zip(sfx, got):
            want = gen.generate(np.concatenate([prefix, s])[None],
                                GenerationConfig(max_new_tokens=4))
            np.testing.assert_array_equal(np.concatenate([prefix, g]),
                                          np.asarray(want)[0])

    def test_prefix_handle_guards(self):
        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=32, vocab_size=64)
        model, params = init_gpt_real(cfg, 1)
        bucketed = Generator(model, params, cfg, prompt_buckets=[16])
        with pytest.raises(ValueError, match="prefill_chunk"):
            bucketed.cache_prefix(np.arange(4, dtype=np.int32))
        chunked = Generator(model, params, cfg, prompt_buckets=[16],
                            prefill_chunk=8)
        handle = chunked.cache_prefix(np.arange(4, dtype=np.int32))
        model2, params2 = init_gpt_real(cfg, 1)
        other = Generator(model2, params2, cfg, prompt_buckets=[16],
                          prefill_chunk=8)
        with pytest.raises(ValueError, match="different"):
            other.generate(np.array([[1]], np.int32),
                           GenerationConfig(max_new_tokens=1),
                           prefix=handle)

    def test_beam_search_uses_chunked_prefill(self):
        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=64, vocab_size=64)
        model, params = init_gpt_real(cfg, 1)
        plain = Generator(model, params, cfg, prompt_buckets=[32])
        chunked = Generator(model, params, cfg, prompt_buckets=[32],
                            prefill_chunk=8)
        rng = np.random.RandomState(3)
        for n in (5, 13):
            p = rng.randint(0, 64, (1, n)).astype(np.int32)
            b1 = plain.generate_beam(p, num_beams=3, max_new_tokens=5)
            b2 = chunked.generate_beam(p, num_beams=3, max_new_tokens=5)
            np.testing.assert_array_equal(np.asarray(b1), np.asarray(b2))
        # both beam prompts rode the single chunk compile
        assert chunked.prefill_traces == 1


class TestRequestBatching:

    def test_concurrent_requests_share_batches(self):
        """Concurrent completions coalesce instead of serializing
        (iteration-level batching; ref wrapper_1d intent)."""
        import threading

        from alpa_tpu.serve.controller import Controller

        controller = Controller()
        gen = _tiny_generator()
        controller.register_model("tiny", gen)
        replica = controller._models["tiny"][0]

        results = {}

        def call(i, n):
            out = controller.completions({
                "model": "tiny",
                "prompt_ids": list(range(1, n + 1)),
                "max_new_tokens": 4,
            })
            results[i] = out["output_ids"]

        threads = [threading.Thread(target=call, args=(i, 3 + i))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 6
        for i in range(6):
            assert len(results[i][0]) == (3 + i) + 4
        # fewer device batches than requests = they coalesced
        assert replica.batcher.batches_run < 6
        # each result must equal its solo generation
        solo = gen.generate(
            np.arange(1, 4, dtype=np.int32)[None],
            GenerationConfig(max_new_tokens=4))
        np.testing.assert_array_equal(np.asarray(results[0][0]), solo[0])


class TestRequestBatchingOversized:

    def test_oversized_request_not_starved(self):
        """A request with more prompts than max_batch runs alone instead
        of hanging forever."""
        from alpa_tpu.serve.controller import Controller

        controller = Controller()
        controller.register_model("tiny", _tiny_generator())
        out = controller.completions({
            "model": "tiny",
            "prompt_ids": [[1, 2, 3]] * 10,   # > max_batch (8)
            "max_new_tokens": 3,
        })
        assert len(out["output_ids"]) == 10
        assert all(len(row) == 6 for row in out["output_ids"])


class TestContinuousBatching:
    """Row-level continuous batching (ref wrapper_1d.py): a persistent
    decode loop refills finished rows immediately; every request matches
    its solo greedy decode, and the engine's executables compile once."""

    def test_three_requests_two_rows(self):
        import threading

        from alpa_tpu.serve.engine import ContinuousBatchingEngine

        gen = _tiny_generator()
        engine = ContinuousBatchingEngine(gen, max_batch=2)
        cfg = GenerationConfig(max_new_tokens=6)
        prompts = [np.array([1, 2, 3], np.int32),
                   np.array([4, 5], np.int32),
                   np.array([7, 8, 9, 10], np.int32)]
        results = {}

        def call(i):
            results[i] = engine.submit(prompts[i], cfg)

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        engine.shutdown()

        assert engine.admissions == 3
        for i, p in enumerate(prompts):
            solo = gen.generate(p[None], cfg)
            np.testing.assert_array_equal(results[i], solo[0])
        # the engine's decode loop compiled once (fixed B x 1 shape) and
        # single-row prefill once (fixed 1 x bucket shape)
        assert gen.decode_traces <= 2   # engine batch + solo replay batch
        assert gen.prefill_traces <= 2


class TestController:

    def test_http_roundtrip(self):
        server = run_controller(port=0)
        try:
            server.controller.register_model("tiny", _tiny_generator())
            base = f"http://127.0.0.1:{server.port}"
            with urllib.request.urlopen(base + "/models") as r:
                assert json.load(r)["models"] == ["tiny"]
            req = urllib.request.Request(
                base + "/completions",
                data=json.dumps({
                    "model": "tiny",
                    "prompt_ids": [1, 2, 3],
                    "max_new_tokens": 4,
                }).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                out = json.load(r)["output_ids"]
            assert len(out) == 1 and len(out[0]) == 7
            # unknown model -> 404 with message
            req2 = urllib.request.Request(
                base + "/completions",
                data=json.dumps({"model": "nope", "prompt_ids": [1]
                                 }).encode())
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req2)
            assert e.value.code == 404
        finally:
            server.shutdown()

    def test_http_streaming_with_registered_prefix(self):
        """A model registered with a system prompt serves streamed
        suffixes whose outputs equal whole-prompt greedy decoding."""
        server = run_controller(port=0)
        try:
            cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                            seq_len=64, vocab_size=64)
            model, params = init_gpt_real(cfg, 1)
            gen = Generator(model, params, cfg, prompt_buckets=[32],
                            prefill_chunk=8)
            system = np.random.RandomState(7).randint(0, 64, (11,)) \
                .astype(np.int32)
            server.controller.register_model("sys", gen,
                                             prefix_ids=system)
            want = gen.generate(
                np.concatenate([system, [5, 6, 7]])[None],
                GenerationConfig(max_new_tokens=5))
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/completions",
                data=json.dumps({"model": "sys", "prompt_ids": [5, 6, 7],
                                 "max_new_tokens": 5,
                                 "stream": True}).encode())
            toks = []
            with urllib.request.urlopen(req) as r:
                for raw in r:
                    line = raw.decode().strip()
                    if line.startswith("data: "):
                        ev = json.loads(line[6:])
                        if "token" in ev:
                            toks.append(ev["token"])
            np.testing.assert_array_equal(
                np.concatenate([system, [5, 6, 7], toks]),
                np.asarray(want)[0])
            # the NON-streaming path applies the same prefix semantics
            req2 = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/completions",
                data=json.dumps({"model": "sys", "prompt_ids": [5, 6, 7],
                                 "max_new_tokens": 5}).encode())
            with urllib.request.urlopen(req2) as r:
                out = json.load(r)["output_ids"][0]
            np.testing.assert_array_equal(
                np.concatenate([system, out]), np.asarray(want)[0])
            # replicas must share one prefix
            with pytest.raises(ValueError, match="share one prefix"):
                server.controller.register_model("sys", gen)
        finally:
            server.shutdown()

    def test_http_streaming(self):
        """SSE streaming: tokens arrive as individual events and the
        assembled row equals the non-streaming greedy result."""
        server = run_controller(port=0)
        try:
            gen = _tiny_generator()
            server.controller.register_model("tiny", gen)
            base = f"http://127.0.0.1:{server.port}"
            body = {"model": "tiny", "prompt_ids": [1, 2, 3],
                    "max_new_tokens": 5}
            want = gen.generate(np.array([[1, 2, 3]], np.int32),
                                GenerationConfig(max_new_tokens=5))
            req = urllib.request.Request(
                base + "/completions",
                data=json.dumps(dict(body, stream=True)).encode(),
                headers={"Content-Type": "application/json"})
            events = []
            with urllib.request.urlopen(req) as r:
                assert r.headers["Content-Type"] == "text/event-stream"
                for raw in r:
                    line = raw.decode().strip()
                    if line.startswith("data: "):
                        events.append(json.loads(line[6:]))
            toks = [e["token"] for e in events if "token" in e]
            assert events[-1].get("done") is True
            assert len(toks) == 5
            np.testing.assert_array_equal(
                np.concatenate([[1, 2, 3], toks]), want[0])
        finally:
            server.shutdown()


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestSpeculativeDecoding:
    """Greedy speculative decoding is EXACT: same tokens as plain greedy
    on the target, fewer target forwards."""

    def _models(self):
        cfg_t = GPTConfig(hidden_size=48, num_layers=3, num_heads=4,
                          seq_len=64, vocab_size=64)
        model_t, params_t = init_gpt_real(cfg_t, 1)
        target = Generator(model_t, params_t, cfg_t, prompt_buckets=[16])
        cfg_d = GPTConfig(hidden_size=16, num_layers=1, num_heads=2,
                          seq_len=64, vocab_size=64)
        model_d, params_d = init_gpt_real(cfg_d, 1)
        draft = Generator(model_d, params_d, cfg_d, prompt_buckets=[16])
        return target, draft

    def test_exactly_matches_plain_greedy(self):
        target, draft = self._models()
        prompt = np.random.RandomState(5).randint(0, 64, (9,)) \
            .astype(np.int32)
        want = target.generate(prompt[None],
                               GenerationConfig(max_new_tokens=12))
        got, stats = target.generate_speculative(
            draft, prompt, GenerationConfig(max_new_tokens=12),
            num_draft=3)
        np.testing.assert_array_equal(got, np.asarray(want)[0])
        assert stats["rounds"] >= 1
        assert 0 <= stats["accepted"] <= stats["proposed"]

    def test_self_draft_accepts_everything(self):
        """Draft == target: every proposal must be accepted (the
        verification logic agrees with itself)."""
        target, _ = self._models()
        prompt = np.array([3, 1, 4, 1, 5], np.int32)
        got, stats = target.generate_speculative(
            target, prompt, GenerationConfig(max_new_tokens=10),
            num_draft=4)
        want = target.generate(prompt[None],
                               GenerationConfig(max_new_tokens=10))
        np.testing.assert_array_equal(got, np.asarray(want)[0])
        assert stats["accepted"] == stats["proposed"]

    def test_exact_up_to_kv_capacity(self):
        """Near the cache edge the round shrinks (and falls back to
        single decodes) instead of silently under-generating."""
        cfg_t = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                          seq_len=32, vocab_size=64)
        model_t, params_t = init_gpt_real(cfg_t, 1)
        target = Generator(model_t, params_t, cfg_t, prompt_buckets=[32])
        prompt = np.random.RandomState(6).randint(0, 64, (18,)) \
            .astype(np.int32)
        # 18 + 14 == seq_len exactly; num_draft=5 must shrink at the edge
        want = target.generate(prompt[None],
                               GenerationConfig(max_new_tokens=14))
        got, stats = target.generate_speculative(
            target, prompt, GenerationConfig(max_new_tokens=14),
            num_draft=5)
        np.testing.assert_array_equal(got, np.asarray(want)[0])
        assert len(got) == 32  # full budget emitted

    def test_undersized_draft_rejected(self):
        target, _ = self._models()
        cfg_d = GPTConfig(hidden_size=16, num_layers=1, num_heads=2,
                          seq_len=8, vocab_size=64)
        model_d, params_d = init_gpt_real(cfg_d, 1)
        draft = Generator(model_d, params_d, cfg_d, prompt_buckets=[8])
        with pytest.raises(ValueError, match="draft seq_len"):
            target.generate_speculative(
                draft, np.arange(6, dtype=np.int32),
                GenerationConfig(max_new_tokens=8), num_draft=2)

    def test_eos_stops_early(self):
        target, draft = self._models()
        prompt = np.array([1, 2], np.int32)
        plain = target.generate(prompt[None],
                                GenerationConfig(max_new_tokens=10))
        eos = int(np.asarray(plain)[0, 4])  # force an early stop
        want = target.generate(prompt[None], GenerationConfig(
            max_new_tokens=10, eos_token_id=eos))
        got, _ = target.generate_speculative(
            draft, prompt, GenerationConfig(max_new_tokens=10,
                                            eos_token_id=eos),
            num_draft=3)
        np.testing.assert_array_equal(got, np.asarray(want)[0])


class TestBeamSearch:

    def test_beam_width_one_equals_greedy(self):
        gen = _tiny_generator()
        prompt = np.array([[1, 2, 3]], np.int32)
        greedy = gen.generate(prompt, GenerationConfig(max_new_tokens=5))
        beam1 = gen.generate_beam(prompt, num_beams=1, max_new_tokens=5)
        np.testing.assert_array_equal(greedy, beam1)

    def test_beam_search_finds_higher_likelihood(self):
        gen = _tiny_generator()
        prompt = np.array([[1, 2]], np.int32)
        greedy = gen.generate(prompt, GenerationConfig(max_new_tokens=6))
        beam = gen.generate_beam(prompt, num_beams=4, max_new_tokens=6)

        def seq_logprob(ids):
            logits = gen.model.apply(gen.params, jnp.asarray(ids))
            logp = jax.nn.log_softmax(
                np.asarray(logits, np.float32), axis=-1)
            total = 0.0
            for t in range(1, ids.shape[1]):
                total += float(logp[0, t - 1, ids[0, t]])
            return total

        # the beam result's sequence log-prob must be >= greedy's
        assert seq_logprob(beam) >= seq_logprob(greedy) - 1e-4


class TestHFWrapper:
    """HF-GenerationMixin-shaped front (ref wrapper.py:501)."""

    def test_generate_hf_interface(self):
        from alpa_tpu.serve import WrappedInferenceModel
        gen = _tiny_generator()
        m = WrappedInferenceModel(gen)
        ids = np.array([[1, 2, 3, 4]])
        out = m.generate(input_ids=ids, max_new_tokens=5)
        assert out.shape == (1, 9)
        assert (out[:, :4] == ids).all()
        # max_length alias
        out2 = m.generate(input_ids=ids, max_length=9)
        np.testing.assert_array_equal(out, out2)
        # beam path
        beam = m.generate(input_ids=ids, num_beams=2, max_new_tokens=5)
        assert beam.shape == (1, 9)
        # beam + attention_mask: trailing pads are trimmed, so the result
        # matches beaming the unpadded prompt
        padded = np.array([[1, 2, 3, 4, 0, 0]])
        mask = np.array([[1, 1, 1, 1, 0, 0]])
        beam2 = m.generate(input_ids=padded, attention_mask=mask,
                           num_beams=2, max_new_tokens=5)
        np.testing.assert_array_equal(beam, beam2)
        # forward returns logits
        logits = m(ids)
        assert logits.shape == (1, 4, gen.config.vocab_size)

    def test_generate_attention_mask_lengths(self):
        from alpa_tpu.serve import WrappedInferenceModel
        gen = _tiny_generator()
        m = WrappedInferenceModel(gen)
        ids = np.array([[5, 6, 7, 0], [8, 9, 0, 0]])
        mask = np.array([[1, 1, 1, 0], [1, 1, 0, 0]])
        out = m.generate(input_ids=ids, attention_mask=mask,
                         max_new_tokens=3, pad_token_id=0)
        assert out.shape[0] == 2
        # row 0 continues after its 3 real tokens, row 1 after 2
        assert (out[0, :3] == [5, 6, 7]).all()
        assert (out[1, :2] == [8, 9]).all()
        # separate single generations match the batched masked ones
        solo0 = m.generate(input_ids=np.array([[5, 6, 7]]),
                           max_new_tokens=3)
        np.testing.assert_array_equal(out[0, :6], solo0[0])

    def test_hf_checkpoint_loading(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        from transformers import GPT2Config, GPT2LMHeadModel

        from alpa_tpu.serve import get_hf_model
        hf_config = GPT2Config(vocab_size=128, n_positions=32, n_embd=48,
                               n_layer=2, n_head=4, attn_pdrop=0.0,
                               resid_pdrop=0.0, embd_pdrop=0.0)
        hf_model = GPT2LMHeadModel(hf_config).eval()
        m = get_hf_model(hf_model)
        ids = np.random.RandomState(0).randint(0, 128, (1, 8))
        out = m.generate(input_ids=torch.tensor(ids), max_new_tokens=4)
        assert out.shape == (1, 12)
        # greedy continuation matches HF's own generate
        want = hf_model.generate(torch.tensor(ids), max_new_tokens=4,
                                 do_sample=False).numpy()
        np.testing.assert_array_equal(out, want)


class TestPipelinedGeneration:
    """Pipeshard inference executables behind the Generator (ref
    get_pipeshard_executable, opt_model.py:770): KV caches live on their
    stage meshes between steps."""

    def test_pipelined_greedy_matches_plain(self):
        import alpa_tpu
        from alpa_tpu import PipeshardParallel
        from alpa_tpu.model.gpt_model import init_gpt_real
        from alpa_tpu.pipeline_parallel.layer_construction import (
            ManualLayerOption)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            UniformStageOption)

        alpa_tpu.init(cluster="local")
        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=32, vocab_size=64,
                        pipeline_boundary_every=1)
        model, params = init_gpt_real(cfg, 1)
        plain = Generator(model, params, cfg)
        piped = Generator(
            model, params, cfg,
            parallel_method=PipeshardParallel(
                num_micro_batches=1, layer_option=ManualLayerOption(),
                stage_option=UniformStageOption(num_stages=2),
                pipeline_schedule="inference"))
        ids = np.random.RandomState(0).randint(0, 64, (1, 8))
        g1 = plain.generate(ids, GenerationConfig(max_new_tokens=8))
        g2 = piped.generate(ids, GenerationConfig(max_new_tokens=8))
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
        # cache-resident decoding: repeat generations hit the compiled
        # executables (trace counts stay flat; the pipeshard front-end
        # may trace twice for ONE compile)
        p_traces, d_traces = piped.prefill_traces, piped.decode_traces
        g3 = piped.generate(ids, GenerationConfig(max_new_tokens=8))
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g3))
        assert piped.prefill_traces == p_traces
        assert piped.decode_traces == d_traces

    def test_pipelined_bloom_matches_plain(self):
        """A second family through the pipelined-inference path: the
        cache-as-invars contract composes with stage-resident KV caches
        for ALiBi models too, not just GPT."""
        import alpa_tpu
        from alpa_tpu import PipeshardParallel
        from alpa_tpu.model.bloom_model import BloomConfig, BloomModel
        from alpa_tpu.pipeline_parallel.layer_construction import (
            ManualLayerOption)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            UniformStageOption)

        alpa_tpu.init(cluster="local")
        cfg = BloomConfig(hidden_size=32, num_layers=2, num_heads=4,
                          seq_len=32, vocab_size=64,
                          pipeline_boundary_every=1)
        model = BloomModel(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.ones((1, 8), jnp.int32))
        plain = Generator(model, params, cfg)
        piped = Generator(
            model, params, cfg,
            parallel_method=PipeshardParallel(
                num_micro_batches=1, layer_option=ManualLayerOption(),
                stage_option=UniformStageOption(num_stages=2),
                pipeline_schedule="inference"))
        ids = np.random.RandomState(1).randint(0, 64, (1, 8))
        g1 = plain.generate(ids, GenerationConfig(max_new_tokens=6))
        g2 = piped.generate(ids, GenerationConfig(max_new_tokens=6))
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
