"""Sampled speculative decoding is exact IN DISTRIBUTION (VERDICT r4
next #6): rejection-sampling acceptance (Leviathan et al.) makes every
emitted token target-distributed regardless of the draft.

Two layers of proof:
  1. the acceptance math itself (``speculative_accept``) — the marginal
     of the first emitted token over many synthetic rounds equals the
     target row p_0 exactly (TV distance -> 0), for adversarial q;
  2. end-to-end on a tiny model — the empirical joint of the first two
     sampled tokens from ``generate_speculative(do_sample=True)``
     matches the exact joint computed from the target's own warped
     logits (the same check a plain-sampling run would pass).
"""
import numpy as np
import pytest

from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
from alpa_tpu.serve.generation import (GenerationConfig, Generator,
                                       _sample_from_probs, _warp_probs_np,
                                       speculative_accept)


def _tv(p, q):
    return 0.5 * float(np.abs(np.asarray(p) - np.asarray(q)).sum())


class TestAcceptanceMath:

    @pytest.mark.parametrize("case", ["random", "disjointish", "equal"])
    def test_first_token_marginal_is_exactly_target(self, case):
        """Simulate many speculative rounds against fixed q/p tensors;
        the first emitted token's empirical distribution must converge
        to p_0 — the defining property of speculative sampling."""
        rng = np.random.RandomState(0)
        V, k, N = 8, 3, 200_000
        q = rng.dirichlet(np.ones(V), size=k)
        p = rng.dirichlet(np.ones(V), size=k + 1)
        if case == "disjointish":
            # draft mass concentrated where the target is thin
            q = rng.dirichlet(np.full(V, 0.2), size=k)
        elif case == "equal":
            p[:k] = q
        counts = np.zeros(V)
        for _ in range(N):
            props = [_sample_from_probs(q[i], rng.uniform())
                     for i in range(k)]
            a, extra = speculative_accept(props, q, p, rng.uniform(size=k),
                                          rng.uniform())
            first = props[0] if a >= 1 else extra
            counts[first] += 1
        assert _tv(counts / N, p[0]) < 0.01, (case, counts / N, p[0])

    def test_equal_distributions_accept_everything(self):
        rng = np.random.RandomState(1)
        V, k = 16, 4
        q = rng.dirichlet(np.ones(V), size=k)
        p = np.concatenate([q, rng.dirichlet(np.ones(V), size=1)])
        for _ in range(500):
            props = [_sample_from_probs(q[i], rng.uniform())
                     for i in range(k)]
            a, _extra = speculative_accept(props, q, p,
                                           rng.uniform(size=k),
                                           rng.uniform())
            assert a == k

    def test_warp_matches_sample_logits_support(self):
        """_warp_probs_np's top-k semantics match _sample_logits: mass
        only on the top-k (ties at the k-th value included)."""
        logits = np.array([1.0, 3.0, 3.0, 0.0, 2.0])
        p = _warp_probs_np(logits, GenerationConfig(do_sample=True,
                                                    top_k=2))
        assert p[3] == 0.0 and p[0] == 0.0
        assert p[1] > 0 and p[2] > 0 and p[4] == 0.0
        np.testing.assert_allclose(p.sum(), 1.0)

    def test_top_k_mask_constant_unified(self):
        """The device sampler and the host warper share ONE mask
        constant, and it is -inf: a finite sentinel (the old -1e9)
        leaves masked tokens with tiny-but-nonzero device probability
        while the host assigns exactly zero — speculative acceptance
        p/q is only exact when both agree on the support."""
        import jax
        import jax.numpy as jnp

        from alpa_tpu.serve import generation

        assert generation.TOP_K_MASK == float("-inf")
        logits = np.array([1.0, 3.0, 3.0, 0.0, 2.0], np.float32)
        cfg = GenerationConfig(do_sample=True, top_k=2)
        # device-path probabilities under exactly _sample_logits' warp
        x = jnp.asarray(logits)
        kth = jax.lax.top_k(x, cfg.top_k)[0][..., -1:]
        dev_p = np.asarray(jax.nn.softmax(
            jnp.where(x < kth, generation.TOP_K_MASK, x)), np.float64)
        host_p = _warp_probs_np(logits, cfg)
        # identical support: zero exactly where the other is zero
        np.testing.assert_array_equal(dev_p == 0.0, host_p == 0.0)
        np.testing.assert_allclose(dev_p, host_p, atol=1e-6)


class TestEndToEndSampled:

    def test_sampled_joint_matches_target_chain(self):
        """Empirical (t0, t1) joint over many seeded speculative runs ==
        the exact joint from the target's warped logits."""
        import jax.numpy as jnp

        cfg_t = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                          seq_len=32, vocab_size=32)
        model_t, params_t = init_gpt_real(cfg_t, 1)
        target = Generator(model_t, params_t, cfg_t, prompt_buckets=[8])
        cfg_d = GPTConfig(hidden_size=16, num_layers=1, num_heads=2,
                          seq_len=32, vocab_size=32)
        model_d, params_d = init_gpt_real(cfg_d, 1)
        draft = Generator(model_d, params_d, cfg_d, prompt_buckets=[8])

        prompt = np.array([5, 3, 1], np.int32)
        gcfg = GenerationConfig(max_new_tokens=2, do_sample=True,
                                temperature=1.5, top_k=3)

        # exact joint from the target itself: p(t0) from the prefill
        # logits; p(t1 | t0) from one cached decode per t0 in support
        logits0, caches0 = target._spec_prefill(target, prompt)
        p0 = _warp_probs_np(np.asarray(logits0)[0], gcfg)
        support0 = np.nonzero(p0)[0]
        joint = {}
        for t0 in support0:
            l1, _ = target._decode(
                target.params, jnp.asarray([[int(t0)]], jnp.int32),
                caches0[0][2], caches0)
            p1 = _warp_probs_np(np.asarray(l1)[0], gcfg)
            for t1 in np.nonzero(p1)[0]:
                joint[(int(t0), int(t1))] = float(p0[t0] * p1[t1])

        N = 1500
        counts = {}
        for seed in range(N):
            out, _stats = target.generate_speculative(
                draft, prompt, gcfg, num_draft=2, seed=seed)
            t0, t1 = int(out[len(prompt)]), int(out[len(prompt) + 1])
            counts[(t0, t1)] = counts.get((t0, t1), 0) + 1

        assert set(counts) <= set(joint), (
            "sampled a pair outside the target's warped support",
            sorted(set(counts) - set(joint)))
        keys = sorted(joint)
        emp = np.array([counts.get(kk, 0) / N for kk in keys])
        exact = np.array([joint[kk] for kk in keys])
        # TV tolerance ~3 sigma for N=1500 over <=9 support pairs
        assert _tv(emp, exact) < 0.06, (dict(zip(keys, emp)), joint)

    def test_greedy_zero_temperature_limit_unchanged(self):
        """do_sample with the greedy path still matches plain greedy
        (regression guard: the sampled path must not perturb greedy)."""
        cfg_t = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                          seq_len=32, vocab_size=32)
        model_t, params_t = init_gpt_real(cfg_t, 1)
        target = Generator(model_t, params_t, cfg_t, prompt_buckets=[8])
        prompt = np.array([7, 2, 4], np.int32)
        want = target.generate(prompt[None],
                               GenerationConfig(max_new_tokens=8))
        got, _ = target.generate_speculative(
            target, prompt, GenerationConfig(max_new_tokens=8),
            num_draft=3)
        np.testing.assert_array_equal(got, np.asarray(want)[0])


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
